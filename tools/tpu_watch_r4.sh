#!/usr/bin/env bash
# Round-4 tunnel watcher. On recovery, in priority order (tunnel windows
# can be short — the committed primary artifact comes before diagnostics):
#   1. layout probe        (fast; validates the plane-major design on-chip)
#   2. bench.py            (the primary metric, count-checked)
#   3. superstep profile   (per-stage accounting + dedup/lowering A/B)
# then COMMITS the artifacts (the session may have ended by then; a
# measurement that is not in git did not happen). Unlike the r3b watcher,
# this one stages ONLY the files it produced — an unattended `git add -A`
# would sweep unrelated in-progress working-tree changes into the
# automated commit (ADVICE.md round-3 item 3).
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch_r4.log
ARTIFACTS=(tpu_layout_probe.log bench_r4_out.json bench_detail.json \
           bench_probe.log tpu_profile.log "$LOG")
log() { echo "[watch $(date +%H:%M:%S)] $*" >>"$LOG"; }
log "watcher started (pid $$)"
while true; do
  if timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; then
    log "TUNNEL UP — layout probe"
    timeout 1200 python tools/layout_probe.py >tpu_layout_probe.log 2>&1
    rc1=$?
    log "layout_probe rc=$rc1"
    log "bench.py (primary)"
    timeout 3000 python bench.py >bench_r4_out.json 2>>"$LOG"
    rc2=$?
    log "bench rc=$rc2: $(tail -c 300 bench_r4_out.json 2>/dev/null)"
    log "superstep profile"
    timeout 2700 python tools/profile_superstep.py 8 >tpu_profile.log 2>&1
    rc3=$?
    log "profile_superstep rc=$rc3"
    # -f: bench_detail.json / bench_probe.log are gitignored working files,
    # but a TPU window's capture of them is an artifact worth committing.
    git add -f -- "${ARTIFACTS[@]}" >>"$LOG" 2>&1
    git commit -q -m "TPU window artifacts: layout probe (rc=$rc1), bench (rc=$rc2), superstep profile + A/B (rc=$rc3)" >>"$LOG" 2>&1
    log "artifacts committed"
    if [ "$rc1" -eq 0 ] && [ "$rc2" -eq 0 ] && [ "$rc3" -eq 0 ]; then
      log "all stages done; watcher exiting"
      exit 0
    fi
    log "a stage failed; resuming watch"
  else
    log "tunnel down"
  fi
  sleep 240
done
