"""Per-stage on-chip profile of the sorted-dedup superstep at real shapes.

The committed cost model (BASELINE.md) was measured against the round-2
hash structure; after the sort-merge visited set landed the bottleneck
moved and the stage accounting must be re-measured on hardware.  This
tool times, as separate jits at the rm=8 primary-bench shapes:

  expand     vmap(packed_step) over the frontier bucket
  fingerprint  two-lane murmur over the candidate buffer
  compact    gather-based stream compaction of the F*A grid
  insert     sortedset.insert (the 5-plane 3-key sort + route-back)
  frontier   gather compaction of survivors into the next frontier
  superstep  the engine's real fused-per-level program (sum of the above)
  level-loop the fused 32-level dispatch, from the real checker

plus the same full-coverage measured pass bench.py runs, with per-level
wall time from one-level dispatches.

Usage: python tools/profile_superstep.py [rm] [--cpu]
Run under `timeout` — the tunnel wedges rather than failing.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, *args, n=5):
    import jax

    jax.block_until_ready(fn(*args))  # compile / warm
    t0 = time.monotonic()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / n


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
    from stateright_tpu.ops import fphash, sortedset

    rm = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    print(f"backend={jax.default_backend()} rm={rm}", flush=True)

    model = PackedTwoPhaseSys(rm)
    W, A = model.state_words, model.max_actions

    # Real rm=8 shapes: the big levels run at the 2^18/2^19 buckets with a
    # 2^22-capacity sorted table.
    f_cap = 1 << 18
    table_cap = 1 << 22
    cand_cap = max(1024, 1 << (f_cap * A // 4 - 1).bit_length())
    cand_cap = min(cand_cap, 1 << (f_cap * A - 1).bit_length())
    print(f"W={W} A={A} f_cap=2^{f_cap.bit_length()-1} cand_cap=2^{cand_cap.bit_length()-1}", flush=True)

    rng = np.random.default_rng(0)
    frontier = jnp.asarray(rng.integers(0, 2**32, (f_cap, W), dtype=np.uint32))
    mask_grid = jnp.asarray(rng.integers(0, 4, f_cap * A, dtype=np.uint32) == 0)

    # --- expand ---------------------------------------------------------
    expand = jax.jit(lambda f: jax.vmap(model.packed_step)(f))
    dt = timeit(lambda: expand(frontier))
    print(f"expand       [2^{f_cap.bit_length()-1} x A]: {dt*1e3:8.1f} ms ({f_cap*A/dt/1e6:8.1f} M cand/s)", flush=True)

    # --- fingerprint ----------------------------------------------------
    cand_rows = jnp.asarray(rng.integers(0, 2**32, (cand_cap, W), dtype=np.uint32))
    fp = jax.jit(lambda r: fphash.fingerprint_words(r, jnp))
    dt = timeit(lambda: fp(cand_rows))
    print(f"fingerprint  [2^{cand_cap.bit_length()-1}]: {dt*1e3:8.1f} ms ({cand_cap/dt/1e6:8.1f} M fp/s)", flush=True)

    # --- candidate compaction (grid -> cand buffer; planes form) --------
    gplanes = jnp.asarray(rng.integers(0, 2**32, (W, f_cap * A), dtype=np.uint32))
    par = jnp.asarray(rng.integers(0, 2**32, f_cap * A, dtype=np.uint32))

    def compact_gather(mask, gp, par):
        order = jnp.argsort(~mask, stable=True)[:cand_cap]
        sm = mask[order]
        rows = jnp.where(sm[None, :], gp[:, order], 0)
        p = jnp.where(sm, par[order], 0)
        return rows, p, jnp.sum(mask, dtype=jnp.int32)

    compact_j = jax.jit(compact_gather)
    dt = timeit(compact_j, mask_grid, gplanes, par, n=3)
    print(f"compact grid [2^{(f_cap*A-1).bit_length()}]: {dt*1e3:8.1f} ms", flush=True)

    # --- sortedset insert at load --------------------------------------
    n_occ = (table_cap * 3) // 8
    keys = rng.integers(1, 2**63, table_cap, dtype=np.uint64)
    keys[n_occ:] = 0
    keys[:n_occ] = np.sort(keys[:n_occ])
    ss = sortedset.SortedSet(
        jnp.asarray((keys >> 32).astype(np.uint32)),
        jnp.asarray((keys & 0xFFFFFFFF).astype(np.uint32)),
        jnp.asarray((keys >> 32).astype(np.uint32)),
        jnp.asarray((keys & 0xFFFFFFFF).astype(np.uint32)),
        jnp.asarray(n_occ, jnp.int32),
    )
    chi = jnp.asarray(rng.integers(1, 2**32, cand_cap, dtype=np.uint32))
    clo = jnp.asarray(rng.integers(1, 2**32, cand_cap, dtype=np.uint32))
    act = jnp.asarray(rng.integers(0, 2, cand_cap, dtype=np.uint32).astype(bool))
    ins = jax.jit(sortedset.insert)
    dt = timeit(lambda: ins(ss, chi, clo, chi, clo, act))
    print(f"sorted insert[tab 2^{table_cap.bit_length()-1} + 2^{cand_cap.bit_length()-1}]: {dt*1e3:8.1f} ms", flush=True)

    # breakdown: the insert's component sorts at its [cap + m] shape
    kh = jnp.concatenate([ss.key_hi, chi])
    kl = jnp.concatenate([ss.key_lo, clo])
    tick = jnp.arange(table_cap + cand_cap, dtype=jnp.int32)
    sort3 = jax.jit(lambda a, b, t: jax.lax.sort((a, b, t), num_keys=3))
    dt = timeit(sort3, kh, kl, tick, n=3)
    print(f"  3-op 3-key sort [2^{(table_cap+cand_cap-1).bit_length()}]: {dt*1e3:8.1f} ms", flush=True)
    sort5 = jax.jit(lambda a, b, t, c, d: jax.lax.sort((a, b, t, c, d), num_keys=3))
    dt = timeit(sort5, kh, kl, tick, kh, kl, n=3)
    print(f"  5-op 3-key sort [2^{(table_cap+cand_cap-1).bit_length()}]: {dt*1e3:8.1f} ms", flush=True)
    keep = jnp.asarray(rng.integers(0, 2, table_cap + cand_cap, dtype=np.uint32).astype(bool))
    argc = jax.jit(lambda k: jnp.argsort(~k, stable=True)[:table_cap])
    dt = timeit(argc, keep, n=3)
    print(f"  argsort compaction [2^{(table_cap+cand_cap-1).bit_length()}]: {dt*1e3:8.1f} ms", flush=True)

    # --- the engine's real superstep at this bucket ---------------------
    c = model.checker().spawn_xla(
        frontier_capacity=1 << 19, table_capacity=table_cap, levels_per_dispatch=1,
        dedup="sorted",
    )
    step = c._superstep_for(f_cap)
    ebits = jnp.zeros((f_cap,), jnp.uint32)
    dt = timeit(lambda: step(frontier, ebits, jnp.int32(f_cap), ss, c._disc_found, c._disc_fp), n=3)
    print(f"real superstep [bucket 2^{f_cap.bit_length()-1}]: {dt*1e3:8.1f} ms ({f_cap*A/dt/1e6:8.1f} M grid-cand/s)", flush=True)

    # --- full measured pass, one level per dispatch, per-level times ----
    for lpd in (32, 1):
        m2 = PackedTwoPhaseSys(rm)
        kw = dict(frontier_capacity=1 << 19, table_capacity=table_cap,
                  levels_per_dispatch=lpd, dedup="sorted")
        t0 = time.monotonic()
        m2.checker().spawn_xla(**kw).join()
        warm = time.monotonic() - t0
        ck = m2.checker().spawn_xla(**kw)
        t0 = time.monotonic()
        lvl_times = []
        while not ck.is_done():
            t1 = time.monotonic()
            ck._run_block()
            lvl_times.append(time.monotonic() - t1)
        dt = time.monotonic() - t0
        print(f"full check lpd={lpd}: warm {warm:6.1f}s measured {dt:6.2f}s "
              f"({ck.state_count()/dt/1e6:6.2f} M gen/s; {ck.state_count():,} gen "
              f"{ck.unique_state_count():,} uniq depth {ck.max_depth()})", flush=True)
        if lpd != 1:
            # Bucket choices incl. tail shrink-exits: (run_cap, committed).
            print(f"  dispatches: {ck.dispatch_log}", flush=True)
        if lpd == 1:
            for lv, t in zip(ck.level_log, lvl_times):
                print(f"  depth {lv['depth']:3d} frontier {lv['frontier']:9,} gen {lv['generated']:9,} uniq {lv['unique']:9,}  {t*1e3:8.1f} ms", flush=True)

    # --- A/B: gather-family vs sort-family lowerings, end to end --------
    # (insert-values + is_new routing via STPU_SORTEDSET_VALUES, planes
    # compaction via spawn_xla(compaction=); fresh model instances so the
    # in-process superstep cache cannot mix lowerings.)
    # Decisive rows FIRST — tunnel windows can be short. Row 2 (the
    # pallas compaction, O(n) stream vs n log^2 n sort) is the defaults
    # decision; the mixed gather/sort families re-confirm the round-5
    # 2.3x split. EVERY delta row runs LAST: the delta structure
    # reproducibly faults the TPU runtime (registry #4, still open
    # post-redesign), and a fault poisons the process's device state —
    # once one row dies with a runtime error, the remaining rows are
    # unmeasurable and the loop bails with what it banked.
    for dedup, values_via, comp in (
        ("sorted", "sort", "sort"),
        ("sorted", "sort", "pallas"),
        ("sorted", "sort", "gather"),
        ("sorted", "gather", "sort"),
        ("sorted", "gather", "gather"),
        ("delta", "sort", "sort"),
        ("delta", "sort", "pallas"),
        ("delta", "gather", "sort"),
        ("delta", "gather", "gather"),
    ):
        sortedset.VALUES_VIA = values_via
        m3 = PackedTwoPhaseSys(rm)
        kw = dict(frontier_capacity=1 << 19, table_capacity=table_cap,
                  dedup=dedup, compaction=comp)
        try:
            t0 = time.monotonic()
            m3.checker().spawn_xla(**kw).join()
            warm = time.monotonic() - t0
            t0 = time.monotonic()
            ck = m3.checker().spawn_xla(**kw).join()
            dt = time.monotonic() - t0
            print(f"A/B dedup={dedup} values={values_via} compaction={comp}: "
                  f"warm {warm:6.1f}s measured {dt:6.2f}s "
                  f"({ck.state_count()/dt/1e6:6.2f} M gen/s)", flush=True)
        except Exception as e:
            import jax.errors
            print(f"A/B dedup={dedup} values={values_via} compaction={comp}: "
                  f"FAILED {type(e).__name__}: {str(e)[:300]}", flush=True)
            # Only an execution fault poisons device state; tunnel
            # compile-service hiccups also raise JaxRuntimeError
            # (INTERNAL: ... remote_compile) and stay row-local.
            if isinstance(e, jax.errors.JaxRuntimeError) and (
                "UNAVAILABLE" in str(e) or "crashed" in str(e)
            ):
                print("device runtime fault — remaining A/B rows skipped "
                      "(restarting the client is the only recovery)",
                      flush=True)
                break
    sortedset.VALUES_VIA = "auto"


if __name__ == "__main__":
    main()
