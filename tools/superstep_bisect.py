"""Bisect the superstep's per-level fixed cost on chip, by shape and stage.

Round-5 on-chip facts (tpu_profile_r5.log): the engine's real fused
superstep costs ~554 ms per level at bucket 2^18 / table 2^22 while its
component ops (expand, fingerprint, grid compaction, sorted insert)
measure ~0.1-1 ms standalone at the same shapes, and lpd=32 fusion does
NOT remove the cost — it is inside the compiled level body, and it
matches round 3's ~475 ms at an *empty frontier*. This tool pins where
it lives:

  sweep   time the real single-level superstep program across
          (bucket, table) shapes — the scaling law separates
          "per-kernel/serialization overhead" (flat) from "hidden
          O(table) or O(grid) data passes" (sloped)
  stages  rebuild the superstep with stages disabled one at a time
          (property eval, expansion+compaction, insert, frontier
          route-back) and time each variant at the flagship shape
  hlo     dump instruction/fusion counts of the compiled program

Usage: python tools/superstep_bisect.py [sweep|stages|hlo] [--cpu]
Run under `timeout` — the axon tunnel wedges rather than failing.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _setup():
    import jax

    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    print(f"platform={jax.devices()[0].platform}", flush=True)
    return jax


def _checker(f_pow: int, t_pow: int, rm: int = 8):
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    model = PackedTwoPhaseSys(rm)
    c = model.checker().spawn_xla(
        frontier_capacity=1 << f_pow, table_capacity=1 << t_pow,
        levels_per_dispatch=1, dedup="sorted",
    )
    return model, c


def _time_step(jax, c, f_cap: int, n: int = 5) -> float:
    """Median wall time of the engine's real one-level program at run
    capacity ``f_cap``, on a synthetic full frontier (every row valid —
    the steady-state worst case), timed by host-observed readback of a
    returned scalar (immune to async-dispatch undercounting)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    frontier = jnp.asarray(
        rng.integers(0, 2**32, (f_cap, c._W), dtype=np.uint32))
    ebits = jnp.zeros((f_cap,), jnp.uint32)
    step = c._superstep_for(f_cap)
    ts = []
    for _ in range(n + 1):
        t0 = time.monotonic()
        out = step(frontier, ebits, jnp.int32(f_cap), c._table,
                   c._disc_found, c._disc_fp)
        int(out[2])  # ncount readback: forces the whole dispatch
        ts.append(time.monotonic() - t0)
    return float(np.median(ts[1:]))  # drop the compile call


def sweep(jax) -> None:
    print("bucket x table sweep (real superstep, full frontier, median of 5)")
    for f_pow in (12, 14, 16, 18):
        for t_pow in (18, 20, 22):
            _, c = _checker(f_pow, t_pow)
            dt = _time_step(jax, c, 1 << f_pow)
            print(f"  f=2^{f_pow} table=2^{t_pow}: {dt*1e3:8.1f} ms "
                  f"({(1 << f_pow) * c._A / dt / 1e6:7.1f} M cand/s)",
                  flush=True)


def stages(jax) -> None:
    """Time the flagship-shape superstep with engine stages neutralized.

    Monkeypatches build-time hooks on fresh checker instances (each gets
    its own compile): every variant keeps the program's output signature
    so the dispatch protocol still works; the measured delta against
    "full" prices the stage.
    """
    import jax.numpy as jnp

    f_pow, t_pow = 18, 22
    rows = []

    def run(tag, patch=None):
        model, c = _checker(f_pow, t_pow)
        if patch:
            patch(model, c)
        dt = _time_step(jax, c, 1 << f_pow)
        rows.append((tag, dt))
        print(f"  {tag:24s} {dt*1e3:8.1f} ms", flush=True)

    run("full")

    def no_props(model, c):
        # Property evaluation priced out: no packed properties at all.
        c._P = 0
        c._prop_names = []
        c._prop_kinds = []
        import numpy as _np
        c._disc_found = jnp.zeros((0,), bool)
        c._disc_fp = jnp.zeros((0, 2), jnp.uint32)
        model.packed_properties = lambda words: jnp.zeros((0,), bool)

    run("no-properties", no_props)

    def no_expand(model, c):
        # Expansion priced out: one self-successor per state (A=1).
        model.packed_step = lambda words: (
            words[None, :], jnp.ones((1,), bool))
        model.max_actions = 1
        c._A = 1

    run("A=1 expand", no_expand)

    def no_insert(model, c):
        # Insert priced out: every candidate arrives inactive, so the
        # structure's sort/merge machinery sees an all-pad batch. c._ds
        # is the dedup module; a proxy namespace overrides insert only.
        import types

        real = c._ds

        def fake_insert(tbl, chi, clo, vhi, vlo, active, **kw):
            # Table untouched, everything "new": the sort/merge dead-codes
            # out of the program entirely — the variant prices the whole
            # visited-set stage.
            return tbl, active, jnp.bool_(False)

        proxy = types.SimpleNamespace(
            **{k: getattr(real, k) for k in dir(real) if not k.startswith("__")}
        )
        proxy.insert = fake_insert
        c._ds = proxy

    run("insert-inactive", no_insert)

    full = rows[0][1]
    for tag, dt in rows[1:]:
        print(f"  {tag:24s} saves {1e3*(full-dt):8.1f} ms", flush=True)


def hlo(jax) -> None:
    f_pow, t_pow = 18, 22
    _, c = _checker(f_pow, t_pow)
    import jax.numpy as jnp

    f_cap = 1 << f_pow
    rng = np.random.default_rng(0)
    frontier = jnp.asarray(rng.integers(0, 2**32, (f_cap, c._W), dtype=np.uint32))
    ebits = jnp.zeros((f_cap,), jnp.uint32)
    fn = c._superstep_for(f_cap)
    txt = fn.lower(frontier, ebits, jnp.int32(f_cap), c._table,
                   c._disc_found, c._disc_fp).compile().as_text()
    lines = txt.splitlines()
    import collections
    ops = collections.Counter()
    fusion_sizes = []
    for ln in lines:
        ln = ln.strip()
        if "= " in ln and "(" in ln:
            rhs = ln.split("= ", 1)[1]
            # "type opname(" — take the opname token.
            parts = rhs.split("(", 1)[0].split()
            if parts:
                ops[parts[-1]] += 1
    print(f"total instructions: {sum(ops.values())}")
    for op, n in ops.most_common(25):
        print(f"  {op:28s} {n}")
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "superstep_hlo.txt")
    with open(out, "w") as fh:
        fh.write(txt)
    print(f"full HLO -> {out} ({len(lines)} lines)")


def main() -> None:
    jax = _setup()
    mode = next((a for a in sys.argv[1:] if not a.startswith("-")), "sweep")
    {"sweep": sweep, "stages": stages, "hlo": hlo}[mode](jax)


if __name__ == "__main__":
    main()
