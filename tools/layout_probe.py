"""Measure the TPU cost of the engine's array layouts and primitives.

Answers four hardware questions the engine design hinges on:

1. the (8, 128) minor-dim tiling tax — elementwise/gather over ``[N, W]``
   row buffers (W=2) vs ``[W, N]`` transposed vs W separate ``[N]`` planes;
2. random 1-D gather throughput (the gather-vs-sort compaction decision,
   and whether a searchsorted/delta visited-set design could beat the
   per-level full-table sort);
3. sort cost vs operand count (payload-through-sort vs gather lowerings;
   2-key u32 pairs vs one fused u64 key);
4. scatter throughput (the is_new routing scatter).

All timed computations take their inputs as jit ARGUMENTS — a jitted
closure over device arrays is constant-folded by XLA at compile time and
times nothing (the bug that invalidated this tool's first draft).

Usage: python tools/layout_probe.py [--cpu] [pow]   (run under timeout)
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, *args, n=10):
    import jax

    jax.block_until_ready(fn(*args))
    t0 = time.monotonic()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / n


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        jax.config.update("jax_platforms", "cpu")
    # The engine is u32-only; x64 is enabled here just so the fused-u64-key
    # sort rows measure real 64-bit sorts instead of silently truncating.
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    pow_n = int(sys.argv[1]) if len(sys.argv) > 1 else 23
    N, W = 1 << pow_n, 2
    print(f"backend={jax.default_backend()} N=2^{pow_n} W={W}", flush=True)
    rng = np.random.default_rng(0)
    rows = jnp.asarray(rng.integers(0, 2**32, (N, W), dtype=np.uint32))
    rowsT = jnp.asarray(np.asarray(rows).T.copy())
    p0 = jnp.asarray(np.asarray(rows)[:, 0].copy())
    p1 = jnp.asarray(np.asarray(rows)[:, 1].copy())
    idx = jnp.asarray(rng.permutation(N).astype(np.int32))

    # 1. elementwise across layouts
    xor_rows = jax.jit(lambda r: r ^ jnp.uint32(0x9E3779B9))
    dt = timeit(xor_rows, rows)
    print(f"xor [N,{W}] rows    : {dt*1e3:8.2f} ms ({N*W*4/dt/1e9:7.1f} GB/s logical)", flush=True)
    dt = timeit(xor_rows, rowsT)
    print(f"xor [{W},N] transp  : {dt*1e3:8.2f} ms ({N*W*4/dt/1e9:7.1f} GB/s logical)", flush=True)
    xor_planes = jax.jit(lambda a, b: (a ^ jnp.uint32(0x9E3779B9), b ^ jnp.uint32(0x9E3779B9)))
    dt = timeit(xor_planes, p0, p1)
    print(f"xor {W}x[N] planes  : {dt*1e3:8.2f} ms ({N*W*4/dt/1e9:7.1f} GB/s logical)", flush=True)

    # 2. gathers
    grow = jax.jit(lambda r, i: r[i])
    dt = timeit(grow, rows, idx)
    print(f"gather [N,{W}] rows : {dt*1e3:8.2f} ms ({N/dt/1e6:7.1f} M rows/s)", flush=True)
    gplane = jax.jit(lambda a, b, i: (a[i], b[i]))
    dt = timeit(gplane, p0, p1, idx)
    print(f"gather {W}x[N] plane: {dt*1e3:8.2f} ms ({N*W/dt/1e6:7.1f} M elem/s)", flush=True)
    # sorted-ascending indices (searchsorted-ish locality, best case)
    idx_sorted = jnp.asarray(np.sort(np.asarray(idx)))
    dt = timeit(gplane, p0, p1, idx_sorted)
    print(f"gather {W}x[N] asc  : {dt*1e3:8.2f} ms ({N*W/dt/1e6:7.1f} M elem/s)", flush=True)

    # 3. scatter (is_new-routing shape: bool by unique indices)
    scat = jax.jit(
        lambda i: jnp.zeros((N,), jnp.bool_).at[i].set(True, mode="drop")
    )
    dt = timeit(scat, idx)
    print(f"scatter bool [N]   : {dt*1e3:8.2f} ms ({N/dt/1e6:7.1f} M elem/s)", flush=True)

    # 4. sorts: operand-count scaling + fused u64 key
    tick = jnp.arange(N, dtype=jnp.int32)
    s2 = jax.jit(lambda a, b: jax.lax.sort((a, b), num_keys=2))
    dt = timeit(s2, p0, p1, n=3)
    print(f"sort 2-key 2-op    : {dt*1e3:8.2f} ms ({N/dt/1e6:7.1f} M keys/s)", flush=True)
    s3 = jax.jit(lambda a, b, t: jax.lax.sort((a, b, t), num_keys=3))
    dt = timeit(s3, p0, p1, tick, n=3)
    print(f"sort 3-key 3-op    : {dt*1e3:8.2f} ms", flush=True)
    s5 = jax.jit(lambda a, b, t, c, d: jax.lax.sort((a, b, t, c, d), num_keys=3))
    dt = timeit(s5, p0, p1, tick, p0, p1, n=3)
    print(f"sort 3-key 5-op    : {dt*1e3:8.2f} ms", flush=True)
    s8 = jax.jit(
        lambda a, b, t, c, d, e, f, g: jax.lax.sort(
            (a, b, t, c, d, e, f, g), num_keys=3
        )
    )
    dt = timeit(s8, p0, p1, tick, p0, p1, p0, p1, tick, n=3)
    print(f"sort 3-key 8-op    : {dt*1e3:8.2f} ms", flush=True)
    try:
        k64j = jax.jit(lambda a, b: (a.astype(jnp.uint64) << 32) | b)
        k64 = k64j(p0, p1)
        s1u = jax.jit(lambda k: jax.lax.sort(k))
        dt = timeit(s1u, k64, n=3)
        print(f"sort u64 1-op      : {dt*1e3:8.2f} ms", flush=True)
        s2u = jax.jit(lambda k, t: jax.lax.sort((k, t), num_keys=1))
        dt = timeit(s2u, k64, tick, n=3)
        print(f"sort u64 + idx     : {dt*1e3:8.2f} ms", flush=True)
    except Exception as e:  # 64-bit ints may not lower on this backend
        print(f"sort u64: unavailable ({type(e).__name__})", flush=True)
    # 1-key i32 + payload (the engine's fused compaction key shape)
    ki = jnp.asarray(rng.integers(0, 2**30, N, dtype=np.int32))
    s2i = jax.jit(lambda k, t: jax.lax.sort((k, t), num_keys=1))
    dt = timeit(s2i, ki, tick, n=3)
    print(f"sort i32 + idx     : {dt*1e3:8.2f} ms", flush=True)

    # 5. searchsorted-style binary search: log2(N) rounds of gathers
    def bsearch(keys, queries):
        off = jnp.zeros(queries.shape, jnp.int32)
        step = keys.shape[0]
        while step > 1:
            step //= 2
            mid = off + step
            less = keys[jnp.minimum(mid, keys.shape[0] - 1)] <= queries
            off = jnp.where(less, mid, off)
        return off

    skeys = jnp.asarray(np.sort(rng.integers(0, 2**32, N, dtype=np.uint32)))
    queries = jnp.asarray(rng.integers(0, 2**32, N // 2, dtype=np.uint32))
    bs = jax.jit(bsearch)
    dt = timeit(bs, skeys, queries, n=3)
    print(f"bsearch [N/2] in [N]: {dt*1e3:8.2f} ms ({(N//2)/dt/1e6:7.1f} M lookups/s)", flush=True)


if __name__ == "__main__":
    main()
