"""Measure the TPU cost of the engine's array layouts.

Hypothesis: ``[N, W]`` row-major state buffers with tiny minor dims
(W=2 for 2pc) are tiled by XLA:TPU as (8, 128) blocks with the minor
dimension padded to 128 lanes — a ~64x memory-traffic blowup on every
elementwise op and gather over packed-state rows.  If true, the engine
should hold states as W separate ``[N]`` planes (structure-of-arrays,
like the visited set already does) instead of ``[N, W]`` rows.

Times, per layout: an elementwise op, a gather by row index (the
compaction shape), and a vmapped packed_step-style expand.

Usage: python tools/layout_probe.py [--cpu]   (run under timeout)
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, n=10):
    import jax

    jax.block_until_ready(fn())
    t0 = time.monotonic()
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / n


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    print(f"backend={jax.default_backend()}", flush=True)
    N, W = 1 << 23, 2
    rng = np.random.default_rng(0)
    rows = jnp.asarray(rng.integers(0, 2**32, (N, W), dtype=np.uint32))
    rowsT = jnp.asarray(np.asarray(rows).T.copy())
    planes = [jnp.asarray(np.asarray(rows)[:, i].copy()) for i in range(W)]
    idx = jnp.asarray(rng.permutation(N).astype(np.int32))

    # elementwise
    dt = timeit(jax.jit(lambda: rows ^ jnp.uint32(0x9E3779B9)))
    print(f"xor [N,{W}] rows    : {dt*1e3:8.2f} ms ({N*W*4/dt/1e9:7.1f} GB/s)", flush=True)
    dt = timeit(jax.jit(lambda: rowsT ^ jnp.uint32(0x9E3779B9)))
    print(f"xor [{W},N] transp  : {dt*1e3:8.2f} ms ({N*W*4/dt/1e9:7.1f} GB/s)", flush=True)
    dt = timeit(jax.jit(lambda: [p ^ jnp.uint32(0x9E3779B9) for p in planes]))
    print(f"xor {W}x[N] planes  : {dt*1e3:8.2f} ms ({N*W*4/dt/1e9:7.1f} GB/s)", flush=True)

    # gather rows by index (compaction inner op)
    dt = timeit(jax.jit(lambda: rows[idx]))
    print(f"gather [N,{W}] rows : {dt*1e3:8.2f} ms", flush=True)
    dt = timeit(jax.jit(lambda: rowsT[:, idx]))
    print(f"gather [{W},N] transp: {dt*1e3:8.2f} ms", flush=True)
    dt = timeit(jax.jit(lambda: [p[idx] for p in planes]))
    print(f"gather {W}x[N] planes: {dt*1e3:8.2f} ms", flush=True)

    # argsort-based compaction end to end at grid scale
    mask = jnp.asarray(rng.integers(0, 4, N, dtype=np.uint32) == 0)
    cap = N // 4

    def compact_rows():
        order = jnp.argsort(~mask, stable=True)[:cap]
        return rows[order]

    def compact_planes():
        order = jnp.argsort(~mask, stable=True)[:cap]
        return [p[order] for p in planes]

    dt = timeit(jax.jit(compact_rows), n=3)
    print(f"compact [N,{W}] rows : {dt*1e3:8.2f} ms", flush=True)
    dt = timeit(jax.jit(compact_planes), n=3)
    print(f"compact {W}x[N] planes: {dt*1e3:8.2f} ms", flush=True)

    # sort payload: 5-op 3-key sort with [N] planes (sortedset.insert shape)
    kh, kl = planes[0], planes[1]
    tick = jnp.arange(N, dtype=jnp.int32)
    dt = timeit(jax.jit(lambda: jax.lax.sort((kh, kl, tick, kh, kl), num_keys=3)), n=3)
    print(f"sort5 3-key [N]    : {dt*1e3:8.2f} ms", flush=True)
    dt = timeit(jax.jit(lambda: jax.lax.sort((kh, kl, tick), num_keys=3)), n=3)
    print(f"sort3 3-key [N]    : {dt*1e3:8.2f} ms", flush=True)
    # 2-key without index payloads (pure dedup shape)
    dt = timeit(jax.jit(lambda: jax.lax.sort((kh, kl), num_keys=2)), n=3)
    print(f"sort2 2-key [N]    : {dt*1e3:8.2f} ms", flush=True)
    # single fused 64-bit key
    k64 = (planes[0].astype(jnp.uint64) << 32) | planes[1].astype(jnp.uint64)
    dt = timeit(jax.jit(lambda: jax.lax.sort(k64)), n=3)
    print(f"sort1 u64 [N]      : {dt*1e3:8.2f} ms", flush=True)
    t64 = jnp.arange(N, dtype=jnp.int32)
    dt = timeit(jax.jit(lambda: jax.lax.sort((k64, t64), num_keys=1)), n=3)
    print(f"sort u64+idx [N]   : {dt*1e3:8.2f} ms", flush=True)


if __name__ == "__main__":
    main()
