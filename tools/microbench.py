"""Microbenchmark the device-engine cost model on the current backend.

Separates the four costs that determine checker throughput so tuning is
evidence-driven rather than guesswork:

1. dispatch RTT — a trivial jit call (the floor for any per-level host sync;
   large over the axon tunnel),
2. superstep compile time per bucket size,
3. steady-state superstep wall time per bucket (states/sec at that width),
4. hash-set insert cost vs batch size (the scatter-heavy op most likely to
   be TPU-hostile).

Usage: python tools/microbench.py [rm] [--cpu]

``--cpu`` pins the CPU backend at config level BEFORE first backend use —
without it the script initializes the session's default backend, which on
this container is the axon TPU plugin and can WEDGE while the tunnel is
down (the CLAUDE.md gotcha; tpu_plan.sh runs it un-pinned on purpose,
after a successful probe).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, *args, n=5):
    fn(*args)  # compile / warm
    t0 = time.monotonic()
    for _ in range(n):
        out = fn(*args)
    import jax

    jax.block_until_ready(out)
    return (time.monotonic() - t0) / n


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    rm = int(sys.argv[1]) if len(sys.argv) > 1 else 6
    print(f"backend={jax.default_backend()} device={jax.devices()[0]}", flush=True)

    # 1. dispatch RTT
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.uint32)
    rtt = timeit(lambda v: f(v), x, n=20)
    print(f"dispatch RTT (trivial jit): {rtt*1e3:.2f} ms", flush=True)

    # 2+3. superstep compile + steady time per bucket
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    model = PackedTwoPhaseSys(rm)
    c = model.checker().spawn_xla(
        frontier_capacity=1 << 17, table_capacity=1 << 22, levels_per_dispatch=1
    )
    from stateright_tpu.ops import fphash, hashset

    for pow2 in (10, 12, 14, 16, 17):
        cap = 1 << pow2
        t0 = time.monotonic()
        step = c._superstep_for(cap)
        frontier = jnp.zeros((cap, model.state_words), jnp.uint32)
        ebits = jnp.zeros((cap,), jnp.uint32)
        out = step(
            frontier, ebits, jnp.int32(cap), c._table, c._disc_found, c._disc_fp
        )
        jax.block_until_ready(out)
        compile_s = time.monotonic() - t0
        dt = timeit(
            lambda: step(
                frontier, ebits, jnp.int32(cap), c._table, c._disc_found, c._disc_fp
            ),
            n=5,
        )
        cands = cap * model.max_actions
        print(
            f"superstep bucket 2^{pow2}: compile {compile_s:6.1f}s  steady "
            f"{dt*1e3:8.1f} ms  ({cands/dt/1e6:8.2f} M cand/s)",
            flush=True,
        )

    # 4. insert cost vs batch — both visited-set structures at the same
    #    shapes (the hash/scatter vs sort-merge design decision,
    #    BASELINE.md cost model).
    from stateright_tpu.ops import sortedset

    table = hashset.make(1 << 22, jnp)
    n_occ = (3 << 22) // 8  # sorted set at its 3/4-load growth ceiling's half
    rng0 = np.random.default_rng(9)
    keys = np.sort(rng0.integers(1, 2**63, n_occ, dtype=np.uint64))
    stab = sortedset.from_entries(
        (keys >> 32).astype(np.uint32), (keys & 0xFFFFFFFF).astype(np.uint32),
        np.zeros(n_occ, np.uint32), np.zeros(n_occ, np.uint32), 1 << 22, jnp,
    )
    ins = jax.jit(hashset.insert, static_argnames="max_probes")
    sins = jax.jit(sortedset.insert)
    for pow2 in (14, 17, 20, 22):
        m = 1 << pow2
        rng = np.random.default_rng(0)
        hi = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
        lo = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
        act = jnp.ones((m,), jnp.bool_)
        dt = timeit(lambda: ins(table, hi, lo, hi, lo, act), n=3)
        ds = timeit(lambda: sins(stab, hi, lo, hi, lo, act), n=3)
        print(
            f"insert m=2^{pow2}: hash {dt*1e3:8.1f} ms ({m/dt/1e6:7.2f} M/s)  "
            f"sorted {ds*1e3:8.1f} ms ({m/ds/1e6:7.2f} M/s)",
            flush=True,
        )

    # 5. cost model for the sort-based dedup alternative: a two-key sort of
    #    the candidate batch (in-batch dedup + visited-merge building block)
    #    and a pure scatter vs gather-compaction comparison at batch size.
    def sort2(hi, lo):
        return jax.lax.sort((hi, lo), num_keys=2)

    sort2j = jax.jit(sort2)
    for pow2 in (17, 20, 22, 24):
        m = 1 << pow2
        rng = np.random.default_rng(1)
        hi = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
        lo = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
        dt = timeit(lambda: sort2j(hi, lo), n=3)
        print(
            f"two-key sort m=2^{pow2}: {dt*1e3:8.1f} ms  ({m/dt/1e6:8.2f} M keys/s)",
            flush=True,
        )

    # 6. end-to-end amortization: warm full-coverage checks with the level
    #    loop on device (fused, default) vs one level per dispatch — the
    #    direct measurement of dispatch/tunnel-latency amortization.
    for levels in (32, 1):
        kw = dict(
            frontier_capacity=1 << 17,
            table_capacity=1 << 21,
            levels_per_dispatch=levels,
        )
        model2 = PackedTwoPhaseSys(rm)
        model2.checker().spawn_xla(**kw).join()  # warm/compile
        t0 = time.monotonic()
        c2 = model2.checker().spawn_xla(**kw).join()
        dt = time.monotonic() - t0
        print(
            f"full check rm={rm} levels_per_dispatch={levels}: {dt:7.2f}s "
            f"({c2.state_count()/dt/1e3:8.1f} k gen/s)",
            flush=True,
        )

    W = 4
    for pow2 in (17, 20):
        m = 1 << pow2
        rng = np.random.default_rng(2)
        rows = jnp.asarray(rng.integers(0, 2**32, (m, W), dtype=np.uint32))
        keep = jnp.asarray(rng.integers(0, 2, m, dtype=np.uint32).astype(bool))

        def compact_scatter(rows, keep):
            pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
            idx = jnp.where(keep, pos, m)
            return jnp.zeros((m, W), jnp.uint32).at[idx].set(rows, mode="drop")

        def compact_gather(rows, keep):
            order = jnp.argsort(~keep, stable=True)
            return rows[order]

        ds = timeit(jax.jit(compact_scatter), rows, keep, n=3)
        dg = timeit(jax.jit(compact_gather), rows, keep, n=3)
        print(
            f"compaction m=2^{pow2} W={W}: scatter {ds*1e3:8.1f} ms vs "
            f"sort+gather {dg*1e3:8.1f} ms",
            flush=True,
        )


if __name__ == "__main__":
    main()
