#!/usr/bin/env python
"""Pre-seed the persistent XLA compile cache with the shipped model shapes.

A fresh CheckerService's first request pays the full XLA trace+compile for
its model's bucket schedule — minutes on the tunnel (VERDICT item 6:
paxos warm <= 29 s only once the cache is hot). This tool banks those
compiles ahead of time: it runs each shipped packed-model configuration
(``stateright_tpu/service/registry.py`` :data:`SHIPPED` — the exact specs
and capacities service jobs default to, so the (shape, bucket) schedules
match and every program lands in ``.jax_cache/``) once to completion
through the REAL service worker, each under its own supervised process
group — a wedge mid-warm burns one spec's budget, never the tool.

The warm set is DERIVED from the STPU007 compile-plan census
(``stateright_tpu/analysis/census.py`` — the same shared ladder planner
the engine runs), not hand-maintained: the census enumerates each
shipped spec's (bucket, cand-rung) schedule at the registry capacities,
so a registry or planner change re-aims this tool automatically
(census/SHIPPED drift is a test failure, ``tests/test_analysis.py``).

Usage::

    python tools/warm_cache.py                 # the censused shipped specs
    python tools/warm_cache.py --specs 2pc:4 paxos:2,3
    python tools/warm_cache.py --platform cpu  # warm the CPU cache (CI)
    python tools/warm_cache.py --mux 4         # + the K=4 batched programs
    python tools/warm_cache.py --sym           # + the symmetry-variant programs

``--mux K`` additionally banks the multiplexed-superstep programs a
service running with ``STPU_MUX=K`` compiles (the census's ``mux`` shape
classes — ``plan_for(..., mux_k=K)``): after each eligible spec's solo
warm, one K-lane ``worker.py --mux`` group of that spec runs to
completion, landing the batched (k, bucket, cand_cap) programs in the
same cache. Specs outside ``registry.MUX_FAMILIES`` warm solo only.

``--sym`` additionally banks the symmetry-variant programs
(docs/symmetry.md; the census's ``sym`` shape classes —
``plan_for(..., symmetry=True)``): after the solo warms, each
``registry.SYM_FAMILIES`` spec re-runs its worker with ``STPU_SYMMETRY=1``
so the canonicalization-fused bucket programs land in the same cache.

Emits one JSON line per spec and a final summary. Re-running is cheap:
already-cached programs load in seconds, so this doubles as a cache
health check. See docs/service.md ("First-request latency").
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from stateright_tpu import supervise as sup  # noqa: E402 (path bootstrap)
from stateright_tpu.service.registry import parse  # noqa: E402

WORKER = os.path.join(REPO, "stateright_tpu", "service", "worker.py")


def default_specs():
    """The warm set, derived from the compile-plan census. The banked
    artifact (``runs/compile_plan.json``, written by every full
    stpu-lint run) is preferred — no jax import in this parent at all;
    only when it is absent does the parent build the census in-process,
    CPU-pinned first (the first jax backend use here must never be the
    axon plugin — CLAUDE.md gotcha #1; the workers pick their own
    platform via ``--platform``). The analyzer's pin appends the
    8-virtual-device XLA flag for its mesh surface; that is restored
    afterwards so warm WORKERS never inherit it."""
    try:
        with open(os.path.join(REPO, "runs", "compile_plan.json")) as fh:
            census = json.load(fh)
        # Freshness via the census's banked tree hash (tree_hash is pure
        # file hashing — no jax): a census banked for some OTHER tree
        # (e.g. before a spec joined SHIPPED) must not shape the warm
        # set — that is exactly the drift the derivation eliminates.
        from stateright_tpu.analysis.cache import tree_hash

        specs = list(census["specs"])
        if specs and census.get("tree") == tree_hash()[:12]:
            return specs
    except (OSError, json.JSONDecodeError, KeyError):
        pass
    flags = os.environ.get("XLA_FLAGS")
    from stateright_tpu.analysis.census import warm_specs
    from stateright_tpu.analysis.surfaces import pin_cpu

    pin_cpu()
    try:
        return warm_specs()
    finally:
        if flags is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = flags


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument(
        "--specs", nargs="*", default=None,
        help="default: derived from the STPU007 compile-plan census",
    )
    p.add_argument("--platform", default="default",
                   help='"default" (accelerator) or "cpu"')
    p.add_argument("--budget-s", type=float, default=900.0,
                   help="per-spec wall-clock budget")
    p.add_argument("--stall-s", type=float, default=300.0,
                   help="mid-dispatch heartbeat leash (3x while compiling)")
    p.add_argument("--cache-dir", default=os.path.join(REPO, ".jax_cache"))
    p.add_argument("--out-dir", default=os.path.join(REPO, "runs", "warm"))
    p.add_argument(
        "--mux", type=int, default=0, metavar="K",
        help="also pre-warm the K-lane multiplexed programs "
             "(one worker.py --mux group per MUX_FAMILIES spec)",
    )
    p.add_argument(
        "--sym", action="store_true",
        help="also pre-warm the symmetry-variant programs "
             "(STPU_SYMMETRY=1 worker run per SYM_FAMILIES spec)",
    )
    args = p.parse_args()

    if args.specs is None:
        args.specs = default_specs()
    for spec in args.specs:
        parse(spec)  # fail fast on typos, before any jax import anywhere

    os.makedirs(args.out_dir, exist_ok=True)
    env = dict(os.environ, STPU_COMPILE_CACHE=args.cache_dir)
    env.pop("STPU_TRACE", None)
    env.pop("STPU_CHECKPOINT_TO", None)

    summary = []
    for spec in args.specs:
        tag = spec.replace(":", "_").replace(",", "-")
        out = os.path.join(args.out_dir, f"warm_{tag}.json")
        t0 = time.monotonic()
        res = sup.run_worker(
            [
                sys.executable, WORKER,
                "--spec", spec,
                "--engine", "xla",
                "--platform", args.platform,
                "--out", out,
                "--max-seconds", str(args.budget_s),
            ],
            heartbeat=os.path.join(args.out_dir, f"warm_{tag}_hb.json"),
            timeout_s=args.budget_s * 1.5 + 60.0,
            stall_s=args.stall_s,
            startup_grace_s=600.0,
            poll_s=1.0,
            env=env,
            stdout_path=os.path.join(args.out_dir, f"warm_{tag}.out"),
        )
        row = {
            "spec": spec,
            "ok": res.ok,
            "seconds": round(time.monotonic() - t0, 2),
            "killed": res.killed,
            "rc": res.rc,
        }
        if res.ok and os.path.exists(out):
            with open(out) as fh:
                r = json.load(fh)
            row.update(
                generated=r["generated"], unique=r["unique"],
                platform=r["platform"],
            )
        summary.append(row)
        print(json.dumps(row), flush=True)

    if args.sym:
        from stateright_tpu.service.registry import SYM_FAMILIES

        for spec in args.specs:
            if parse(spec)[0] not in SYM_FAMILIES:
                continue
            tag = spec.replace(":", "_").replace(",", "-")
            out = os.path.join(args.out_dir, f"warm_{tag}_sym.json")
            t0 = time.monotonic()
            res = sup.run_worker(
                [
                    sys.executable, WORKER,
                    "--spec", spec,
                    "--engine", "xla",
                    "--platform", args.platform,
                    "--out", out,
                    "--max-seconds", str(args.budget_s),
                ],
                heartbeat=os.path.join(args.out_dir, f"warm_{tag}_sym_hb.json"),
                timeout_s=args.budget_s * 1.5 + 60.0,
                stall_s=args.stall_s,
                startup_grace_s=600.0,
                poll_s=1.0,
                env=dict(env, STPU_SYMMETRY="1"),
                stdout_path=os.path.join(args.out_dir, f"warm_{tag}_sym.out"),
            )
            row = {
                "spec": spec,
                "sym": True,
                "ok": res.ok,
                "seconds": round(time.monotonic() - t0, 2),
                "killed": res.killed,
                "rc": res.rc,
            }
            if res.ok and os.path.exists(out):
                with open(out) as fh:
                    r = json.load(fh)
                row.update(
                    generated=r["generated"], unique=r["unique"],
                    platform=r["platform"],
                )
            summary.append(row)
            print(json.dumps(row), flush=True)

    if args.mux > 1:
        from stateright_tpu.service.registry import MUX_FAMILIES

        for spec in args.specs:
            if parse(spec)[0] not in MUX_FAMILIES:
                continue
            tag = spec.replace(":", "_").replace(",", "-")
            lanes = []
            for i in range(args.mux):
                lanes.append({
                    "job": f"warm-{tag}-l{i}",
                    "out": os.path.join(
                        args.out_dir, f"warm_{tag}_mux_l{i}.json"
                    ),
                })
            manifest = os.path.join(args.out_dir, f"warm_{tag}_mux.json")
            with open(manifest, "w") as fh:
                json.dump(
                    {"group": f"warm-mux-{tag}", "spec": spec,
                     "lanes": lanes}, fh,
                )
            t0 = time.monotonic()
            res = sup.run_worker(
                [
                    sys.executable, WORKER,
                    "--mux", manifest,
                    "--spec", spec,
                    "--engine", "xla",
                    "--platform", args.platform,
                    "--out", os.path.join(
                        args.out_dir, f"warm_{tag}_mux_group.json"
                    ),
                    "--max-seconds", str(args.budget_s),
                ],
                heartbeat=os.path.join(
                    args.out_dir, f"warm_{tag}_mux_hb.json"
                ),
                timeout_s=args.budget_s * 1.5 + 60.0,
                stall_s=args.stall_s,
                startup_grace_s=600.0,
                poll_s=1.0,
                env=env,
                stdout_path=os.path.join(args.out_dir, f"warm_{tag}_mux.out"),
            )
            row = {
                "spec": spec,
                "mux": args.mux,
                "ok": res.ok,
                "seconds": round(time.monotonic() - t0, 2),
                "killed": res.killed,
                "rc": res.rc,
            }
            summary.append(row)
            print(json.dumps(row), flush=True)

    ok = sum(1 for r in summary if r["ok"])
    print(
        json.dumps(
            {
                "warmed": ok,
                "failed": len(summary) - ok,
                "cache_dir": args.cache_dir,
            }
        )
    )
    return 0 if ok == len(summary) else 1


if __name__ == "__main__":
    sys.exit(main())
