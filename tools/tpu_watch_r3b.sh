#!/usr/bin/env bash
# Round-3b tunnel watcher: on recovery, run the layout probe and the
# superstep stage profile (the evidence the planes-layout decision needs),
# then stop. Logs -> tpu_watch_r3b.log, tpu_layout_probe.log, tpu_profile.log
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch_r3b.log
log() { echo "[watch $(date +%H:%M:%S)] $*" >>"$LOG"; }
log "watcher started (pid $$)"
while true; do
  if timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; then
    log "TUNNEL UP — layout probe"
    timeout 1200 python tools/layout_probe.py >tpu_layout_probe.log 2>&1
    rc1=$?
    log "layout_probe rc=$rc1"
    timeout 2400 python tools/profile_superstep.py 8 >tpu_profile.log 2>&1
    rc2=$?
    log "profile_superstep rc=$rc2"
    if [ "$rc1" -eq 0 ] && [ "$rc2" -eq 0 ]; then
      log "both probes done; watcher exiting"
      exit 0
    fi
    log "a probe failed; resuming watch"
  else
    log "tunnel down"
  fi
  sleep 240
done
