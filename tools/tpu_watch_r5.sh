#!/usr/bin/env bash
# Round-5 tunnel watcher. The verdict made round 5 a perf round: the one
# thing that matters is on-chip numbers for the engine the repo ships.
# On tunnel recovery, in priority order (windows can be short):
#   1. bench.py               — the primary metric + matrix, count-checked
#                               + audited (VERDICT items 1, 2-sorted, 4)
#   2. paxos A/B              — sorted vs hash on chip with the audit
#                               (VERDICT item 2, the round-3 drift question)
#   3. superstep profile      — per-stage on-chip accounting for the
#                               roofline roadmap (VERDICT item 3)
#   4. soak rm=9/10/11        — visited-set architecture at 10^8 scale
#                               (VERDICT item 5; tpu_plan.sh stage 5)
# Unlike the r4 watcher, artifacts are committed AFTER EACH STAGE — a
# tunnel drop mid-plan must not lose the stages that finished. Only files
# this watcher produced are staged (never `git add -A`).
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch_r5.log
log() { echo "[watch $(date +%H:%M:%S)] $*" >>"$LOG"; }
commit_stage() { # $1 = message; rest = artifact files
  local msg=$1 f; shift
  # Add one-by-one: a single missing artifact (stage killed early) must
  # not abort staging of the ones that DO exist.
  for f in "$@" "$LOG"; do
    git add -f -- "$f" >>"$LOG" 2>&1 || log "artifact missing: $f"
  done
  git commit -q -m "$msg" >>"$LOG" 2>&1 && log "committed: $msg"
}
log "watcher started (pid $$)"
while true; do
  if timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; then
    log "TUNNEL UP — stage 1: bench.py (primary)"
    timeout 3600 python bench.py >bench_r5_out.json 2>>"$LOG"
    rc1=$?
    log "bench rc=$rc1: $(tail -c 300 bench_r5_out.json 2>/dev/null)"
    commit_stage "TPU r5 stage 1: primary bench (rc=$rc1)" \
      bench_r5_out.json bench_detail.json bench_probe.log

    log "stage 2: paxos A/B (sorted vs hash + audit)"
    timeout 2400 python tools/paxos_ab.py --deep >tpu_paxos_ab.jsonl 2>>"$LOG"
    rc2=$?
    log "paxos_ab rc=$rc2: $(cat tpu_paxos_ab.jsonl 2>/dev/null | tail -c 400)"
    commit_stage "TPU r5 stage 2: paxos sorted-vs-hash A/B (rc=$rc2)" \
      tpu_paxos_ab.jsonl

    log "stage 3: superstep profile (rm=8)"
    timeout 2700 python tools/profile_superstep.py 8 >tpu_profile_r5.log 2>&1
    rc3=$?
    log "profile_superstep rc=$rc3"
    commit_stage "TPU r5 stage 3: superstep per-stage profile (rc=$rc3)" \
      tpu_profile_r5.log

    log "stage 4: scale soak rm=9/10/11"
    timeout 5400 python tools/tpu_soak.py >tpu_soak_r5.log 2>&1
    rc4=$?
    log "soak rc=$rc4"
    commit_stage "TPU r5 stage 4: scale soak rm=9/10/11 + paxos 3c/3s (rc=$rc4)" \
      tpu_soak_r5.log

    if [ "$rc1" -eq 0 ] && [ "$rc2" -eq 0 ] && [ "$rc3" -eq 0 ] && [ "$rc4" -eq 0 ]; then
      log "all stages done; watcher exiting"
      exit 0
    fi
    log "a stage failed; resuming watch"
  else
    log "tunnel down"
  fi
  sleep 240
done
