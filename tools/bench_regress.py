#!/usr/bin/env python
"""Perf-regression gate: a fresh bench line judged against the archived
trajectory.

The perf record (CPU 129k -> 772k, chip 1.25M -> 2.72M gen/s) lives in
``runs/archive/BENCH_r*.json`` and the service SLO line in
``runs/service_chaos.json`` — but until this tool, nothing compared a
fresh run against them mechanically: a regression would only be noticed
by a person re-reading JSON. This gate loads the trajectory, compares the
fresh primary line (gen/s, count_ok, resumed, lint_ok) and the chaos SLO
line (admission p99, turnaround p99) against per-platform baselines with
explicit tolerances, and emits ONE typed verdict JSON line to
``runs/regress.json`` (and stdout):

    {"tool": "bench_regress", "verdict": "pass" | "fail" | "no_baseline",
     "platform": ..., "checks": [...], ...}

Verdicts are typed, never a crash:

- ``pass``        — every applicable check passed;
- ``fail``        — at least one check failed (throughput below
                    ``(1 - tolerance) x`` the platform's archived best,
                    ``count_ok`` false, ``lint_ok`` false, SLO p99 above
                    its limit, or a failed chaos sweep);
- ``no_baseline`` — the archive has no parseable ``BENCH_r*.json`` at all
                    (fresh clones; satellite: a typed non-failure, exit 0).

Per-check ``skip`` verdicts cover the honest gaps: a platform with no
archived line yet (e.g. the first chip line), a ``resumed`` fresh line
(it measures the tail of a space from a checkpoint — not comparable to a
cold full pass), a line whose ``fleet`` provenance records cross-device
migrations (the box was running a fleet failover sweep concurrently —
throughput measured amid evacuations judges the chaos harness, not the
engine), tri-state ``count_ok``/``lint_ok`` = None, and a missing chaos
artifact.

Inputs: the fresh line defaults to ``runs/bench_detail.json`` (it carries
everything the primary stdout line does, plus resume/lint provenance) and
also accepts a raw primary-line JSON file (``--fresh line.json``).

``--self-test`` proves the gate's three verdicts against the real
archived trajectory (pass on the newest real line, fail on a synthetically
degraded copy, no_baseline on an empty dir) — the smoke-stage form, no
jax, <5 s. ``tools/tpu_watch.sh`` exposes the bare stage alias
``bench_regress`` so the next chip window self-judges right after its
bench stage. Exit codes: 0 pass/no_baseline/self-test-ok, 1 fail,
2 tool error (unreadable fresh line).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_ARCHIVE = os.path.join(REPO, "runs", "archive")
DEFAULT_FRESH = os.path.join(REPO, "runs", "bench_detail.json")
DEFAULT_CHAOS = os.path.join(REPO, "runs", "service_chaos.json")
DEFAULT_OUT = os.path.join(REPO, "runs", "regress.json")

#: Fresh throughput must reach (1 - tolerance) x the platform's archived
#: best. 0.35 accommodates the honest run-to-run spread of the 1-core CPU
#: box (runs/archive r02->r04: 600k..772k, a 22% band) while still
#: catching a real regression (an engine bug typically costs 2x+).
DEFAULT_TOLERANCE = 0.35
#: SLO limits for the chaos line (tools/service_chaos.py percentiles);
#: generous absolutes — the archive has no banked SLO trajectory yet, so
#: these are explicit flags, not derived baselines.
DEFAULT_ADMISSION_P99_MS = 5000.0
DEFAULT_TURNAROUND_P99_S = 300.0


def _platform_of(metric: str) -> str:
    """The platform label a primary line carries: the suffix after the
    last comma of its metric string ("... spawn_xla, cpu" -> "cpu")."""
    return metric.rsplit(",", 1)[-1].strip() if "," in metric else "unknown"


def load_trajectory(archive_dir: str) -> Dict[str, Dict[str, Any]]:
    """Per-platform baselines from ``BENCH_r*.json``: each file is the
    driver's wrapper ({"n", "parsed": {primary line}}) or a raw primary
    line; unparseable files are skipped (the verdict reports how many
    lines were read). Baseline = the platform's best archived value (the
    trajectory's high-water mark — rm varies across rounds, but gen/s is
    the platform's throughput metric throughout the archive)."""
    out: Dict[str, Dict[str, Any]] = {}
    for path in sorted(glob.glob(os.path.join(archive_dir, "BENCH_r*.json"))):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        line = doc.get("parsed") if isinstance(doc, dict) else None
        if line is None and isinstance(doc, dict) and "metric" in doc:
            line = doc
        if not isinstance(line, dict) or "value" not in line or "metric" not in line:
            continue
        platform = _platform_of(line["metric"])
        entry = out.setdefault(
            platform, {"best": 0.0, "best_metric": None, "lines": 0}
        )
        entry["lines"] += 1
        if float(line["value"]) > entry["best"]:
            entry["best"] = float(line["value"])
            entry["best_metric"] = line["metric"]
            entry["best_file"] = os.path.basename(path)
        # Batched-scheduling baseline (BENCH_MUX; docs/service.md
        # "Batched scheduling"): archived rounds that ran the mux
        # throughput probe carry its row — the per-platform best
        # jobs_per_sec becomes the mux trajectory. Absent everywhere
        # until a round banks one (the mux check skips, no_baseline-safe).
        mux = (doc.get("mux") if isinstance(doc, dict) else None) or line.get("mux")
        if isinstance(mux, dict) and mux.get("jobs_per_sec"):
            if float(mux["jobs_per_sec"]) > entry.get("mux_best", 0.0):
                entry["mux_best"] = float(mux["jobs_per_sec"])
                entry["mux_best_file"] = os.path.basename(path)
        # Symmetry-reduction baseline (BENCH_SYM; docs/symmetry.md):
        # archived rounds that ran the sym A/B carry its row — the
        # per-platform best off/on wall-clock ratio becomes the sym
        # trajectory (same no_baseline-safe contract as mux).
        sym = (doc.get("sym") if isinstance(doc, dict) else None) or line.get("sym")
        if isinstance(sym, dict) and sym.get("speedup"):
            if float(sym["speedup"]) > entry.get("sym_best", 0.0):
                entry["sym_best"] = float(sym["speedup"])
                entry["sym_best_file"] = os.path.basename(path)
    return out


def normalize_fresh(doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """One shape for the two fresh sources: a primary stdout line
    ({"metric", "value", ...}) or a ``bench_detail.json``. Returns
    {platform, value, count_ok, resumed, lint_ok, full_coverage} or None
    when the document is neither."""
    if "metric" in doc and "value" in doc:
        return {
            "platform": _platform_of(doc["metric"]),
            "value": float(doc["value"]),
            "count_ok": doc.get("count_ok"),
            "resumed": doc.get("resumed"),
            "lint_ok": doc.get("lint_ok"),
            "fleet": doc.get("fleet"),
            "mux": doc.get("mux"),
            "sym": doc.get("sym"),
            "full_coverage": doc.get("count_ok") is not None,
            "metric": doc["metric"],
        }
    if "states_per_sec" in doc:
        resume = doc.get("resume") or {}
        return {
            "platform": doc.get("platform", "unknown"),
            "value": float(doc["states_per_sec"]),
            "count_ok": doc.get("count_ok"),
            "resumed": resume.get("phase"),
            "lint_ok": doc.get("lint_ok"),
            "fleet": doc.get("fleet"),
            "mux": doc.get("mux"),
            "sym": doc.get("sym"),
            "full_coverage": doc.get("full_coverage"),
            "metric": f"bench_detail rm={doc.get('rm')}",
        }
    return None


def _check(name: str, verdict: str, detail: str, **extra: Any) -> Dict[str, Any]:
    return {"name": name, "verdict": verdict, "detail": detail, **extra}


def judge(
    fresh: Dict[str, Any],
    trajectory: Dict[str, Dict[str, Any]],
    chaos: Optional[Dict[str, Any]],
    *,
    tolerance: float = DEFAULT_TOLERANCE,
    admission_p99_ms: float = DEFAULT_ADMISSION_P99_MS,
    turnaround_p99_s: float = DEFAULT_TURNAROUND_P99_S,
) -> Dict[str, Any]:
    """The pure verdict (no I/O): check list + overall verdict."""
    checks: List[Dict[str, Any]] = []
    platform = fresh["platform"]
    base = trajectory.get(platform)

    # -- throughput vs the platform's archived best -----------------------
    if not trajectory:
        pass  # overall no_baseline below; no throughput check to run
    elif base is None:
        checks.append(
            _check(
                "throughput", "skip",
                f"no archived {platform} line yet (archive covers "
                f"{sorted(trajectory)}); banking this one starts the "
                "trajectory",
            )
        )
    elif fresh.get("resumed"):
        checks.append(
            _check(
                "throughput", "skip",
                f"fresh line resumed from a {fresh['resumed']!r} checkpoint "
                "— it measures the tail of the space, not a cold full "
                "pass; not comparable",
            )
        )
    elif (fresh.get("fleet") or {}).get("migrations"):
        fleet = fresh["fleet"]
        checks.append(
            _check(
                "throughput", "skip",
                f"fleet provenance records {fleet['migrations']} "
                f"cross-device migration(s) over {fleet.get('devices')} "
                "device(s) — throughput measured amid failover "
                "evacuations judges the chaos harness, not the engine; "
                "not comparable",
            )
        )
    else:
        floor = base["best"] * (1.0 - tolerance)
        ok = fresh["value"] >= floor
        checks.append(
            _check(
                "throughput", "pass" if ok else "fail",
                f"{fresh['value']:,.0f} gen/s vs {platform} best "
                f"{base['best']:,.0f} ({base.get('best_file')}); floor "
                f"{floor:,.0f} at tolerance {tolerance}",
                value=fresh["value"], baseline=base["best"], floor=round(floor, 1),
            )
        )

    # -- exactness / provenance -------------------------------------------
    count_ok = fresh.get("count_ok")
    if count_ok is None:
        checks.append(
            _check(
                "count_ok", "skip",
                "no exact-count verdict (partial coverage or unpinned rm)",
            )
        )
    else:
        checks.append(
            _check(
                "count_ok", "pass" if count_ok else "fail",
                "exact-count contract "
                + ("holds" if count_ok else "VIOLATED on this platform"),
            )
        )
    lint_ok = fresh.get("lint_ok")
    if lint_ok is None:
        checks.append(
            _check("lint_ok", "skip", "no fresh stpu-lint artifact")
        )
    else:
        checks.append(
            _check(
                "lint_ok", "pass" if lint_ok else "fail",
                "stpu-lint " + ("clean" if lint_ok else "has unwaived findings"),
            )
        )

    # -- batched-scheduling throughput (BENCH_MUX) -------------------------
    mux = fresh.get("mux")
    if isinstance(mux, dict):
        if mux.get("error") or mux.get("jobs_failed"):
            checks.append(
                _check(
                    "mux", "fail",
                    "mux throughput probe "
                    + (f"errored: {mux['error']}" if mux.get("error") else
                       f"lost {mux['jobs_failed']} of {mux.get('k')} jobs"),
                )
            )
        elif base is None or not base.get("mux_best"):
            checks.append(
                _check(
                    "mux", "skip",
                    f"no archived {platform} mux baseline yet "
                    f"({mux.get('jobs_per_sec')} jobs/s at k={mux.get('k')}, "
                    f"{mux.get('dispatches_per_job')} dispatches/job); "
                    "banking this one starts the trajectory",
                )
            )
        else:
            floor = base["mux_best"] * (1.0 - tolerance)
            ok = float(mux.get("jobs_per_sec", 0.0)) >= floor
            checks.append(
                _check(
                    "mux", "pass" if ok else "fail",
                    f"{mux.get('jobs_per_sec')} jobs/s at k={mux.get('k')} "
                    f"vs {platform} mux best {base['mux_best']} "
                    f"({base.get('mux_best_file')}); floor {floor:.3f} at "
                    f"tolerance {tolerance}",
                    value=mux.get("jobs_per_sec"), baseline=base["mux_best"],
                    floor=round(floor, 3),
                )
            )
    # No "skip" row when the probe never ran: the mux mode is an env
    # opt-in (BENCH_MUX), not a default stage of every bench.

    # -- symmetry-reduction A/B (BENCH_SYM) --------------------------------
    sym = fresh.get("sym")
    if isinstance(sym, dict):
        audit = sym.get("audit") or {}
        if sym.get("error") or audit.get("ok") is False:
            checks.append(
                _check(
                    "sym", "fail",
                    "sym A/B probe "
                    + (f"errored: {sym['error']}" if sym.get("error") else
                       f"failed the reduced-run audit: {audit}"),
                )
            )
        elif base is None or not base.get("sym_best"):
            checks.append(
                _check(
                    "sym", "skip",
                    f"no archived {platform} sym baseline yet "
                    f"({sym.get('spec')}: {sym.get('unique_full')} -> "
                    f"{sym.get('unique_reduced')} uniques, speedup "
                    f"{sym.get('speedup')}); banking this one starts the "
                    "trajectory",
                )
            )
        else:
            floor = base["sym_best"] * (1.0 - tolerance)
            ok = float(sym.get("speedup", 0.0)) >= floor
            checks.append(
                _check(
                    "sym", "pass" if ok else "fail",
                    f"speedup {sym.get('speedup')} on {sym.get('spec')} "
                    f"({sym.get('unique_full')} -> "
                    f"{sym.get('unique_reduced')} uniques) vs {platform} "
                    f"sym best {base['sym_best']} "
                    f"({base.get('sym_best_file')}); floor {floor:.3f} at "
                    f"tolerance {tolerance}",
                    value=sym.get("speedup"), baseline=base["sym_best"],
                    floor=round(floor, 3),
                )
            )
    # Same opt-in contract as mux: no row when BENCH_SYM never ran.

    # -- chaos SLO line ----------------------------------------------------
    if chaos is None:
        checks.append(
            _check(
                "slo", "skip",
                "no runs/service_chaos.json (run tools/service_chaos.py)",
            )
        )
    else:
        if not chaos.get("ok", False):
            checks.append(
                _check("slo", "fail", "chaos sweep itself failed (ok: false)")
            )
        else:
            slo_fail = []
            slo_detail = []
            for scen, rep in (chaos.get("scenarios") or {}).items():
                adm = (rep.get("admission_latency_ms") or {}).get("p99")
                turn = (rep.get("turnaround_s") or {}).get("p99")
                if adm is not None:
                    slo_detail.append(f"{scen}: admission p99 {adm}ms")
                    if adm > admission_p99_ms:
                        slo_fail.append(
                            f"{scen} admission p99 {adm}ms > {admission_p99_ms}ms"
                        )
                if turn is not None:
                    slo_detail.append(f"{scen}: turnaround p99 {turn}s")
                    if turn > turnaround_p99_s:
                        slo_fail.append(
                            f"{scen} turnaround p99 {turn}s > {turnaround_p99_s}s"
                        )
                # Per-class SLO gate (ISSUE 18): present only on
                # QoS-era chaos lines — each class's p99s ride under
                # the same ceilings, and an inverted pair (interactive
                # p99 at or above best_effort's) is a scheduling
                # regression in its own right.
                classes = rep.get("classes")
                if isinstance(classes, dict):
                    for cls, crow in sorted(classes.items()):
                        cturn = ((crow or {}).get("turnaround_s")
                                 or {}).get("p99")
                        if cturn is None:
                            continue
                        slo_detail.append(
                            f"{scen}/{cls}: turnaround p99 {cturn}s"
                        )
                        if cturn > turnaround_p99_s:
                            slo_fail.append(
                                f"{scen} {cls} turnaround p99 {cturn}s"
                                f" > {turnaround_p99_s}s"
                            )
                    if rep.get("priority_inversion"):
                        # The harness only fails the scenario when both
                        # classes had enough samples; surface the
                        # low-sample case as detail, not a gate fail.
                        slo_detail.append(
                            f"{scen}: priority_inversion flagged"
                        )
            if not slo_detail:
                checks.append(
                    _check("slo", "skip", "chaos line carries no percentiles")
                )
            else:
                checks.append(
                    _check(
                        "slo", "fail" if slo_fail else "pass",
                        "; ".join(slo_fail or slo_detail),
                    )
                )

    # Failure wins over no_baseline: a missing archive only excuses the
    # throughput comparison — a count_ok/lint_ok/SLO failure must never
    # ride out of the gate under a "no_baseline" exit 0.
    if any(c["verdict"] == "fail" for c in checks):
        verdict = "fail"
    elif not trajectory:
        verdict = "no_baseline"
    else:
        verdict = "pass"
    return {
        "tool": "bench_regress",
        "verdict": verdict,
        "platform": platform,
        "fresh": {k: fresh.get(k) for k in
                  ("metric", "value", "count_ok", "resumed", "lint_ok",
                   "fleet", "mux")},
        "baseline": base,
        "platforms_archived": sorted(trajectory),
        "tolerances": {
            "throughput": tolerance,
            "admission_p99_ms": admission_p99_ms,
            "turnaround_p99_s": turnaround_p99_s,
        },
        "checks": checks,
    }


def _load_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def _emit(line: Dict[str, Any], out_path: Optional[str]) -> None:
    print(json.dumps(line))
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = f"{out_path}.tmp-{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(line, fh, indent=1)
        os.replace(tmp, out_path)


def self_test(args) -> int:
    """The gate judging its own three verdicts against the REAL archive:
    the newest archived line must pass, a synthetically degraded copy
    must fail, an empty archive must report no_baseline. The smoke-stage
    form (tools/smoke.sh) — no jax, no device, <5 s."""
    trajectory = load_trajectory(args.archive)
    cases: Dict[str, Any] = {}
    ok = True
    if not trajectory:
        cases["archive"] = "no parseable BENCH_r*.json under " + args.archive
        ok = False
    else:
        # Newest real line per the best platform = a known-good fresh line.
        platform = sorted(trajectory)[0]
        base = trajectory[platform]
        real = {
            "metric": base["best_metric"],
            "value": base["best"],
            "count_ok": True,
        }
        v = judge(normalize_fresh(real), trajectory, None,
                  tolerance=args.tolerance)["verdict"]
        cases["real_line"] = v
        ok &= v == "pass"
        degraded = dict(real, value=base["best"] * 0.1)
        v = judge(normalize_fresh(degraded), trajectory, None,
                  tolerance=args.tolerance)["verdict"]
        cases["degraded_line"] = v
        ok &= v == "fail"
    with tempfile.TemporaryDirectory() as empty:
        v = judge(
            normalize_fresh({"metric": "x, cpu", "value": 1.0}),
            load_trajectory(empty), None,
        )["verdict"]
        cases["empty_archive"] = v
        ok &= v == "no_baseline"
    print(json.dumps({"tool": "bench_regress", "self_test": True,
                      "ok": bool(ok), "cases": cases}))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--archive", default=DEFAULT_ARCHIVE,
                   help="dir of BENCH_r*.json trajectory files")
    p.add_argument("--fresh", default=DEFAULT_FRESH,
                   help="fresh line: bench_detail.json or a primary-line JSON")
    p.add_argument("--chaos", default=DEFAULT_CHAOS,
                   help="service_chaos SLO line (skipped when missing)")
    p.add_argument("--out", default=DEFAULT_OUT,
                   help="verdict JSON destination ('' disables)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE)
    p.add_argument("--admission-p99-ms", type=float,
                   default=DEFAULT_ADMISSION_P99_MS)
    p.add_argument("--turnaround-p99-s", type=float,
                   default=DEFAULT_TURNAROUND_P99_S)
    p.add_argument("--self-test", action="store_true")
    args = p.parse_args(argv)

    if args.self_test:
        return self_test(args)

    doc = _load_json(args.fresh)
    fresh = normalize_fresh(doc) if doc else None
    if fresh is None:
        _emit(
            {
                "tool": "bench_regress",
                "verdict": "error",
                "error": f"no readable fresh line at {args.fresh} "
                         "(run python bench.py first, or pass --fresh)",
            },
            args.out or None,
        )
        return 2
    line = judge(
        fresh,
        load_trajectory(args.archive),
        _load_json(args.chaos),
        tolerance=args.tolerance,
        admission_p99_ms=args.admission_p99_ms,
        turnaround_p99_s=args.turnaround_p99_s,
    )
    _emit(line, args.out or None)
    return 0 if line["verdict"] in ("pass", "no_baseline") else 1


if __name__ == "__main__":
    sys.exit(main())
