#!/usr/bin/env python
"""Chaos/load harness for the durable CheckerService (ROADMAP item 3c).

Drives ONE pool — run in a killable child process — through a seeded
schedule of concurrent submissions, injected faults
(``stateright_tpu/chaos.py``), service SIGKILLs, and restarts over the
same run dir, then asserts the invariant that matters:

    every admitted job eventually completes EXACTLY ONCE, with
    generated/unique/discovery counts bit-identical to an undisturbed
    run of the same schedule.

and reports SLO-style measurements — admission latency, Retry-After
accuracy, p50/p99 job turnaround — as one JSON line on stdout, banked
atomically at ``runs/service_chaos.json`` (bench.py folds it into
``bench_detail.json`` as ``journal`` provenance).

Scenarios (``--scenario``):

- ``baseline``  — undisturbed run; its per-spec counts are the ground
  truth the others compare against (it always runs first).
- ``kill``      — SIGKILL the service's process group at a seeded
  wall-clock point, restart over the same run dir (blindly resubmitting
  the whole schedule under the same idempotency keys — the restart
  loop's contract), repeat up to ``--max-restarts``, final pass clean.
- ``die``       — deterministic crash: the first incarnation carries
  ``journal.die@n=K`` (SIGKILL itself right after the K-th journal
  record), so the restart drill is bit-reproducible.
- ``torn``      — like ``die`` but ``journal.torn@n=K``: the crash
  happens MID-append, leaving a torn journal tail the restart must
  recover from (typed, minus the torn record).
- ``device_lost`` (fleet runs, ``--fleet N``) — ``device.lost@n=K``
  kills ONE device's pool mid-schedule: its jobs must migrate and
  complete exactly once on surviving devices, bit-identical to the
  undisturbed baseline (ISSUE 15 acceptance).
- ``mux`` (``--mux K``) — SIGKILL the MULTIPLEXED worker mid-batch
  (ISSUE 16): K same-spec jobs through a ``mux_k=K`` pool, one member
  carrying a seeded ``worker.die`` lane sabotage that kills the shared
  group process; every member must requeue solo, resume from its own
  lane checkpoints, and complete exactly once — bit-identical to a
  mux-OFF baseline of the same schedule (the batched path proves itself
  against the solo engine, not merely against itself). Self-contained:
  it builds its own same-spec schedule and solo baseline.
- ``all``       — baseline + kill + torn (+ device_lost when --fleet,
  + mux when --mux) (the acceptance sweep).

Fleet mode (``--fleet N``): the serve child fronts N per-device pools
through :class:`FleetService` behind the SAME submit/wait_all surface;
the SLO line gains a ``fleet`` dict — device count, migrations,
fleet-level Retry-After accuracy, and p50/p99 turnaround PER DEVICE
(ROADMAP 3(c')). ``--sessions N`` adds N concurrent interactive Explorer
sessions (admission-capped through the real ``register_interactive``
path, polling the real ``ExplorerApp.status()`` handler) alongside the
batch schedule; their admission verdicts and status-poll latencies land
in the ``sessions`` dict.

Everything the parent does is jax-free; model work happens in the
service's worker subprocesses (CPU-pinned via ``ServiceConfig
(platform="cpu")`` by default — the sitecustomize gotcha means a bare
``JAX_PLATFORMS=cpu`` env cannot, see CLAUDE.md).

Reproducibility: the fault schedule (submission order/delays, kill
point, torn/die record index) is a pure function of ``--seed``.
``--check-repro`` runs the schedule twice serially (``max_inflight=1``)
through fresh run dirs and diffs the two journals' event sequences
(event names + job ids, timestamps and pids masked) — same seed, same
sequence.

Usage::

    python tools/service_chaos.py --seed 42                # all scenarios
    python tools/service_chaos.py --seed 7 --scenario kill --jobs 3
    python tools/service_chaos.py --seed 7 --scenario mux --mux 4
    python tools/service_chaos.py --seed 7 --check-repro

``tools/tpu_watch.sh service_chaos`` is the watcher stage alias; the
<30s restart drill in ``tools/smoke.sh`` and the <60s chaos pins in
``tests/test_service_durability.py`` drive these scenarios through the
same entry points.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
RUNS = os.path.join(REPO, "runs")

#: The schedule's spec pool: tiny shipped models (seconds per worker on
#: CPU with a warm compile cache) with exact full-coverage counts.
SPEC_POOL = ("2pc:3", "increment-lock:3", "abd:2")

RESULT_KEYS = ("generated", "unique", "max_depth", "discoveries")


def log(msg: str) -> None:
    print(f"[service_chaos] {msg}", file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# Seeded schedule
# --------------------------------------------------------------------------


def build_schedule(
    seed: int, jobs: int, max_seconds: float, tenants: int = 0
) -> Dict[str, Any]:
    """The seeded submission schedule: pure function of
    (seed, jobs, tenants). With ``tenants`` > 0 every entry carries a
    seeded tenant id and priority class (mixed interactive/batch/
    best_effort traffic; interactive entries get deadlines) — the QoS
    tier's load shape (ISSUE 18)."""
    import random

    rng = random.Random(seed)
    entries = []
    for i in range(jobs):
        entry = {
            "idem": f"chaos-{seed}-{i}",
            "spec": rng.choice(SPEC_POOL),
            "delay_s": round(rng.uniform(0.0, 1.5), 3),
            "max_seconds": max_seconds,
        }
        if tenants:
            entry["tenant"] = f"t{rng.randrange(tenants)}"
            draw = rng.random()
            if draw < 0.3:
                entry["priority"] = "interactive"
                entry["deadline_s"] = round(rng.uniform(60.0, 180.0), 3)
            elif draw < 0.7:
                entry["priority"] = "batch"
            else:
                entry["priority"] = "best_effort"
        entries.append(entry)
    return {"seed": seed, "tenants": tenants or None, "jobs": entries}


def fault_plan(seed: int, scenario: str) -> Dict[str, Any]:
    """The seeded fault schedule for one scenario (reported in the SLO
    line so a rerun is auditable). crc32, not hash(): the builtin is
    PYTHONHASHSEED-randomized per process, which would silently break
    the cross-run reproducibility this function promises."""
    import random
    import zlib

    rng = random.Random((seed << 8) ^ zlib.crc32(scenario.encode()))
    if scenario == "kill":
        return {"kill_after_s": round(rng.uniform(2.0, 9.0), 3)}
    if scenario == "die":
        return {"die_at_record": rng.randint(3, 10)}
    if scenario == "torn":
        return {"torn_at_record": rng.randint(3, 10)}
    if scenario == "device_lost":
        # Which routing decision arms the loss, and how long after it
        # the device dies (mid-job for any spec in the pool).
        return {
            "lost_at_route": rng.randint(1, 2),
            "lost_after_s": round(rng.uniform(1.0, 4.0), 3),
        }
    if scenario == "storm":
        # Which scheduled submission triggers the tenant storm, the
        # burst size, and the mid-storm SIGKILL point (ISSUE 18
        # acceptance: kill + restart with the storm in flight).
        return {
            "storm_at_submit": rng.randint(1, 2),
            "storm_rate": rng.randint(4, 8),
            "kill_after_s": round(rng.uniform(2.0, 9.0), 3),
        }
    return {}


# --------------------------------------------------------------------------
# Serve mode: one service incarnation in THIS process (run as a child)
# --------------------------------------------------------------------------


def serve(args: argparse.Namespace) -> int:
    """One service incarnation: recover (if the run dir has a journal),
    resubmit the whole schedule idempotently, wait for every job, write
    driver_results.json. Killable at any instant — that is the point.
    With ``--fleet N`` the incarnation fronts N per-device pools through
    FleetService behind the same surface."""
    from stateright_tpu.service import (
        CheckerService,
        FleetConfig,
        FleetService,
        ServiceConfig,
    )

    with open(args.schedule) as fh:
        schedule = json.load(fh)
    cfg = ServiceConfig(
        run_dir=args.run_dir,
        platform="cpu",
        # Batched scheduling (ISSUE 16): the mux scenario's incarnations
        # run the pool with mux_k=K so same-spec members fold into one
        # worker.py --mux group.
        mux_k=args.mux or None,
        max_inflight=args.max_inflight,
        max_queue=max(8, len(schedule["jobs"]) + 2),
        # Every restart recovery compacts once (one rotation per
        # incarnation); the exactly-once audit (check_invariant) reads
        # the FULL event history across rotations, so the keep bound
        # must out-last the restart loop (max_restarts <= 4) or early
        # incarnations' completed events would rotate away and read as
        # false invariant failures.
        journal_keep=12,
        stall_s=8.0,
        startup_grace_s=240.0,
        poll_s=0.2,
        backoff_s=0.1,
        probe_auto=False,
        admission_lint=False,
        chaos=args.chaos or None,
    )
    if args.fleet:
        svc = FleetService(FleetConfig(
            run_dir=args.run_dir,
            devices=args.fleet,
            monitor_interval_s=0.3,
            journal_keep=12,
            chaos=args.chaos or None,
            # The pool template: per-device run dirs/devices/halt mode
            # are overwritten per pool; the chaos plan installs ONCE at
            # the fleet level.
            pool=dataclasses.replace(cfg, chaos=None),
        ))
    else:
        svc = CheckerService(cfg)
    svc.log = log
    sessions = (
        _session_swarm(svc, args.sessions, args.run_dir)
        if args.sessions
        else None
    )
    stats_path = os.path.join(args.run_dir, "admission_stats.jsonl")
    t0 = time.monotonic()
    jobs = []
    with open(stats_path, "a") as stats:
        for entry in schedule["jobs"]:
            delay = entry["delay_s"] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            t = time.monotonic()
            job, retries = _submit_with_retry(svc, entry)
            stats.write(
                json.dumps(
                    {
                        "idem": entry["idem"],
                        "job": job.id,
                        "latency_ms": round(
                            (time.monotonic() - t) * 1e3, 3
                        ),
                        "deduped": job.recovered,
                        "priority": entry.get("priority"),
                        "tenant": entry.get("tenant"),
                        "admission_retries": retries,
                    }
                )
                + "\n"
            )
            stats.flush()
            jobs.append((entry, job))
            # Seeded tenant storm (chaos point tenant.storm, ISSUE 18):
            # fires per scheduled submission; admitted burst members
            # join the waited set (exactly-once audited), shed members
            # record their typed rejection + hint.
            storm = _chaos_fire("tenant.storm")
            if storm is not None:
                _storm_burst(svc, schedule, storm, stats, jobs)
    retry_stats = (
        _overload_probe(svc, schedule) if args.overload else None
    )
    if not svc.wait_all(timeout=args.wait_s):
        log(f"wait_all timed out after {args.wait_s}s: {svc.gauges()}")
        if sessions is not None:
            # Stop the swarm BEFORE teardown: its threads must not race
            # a closing service, and the aggregate stats row flushes so
            # the timed-out incarnation still reports its sessions SLO.
            sessions.stop()
        svc.close()
        return 4
    session_stats = sessions.stop() if sessions is not None else None
    out = {
        "jobs": {
            entry["idem"]: {
                "id": job.id,
                "spec": entry["spec"],
                "status": job.status,
                "error": job.error,
                "recovered": job.recovered,
                "requeues": job.requeues,
                "result": (
                    {k: job.result.get(k) for k in RESULT_KEYS}
                    if job.result
                    else None
                ),
            }
            for entry, job in jobs
        },
        "gauges": svc.gauges(),
        "retry_after": retry_stats,
        "sessions": session_stats,
    }
    svc.close()
    tmp = os.path.join(args.run_dir, "driver_results.json.tmp")
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, os.path.join(args.run_dir, "driver_results.json"))
    return 0


def _chaos_fire(point: str):
    from stateright_tpu import chaos as chaos_mod

    return chaos_mod.fire(point)


def _submit_with_retry(svc, entry: Dict[str, Any], max_tries: int = 30):
    """Submit one scheduled entry, honoring typed Retry-After rejections
    (shedding under a storm is the QoS tier WORKING — the scheduled set
    still has to land eventually for the exactly-once audit). Returns
    (job, retries). A hint-less rejection (budget/lint) re-raises:
    retrying it would fail identically."""
    from stateright_tpu.service import AdmissionError

    tries = 0
    while True:
        try:
            return svc.submit(
                entry["spec"],
                max_seconds=entry["max_seconds"],
                idempotency_key=entry["idem"],
                # Per-job worker sabotage (the mux scenario arms its
                # members directly; absent everywhere else).
                chaos=entry.get("chaos"),
                tenant=entry.get("tenant", "default"),
                priority=entry.get("priority", "batch"),
                deadline_s=entry.get("deadline_s"),
            ), tries
        except AdmissionError as e:
            tries += 1
            if e.retry_after_s is None or tries >= max_tries:
                raise
            time.sleep(min(e.retry_after_s, 5.0))


def _storm_burst(svc, schedule, storm, stats, jobs) -> None:
    """One fired ``tenant.storm``: burst ``rate`` same-tenant
    submissions in one class through the live service. Deterministic
    idempotency keys make a restarted incarnation's re-fired storm
    dedupe onto the journal-replayed jobs instead of double-submitting."""
    from stateright_tpu.service import AdmissionError

    rate = int(storm.get("rate", 5))
    tenant = str(storm.get("tenant", "storm"))
    priority = str(storm.get("class", "best_effort"))
    first = schedule["jobs"][0]
    seed = schedule.get("seed", 0)
    for s in range(rate):
        idem = f"storm-{seed}-{s}"
        t = time.monotonic()
        row: Dict[str, Any] = {
            "idem": idem, "tenant": tenant, "priority": priority,
            "storm": True,
        }
        try:
            job = svc.submit(
                first["spec"],
                max_seconds=first["max_seconds"],
                idempotency_key=idem,
                tenant=tenant,
                priority=priority,
            )
            row.update(
                job=job.id,
                latency_ms=round((time.monotonic() - t) * 1e3, 3),
                deduped=job.recovered,
            )
            jobs.append(({"idem": idem, "spec": first["spec"]}, job))
        except AdmissionError as e:
            row.update(
                shed=True, reason=e.reason, retry_after_s=e.retry_after_s
            )
        stats.write(json.dumps(row) + "\n")
        stats.flush()


class _SessionChecker:
    """A jax-free stand-in for an interactive checker: just enough
    surface for ``register_interactive`` + ``ExplorerApp.status()`` —
    the load swarm measures the SERVICE's admission/status path, not an
    engine (the serve child must stay jax-free and killable in <1s)."""

    class _Model:
        def properties(self):
            return []

    def model(self):
        return self._Model()

    def is_done(self):
        return False

    def state_count(self):
        return 0

    def unique_state_count(self):
        return 0

    def max_depth(self):
        return 0

    def discoveries(self):
        return {}

    def metrics(self):
        return {"engine": "session", "job_id": getattr(self, "job_id", None)}

    def attach_job(self, job_id):
        self.job_id = job_id


class _SessionSwarm:
    """N concurrent interactive sessions (ROADMAP 3(c')): each thread
    registers through the real admission path (``AdmissionError`` past
    the cap counts as a rejection, retried after a backoff) and polls
    the real ``ExplorerApp.status()`` handler until stopped. Stats are
    appended live to ``session_stats.jsonl`` so a SIGKILL loses
    nothing."""

    def __init__(self, svc, n: int, run_dir: str):
        self._svc = svc
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.admitted = 0
        self.rejected = 0
        self.polls = 0
        self.poll_ms: List[float] = []
        self._path = os.path.join(run_dir, "session_stats.jsonl")
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True)
            for i in range(n)
        ]
        for t in self._threads:
            t.start()

    def _run(self, i: int) -> None:
        from stateright_tpu.checker.explorer import ExplorerApp
        from stateright_tpu.service import AdmissionError

        while not self._stop.is_set():
            checker = _SessionChecker()
            try:
                job = self._svc.register_interactive(
                    checker, label=f"session-{i}"
                )
            except AdmissionError:
                with self._lock:
                    self.rejected += 1
                self._stop.wait(0.5)
                continue
            except RuntimeError:
                return  # service closed
            with self._lock:
                self.admitted += 1
            app = ExplorerApp(checker, service=self._svc, job=job)
            try:
                # Poll /.status (the handler itself, no socket) for a
                # while, then release the slot so capped siblings admit.
                for _ in range(20):
                    if self._stop.is_set():
                        break
                    t = time.monotonic()
                    app.status()
                    with self._lock:
                        self.polls += 1
                        self.poll_ms.append(
                            round((time.monotonic() - t) * 1e3, 3)
                        )
                    self._stop.wait(0.1)
            finally:
                app.close()
                # Live append: each session lifecycle flushes the
                # running aggregate, so a SIGKILLed incarnation's last
                # row still carries (nearly) everything it measured.
                self._append(self._row())

    def _row(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "sessions": len(self._threads),
                "admitted": self.admitted,
                "rejected": self.rejected,
                "status_polls": self.polls,
                "status_poll_ms": _percentiles(list(self.poll_ms)),
            }

    def _append(self, row: Dict[str, Any]) -> None:
        try:
            with open(self._path, "a") as fh:
                fh.write(json.dumps(row) + "\n")
        except OSError:
            pass

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10.0)
        stats = self._row()
        self._append(stats)
        return stats


def _session_swarm(svc, n: int, run_dir: str) -> _SessionSwarm:
    return _SessionSwarm(svc, n, run_dir)


def _overload_probe(svc, schedule) -> Dict[str, Any]:
    """Retry-After accuracy: push the queue past its cap, record the
    typed hint, retry after (a capped fraction of) it — ``accurate``
    counts hints that were sufficient. Probed per class: the
    ``best_effort`` burst hits the QoS tier's shed threshold first
    (ISSUE 18), so its hint is the measured-drain Retry-After the
    shedding path computes; the ``batch`` burst reproduces the legacy
    queue-pressure path. Legacy top-level keys mirror the batch row."""
    from stateright_tpu.service import AdmissionError

    spec = schedule["jobs"][0]["spec"]
    max_seconds = schedule["jobs"][0]["max_seconds"]
    # Queue capacity: the pool cap, or (fleet) the per-device cap summed
    # — the burst must out-size whatever can absorb it.
    cap = getattr(svc._cfg, "max_queue", None)
    if cap is None:
        cap = sum(p._cfg.max_queue for p in svc.pools)
    out: Dict[str, Any] = {"classes": {}}
    for cls in ("best_effort", "batch"):
        observed = accurate = 0
        hints: List[float] = []
        shed = False
        for i in range(cap + 2):
            try:
                svc.submit(spec, max_seconds=max_seconds, priority=cls)
            except AdmissionError as e:
                if e.retry_after_s is None:
                    continue
                observed += 1
                hints.append(e.retry_after_s)
                shed = "shedding" in (e.reason or "")
                time.sleep(min(e.retry_after_s, 15.0))
                try:
                    svc.submit(
                        spec, max_seconds=max_seconds, priority=cls
                    )
                    accurate += 1
                except AdmissionError:
                    pass
                break
        out["classes"][cls] = {
            "observed": observed, "accurate": accurate,
            "hints_s": hints, "shed": shed,
        }
    out.update(
        observed=out["classes"]["batch"]["observed"],
        accurate=out["classes"]["batch"]["accurate"],
        hints_s=out["classes"]["batch"]["hints_s"],
    )
    return out


# --------------------------------------------------------------------------
# Parent: incarnation driver + invariant checks
# --------------------------------------------------------------------------


def run_incarnation(
    run_dir: str,
    schedule_path: str,
    *,
    kill_after_s: Optional[float] = None,
    chaos: Optional[str] = None,
    max_inflight: int = 2,
    overload: bool = False,
    wait_s: float = 300.0,
    fleet: int = 0,
    sessions: int = 0,
    mux: int = 0,
) -> int:
    """Spawn one ``--serve`` child (its own process group) and either let
    it finish or SIGKILL the whole group after ``kill_after_s`` — the
    harness's service-crash primitive. Returns the child's rc, or -9."""
    argv = [
        sys.executable, os.path.abspath(__file__), "--serve",
        "--run-dir", run_dir, "--schedule", schedule_path,
        "--max-inflight", str(max_inflight),
        "--wait-s", str(wait_s),
    ]
    if fleet:
        argv += ["--fleet", str(fleet)]
    if mux:
        argv += ["--mux", str(mux)]
    if sessions:
        argv += ["--sessions", str(sessions)]
    if chaos:
        argv += ["--chaos", chaos]
    if overload:
        argv += ["--overload"]
    proc = subprocess.Popen(argv, start_new_session=True)
    if kill_after_s is None:
        try:
            return proc.wait(timeout=wait_s + 60.0)
        except subprocess.TimeoutExpired:
            log(f"incarnation overran {wait_s + 60.0:.0f}s; killing group")
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                proc.kill()
            proc.wait(timeout=10.0)
            return 124
    try:
        rc = proc.wait(timeout=kill_after_s)
        return rc  # finished before the kill point
    except subprocess.TimeoutExpired:
        pass
    log(f"SIGKILL service incarnation (pid {proc.pid})")
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except OSError:
        proc.kill()
    proc.wait(timeout=10.0)
    return -9


def _rotation_chain(base: str) -> List[Dict[str, Any]]:
    from stateright_tpu.service import read_journal

    paths = []
    i = 1
    while os.path.exists(f"{base}.{i}"):
        paths.append(f"{base}.{i}")
        i += 1
    paths.reverse()
    if os.path.exists(base):
        paths.append(base)
    records: List[Dict[str, Any]] = []
    for p in paths:
        records.extend(read_journal(p).records)
    return records


def journal_history(run_dir: str) -> List[Dict[str, Any]]:
    """Every POOL journal record across the compaction rotations, oldest
    first — each event appears exactly once (compaction rewrites the
    live log as a snapshot; rotations keep the raw history). Fleet runs
    concatenate every device's journal, each record tagged ``_device``
    (pool job ids collide across devices — "job-0001" exists on each)."""
    single = _rotation_chain(os.path.join(run_dir, "journal.jsonl"))
    if single:
        return single
    records: List[Dict[str, Any]] = []
    for device in sorted(
        d for d in os.listdir(run_dir) if d.startswith("device-")
    ) if os.path.isdir(run_dir) else []:
        for rec in _rotation_chain(
            os.path.join(run_dir, device, "journal.jsonl")
        ):
            rec = dict(rec, _device=device)
            records.append(rec)
    return records


def fleet_journal(run_dir: str) -> List[Dict[str, Any]]:
    """The fleet's own routing journal (``fleet.jsonl`` rotations),
    oldest first; empty for single-pool runs."""
    return _rotation_chain(os.path.join(run_dir, "fleet.jsonl"))


def _is_fleet(run_dir: str) -> bool:
    return os.path.exists(os.path.join(run_dir, "fleet.jsonl"))


def event_signature(records: List[Dict[str, Any]]) -> List[str]:
    """The reproducibility projection: event names + job ids, with
    timestamps/pids/digests/durations masked."""
    return [
        f"{r['event']}:{r.get('job', '-')}"
        for r in records
        if r["event"] not in ("snapshot", "recovered")
    ]


def check_invariant(
    run_dir: str, schedule: Dict[str, Any], reference: Optional[dict]
) -> Dict[str, Any]:
    """The acceptance invariant: every scheduled job present, done,
    completed exactly once across the whole journal history, counts
    bit-identical to the reference (per spec). Fleet runs key done
    events by (device, pool job) and resolve each fleet job's pool-job
    HISTORY through the routing journal — a migrated job must complete
    exactly once across ALL the devices it touched."""
    with open(os.path.join(run_dir, "driver_results.json")) as fh:
        results = json.load(fh)["jobs"]
    problems: List[str] = []
    history = journal_history(run_dir)
    fleet = _is_fleet(run_dir)
    done_events: Dict[str, int] = {}

    def key_of(rec):
        return (
            f"{rec['_device']}:{rec['job']}" if fleet else rec["job"]
        )

    for r in history:
        if r["event"] == "completed" and r.get("status") == "done":
            done_events[key_of(r)] = done_events.get(key_of(r), 0) + 1
    for jid, n in done_events.items():
        if n > 1:
            problems.append(f"{jid} completed done {n} times")
    # Fleet: fleet job id -> every (device, pool_job) it was ever routed
    # to (exactly one of them must have completed it).
    routes: Dict[str, List[str]] = {}
    if fleet:
        for r in fleet_journal(run_dir):
            if r["event"] == "routed":
                routes.setdefault(r["job"], []).append(
                    f"device-{r['device']}:{r['pool_job']}"
                )
            elif r["event"] == "migrated":
                routes.setdefault(r["job"], []).append(
                    f"device-{r['to_device']}:{r['pool_job']}"
                )
            elif r["event"] == "snapshot":
                for fid, route in r["state"].get("routes", {}).items():
                    routes.setdefault(fid, []).append(
                        f"device-{route['device']}:{route['pool_job']}"
                    )
    for entry in schedule["jobs"]:
        got = results.get(entry["idem"])
        if got is None:
            problems.append(f"{entry['idem']} missing from results")
            continue
        if got["status"] != "done":
            problems.append(
                f"{entry['idem']} status={got['status']} ({got['error']})"
            )
            continue
        if fleet:
            dones = sum(
                done_events.get(k, 0)
                for k in dict.fromkeys(routes.get(got["id"], []))
            )
        else:
            dones = done_events.get(got["id"], 0)
        if dones != 1:
            problems.append(
                f"{entry['idem']} ({got['id']}) has "
                f"{dones} done events in the journal"
            )
        if reference is not None:
            want = reference[entry["spec"]]
            have = got["result"]
            for key in RESULT_KEYS:
                if have.get(key) != want.get(key):
                    problems.append(
                        f"{entry['idem']} {key} {have.get(key)!r} != "
                        f"reference {want.get(key)!r}"
                    )
    return {
        "ok": not problems,
        "problems": problems,
        "journal_records": len(history),
    }


def _percentiles(values: List[float]) -> Optional[Dict[str, float]]:
    if not values:
        return None
    vs = sorted(values)

    def pct(p: float) -> float:
        return vs[min(len(vs) - 1, int(round(p * (len(vs) - 1))))]

    return {
        "p50": round(pct(0.50), 3),
        "p99": round(pct(0.99), 3),
        "max": round(vs[-1], 3),
        "n": len(vs),
    }


def slo_stats(run_dir: str) -> Dict[str, Any]:
    """Admission latency (appended live by every incarnation, so kills
    lose nothing) + per-job turnaround from the journal history. Fleet
    runs additionally report the ``fleet`` dict: device count,
    migrations/losses from the routing journal, per-DEVICE turnaround
    percentiles (ROADMAP 3(c')), and the session-swarm stats."""
    latencies: List[float] = []
    lat_by_class: Dict[str, List[float]] = {}
    sheds = 0
    stats_path = os.path.join(run_dir, "admission_stats.jsonl")
    if os.path.exists(stats_path):
        with open(stats_path) as fh:
            for line in fh:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if row.get("shed"):
                    sheds += 1
                    continue
                if "latency_ms" not in row:
                    continue
                latencies.append(row["latency_ms"])
                if row.get("priority"):
                    lat_by_class.setdefault(row["priority"], []).append(
                        row["latency_ms"]
                    )
    fleet = _is_fleet(run_dir)
    submitted: Dict[str, float] = {}
    priorities: Dict[str, str] = {}
    completed: Dict[str, float] = {}
    per_device: Dict[str, List[float]] = {}
    recovery = None
    for r in journal_history(run_dir):
        jid = r.get("job")
        key = f"{r['_device']}:{jid}" if fleet else jid
        if r["event"] == "submitted":
            submitted.setdefault(key, r["ts"])
            if "priority" in r:
                priorities[key] = r["priority"] or "batch"
        elif r["event"] == "completed" and r.get("status") == "done":
            completed[key] = r["ts"]
            # Same filter as the aggregate below: a job whose submitted
            # record rotated out of the keep-K chain must be skipped,
            # not counted as a spurious 0.0s turnaround.
            if fleet and key in submitted:
                per_device.setdefault(r["_device"], []).append(
                    r["ts"] - submitted[key]
                )
        elif r["event"] == "recovered":
            recovery = {
                k: r.get(k)
                for k in (
                    "records_replayed", "jobs_recovered", "jobs_requeued",
                    "jobs_readopted", "orphans_killed", "torn",
                )
            }
    turnaround = [
        completed[j] - submitted[j] for j in completed if j in submitted
    ]
    out = {
        "admission_latency_ms": _percentiles(latencies),
        "turnaround_s": _percentiles(turnaround),
        "journal": recovery,
    }
    # Per-class SLO split (ISSUE 18): present whenever the journal
    # carries priorities (every post-QoS run; pre-QoS journals skip it,
    # and bench_regress gates only when the dict exists).
    if priorities:
        by_class: Dict[str, List[float]] = {}
        for j in completed:
            if j in submitted:
                by_class.setdefault(
                    priorities.get(j, "batch"), []
                ).append(completed[j] - submitted[j])
        out["classes"] = {
            cls: {
                "turnaround_s": _percentiles(by_class.get(cls, [])),
                "admission_latency_ms": _percentiles(
                    lat_by_class.get(cls, [])
                ),
            }
            for cls in sorted(set(by_class) | set(lat_by_class))
        }
        out["sheds"] = sheds
    if fleet:
        froutes = fleet_journal(run_dir)
        devices = {
            d for d in os.listdir(run_dir)
            if d.startswith("device-")
            and os.path.isdir(os.path.join(run_dir, d))
        }
        sessions = None
        spath = os.path.join(run_dir, "session_stats.jsonl")
        if os.path.exists(spath):
            with open(spath) as fh:
                rows = [json.loads(l) for l in fh if l.strip()]
            if rows:
                sessions = rows[-1]
        out["fleet"] = {
            "devices": len(devices),
            "migrations": sum(
                1 for r in froutes if r["event"] == "migrated"
            ),
            "routed": sum(1 for r in froutes if r["event"] == "routed"),
            # Per-device p50/p99 turnaround: the ROADMAP 3(c') SLO split.
            "per_device": {
                d: _percentiles(v) for d, v in sorted(per_device.items())
            },
            "sessions": sessions,
        }
    return out


# --------------------------------------------------------------------------
# Scenarios
# --------------------------------------------------------------------------


def run_scenario(
    name: str,
    seed: int,
    schedule: Dict[str, Any],
    base_dir: str,
    *,
    reference: Optional[dict],
    max_inflight: int = 2,
    max_restarts: int = 4,
    overload: bool = False,
    wait_s: float = 300.0,
    fleet: int = 0,
    sessions: int = 0,
) -> Dict[str, Any]:
    """One scenario end to end; returns its report (and, for baseline,
    the reference counts the others compare against)."""
    run_dir = os.path.join(base_dir, name)
    os.makedirs(run_dir, exist_ok=True)
    schedule_path = os.path.join(run_dir, "schedule.json")
    with open(schedule_path, "w") as fh:
        json.dump(schedule, fh)
    faults = fault_plan(seed, name)
    t0 = time.monotonic()
    restarts = 0
    kw = dict(max_inflight=max_inflight, overload=overload, wait_s=wait_s,
              fleet=fleet, sessions=sessions)
    if name == "baseline":
        rc = run_incarnation(run_dir, schedule_path, **kw)
    elif name == "device_lost":
        if not fleet:
            raise ValueError("device_lost needs --fleet N")
        rc = run_incarnation(
            run_dir, schedule_path,
            chaos=(
                f"seed={seed};device.lost@n={faults['lost_at_route']}"
                f":after_s={faults['lost_after_s']}"
            ),
            **kw,
        )
        while rc != 0 and restarts < max_restarts:
            restarts += 1
            rc = run_incarnation(run_dir, schedule_path, **kw)
    elif name == "kill":
        rc = run_incarnation(
            run_dir, schedule_path,
            kill_after_s=faults["kill_after_s"], **kw,
        )
        while rc != 0 and restarts < max_restarts:
            restarts += 1
            rc = run_incarnation(run_dir, schedule_path, **kw)
    elif name == "storm":
        # Mid-storm SIGKILL + restart (ISSUE 18 acceptance): the storm
        # chaos rides EVERY incarnation — per-process fire counters make
        # the restarted storm re-fire at the same submission, and its
        # deterministic idempotency keys dedupe onto the replayed jobs.
        storm_chaos = (
            f"seed={seed};tenant.storm@n={faults['storm_at_submit']}"
            f":rate={faults['storm_rate']},class=best_effort"
        )
        rc = run_incarnation(
            run_dir, schedule_path,
            kill_after_s=faults["kill_after_s"],
            chaos=storm_chaos, **kw,
        )
        while rc != 0 and restarts < max_restarts:
            restarts += 1
            rc = run_incarnation(
                run_dir, schedule_path, chaos=storm_chaos, **kw
            )
    elif name in ("die", "torn"):
        point = "journal.die" if name == "die" else "journal.torn"
        n = faults.get("die_at_record") or faults.get("torn_at_record")
        rc = run_incarnation(
            run_dir, schedule_path,
            chaos=f"seed={seed};{point}@n={n}", **kw,
        )
        while rc != 0 and restarts < max_restarts:
            restarts += 1
            rc = run_incarnation(run_dir, schedule_path, **kw)
    else:
        raise ValueError(f"unknown scenario {name!r}")
    if rc != 0:
        return {
            "scenario": name, "ok": False, "rc": rc, "restarts": restarts,
            "problems": [f"final incarnation rc={rc}"], "faults": faults,
        }
    invariant = check_invariant(
        run_dir, schedule, None if name == "baseline" else reference
    )
    report = {
        "scenario": name,
        "ok": invariant["ok"],
        "problems": invariant["problems"],
        "faults": faults,
        "restarts": restarts,
        "elapsed_s": round(time.monotonic() - t0, 3),
        **slo_stats(run_dir),
    }
    if name == "device_lost":
        # The migration must actually have happened — a device_lost pass
        # that never killed a device proves nothing.
        migrations = (report.get("fleet") or {}).get("migrations", 0)
        if not migrations:
            report["ok"] = False
            report["problems"] = report["problems"] + [
                "device_lost scenario recorded no migrations"
            ]
    if name == "storm":
        # The storm must actually have fired (a pass with no burst
        # proves nothing), and classes must not invert: interactive p99
        # turnaround strictly better than best_effort's once both have
        # enough samples to make the comparison meaningful.
        stormed = sum(
            1 for r in journal_history(run_dir)
            if r["event"] == "submitted" and r.get("tenant") == "storm"
        )
        report["storm_submissions"] = stormed
        if not stormed:
            report["ok"] = False
            report["problems"] = report["problems"] + [
                "storm scenario journaled no storm-tenant submissions"
            ]
        classes = report.get("classes") or {}
        ip99 = ((classes.get("interactive") or {}).get("turnaround_s")
                or {}).get("p99")
        bp99 = ((classes.get("best_effort") or {}).get("turnaround_s")
                or {}).get("p99")
        i_n = ((classes.get("interactive") or {}).get("turnaround_s")
               or {}).get("n", 0)
        b_n = ((classes.get("best_effort") or {}).get("turnaround_s")
               or {}).get("n", 0)
        if ip99 is not None and bp99 is not None:
            report["priority_inversion"] = bool(ip99 >= bp99)
            if ip99 >= bp99 and min(i_n, b_n) >= 5:
                report["ok"] = False
                report["problems"] = report["problems"] + [
                    f"priority inversion: interactive p99 {ip99:.3f}s >= "
                    f"best_effort p99 {bp99:.3f}s"
                ]
    if overload:
        with open(os.path.join(run_dir, "driver_results.json")) as fh:
            report["retry_after"] = json.load(fh).get("retry_after")
    return report


def run_mux_scenario(
    seed: int,
    base_dir: str,
    k: int,
    *,
    max_seconds: float = 240.0,
    wait_s: float = 300.0,
    max_restarts: int = 4,
) -> Dict[str, Any]:
    """SIGKILL the multiplexed worker mid-batch (ISSUE 16). K same-spec
    jobs through a ``mux_k=K`` pool; EVERY member carries a per-job
    ``die_at_depth`` (marker-once, so each job sabotages exactly one
    attempt) — whichever members the scheduler batches, the first lane
    to reach the depth kills the SHARED group process. Pool-level
    ``worker.die`` can't guarantee that: the seeded victim may start
    solo before siblings arrive, and the kill then proves nothing about
    the batch path. The service must quarantine every member
    individually, retry them solo (resuming from their own lane
    checkpoint rotations), and converge to exactly-once — counts
    bit-identical to a mux-OFF solo baseline of the same schedule
    (chaos stripped), which this scenario runs first (the batched
    engine proves itself against the solo one)."""
    import random
    import zlib

    rng = random.Random((seed << 8) ^ zlib.crc32(b"mux"))
    faults = {"die_depth": rng.randint(2, 4), "armed": "every member"}

    def make_schedule(with_chaos: bool) -> Dict[str, Any]:
        jobs = []
        for i in range(k):
            job = {
                "idem": f"mux-{seed}-{i}",
                "spec": "2pc:3",
                # Zero stagger: members must be co-queued for the
                # scheduler to batch them at all.
                "delay_s": 0.0,
                "max_seconds": max_seconds,
            }
            if with_chaos:
                job["chaos"] = {
                    "die_at_depth": faults["die_depth"], "marker": True,
                }
            jobs.append(job)
        return {"seed": seed, "jobs": jobs}

    schedule = make_schedule(False)
    t0 = time.monotonic()

    def incarnate(sub: str, sched: Dict[str, Any], **kw) -> tuple:
        run_dir = os.path.join(base_dir, sub)
        os.makedirs(run_dir, exist_ok=True)
        sp = os.path.join(run_dir, "schedule.json")
        with open(sp, "w") as fh:
            json.dump(sched, fh)
        return run_dir, run_incarnation(run_dir, sp, wait_s=wait_s, **kw)

    base_run, rc = incarnate("mux_baseline", schedule, max_inflight=2)
    if rc != 0:
        return {
            "scenario": "mux", "ok": False, "rc": rc, "k": k,
            "faults": faults, "problems": [f"mux baseline rc={rc}"],
        }
    reference = reference_counts(base_run, schedule)
    restarts = 0
    run_dir, rc = incarnate(
        "mux", make_schedule(True), mux=k, max_inflight=max(2, k),
    )
    while rc != 0 and restarts < max_restarts:
        restarts += 1
        _, rc = incarnate(
            "mux", make_schedule(True), mux=k, max_inflight=max(2, k),
        )
    if rc != 0:
        return {
            "scenario": "mux", "ok": False, "rc": rc, "k": k,
            "restarts": restarts, "faults": faults,
            "problems": [f"final incarnation rc={rc}"],
        }
    invariant = check_invariant(run_dir, schedule, reference)
    history = journal_history(run_dir)
    groups = {
        r["mux_group"]
        for r in history
        if r["event"] == "started" and r.get("mux_group")
    }
    report = {
        "scenario": "mux",
        "ok": invariant["ok"],
        "problems": invariant["problems"],
        "faults": faults,
        "k": k,
        "restarts": restarts,
        "mux_groups_started": len(groups),
        "elapsed_s": round(time.monotonic() - t0, 3),
        **slo_stats(run_dir),
    }
    if not groups:
        # A mux pass that never batched proves nothing — same contract
        # as device_lost's no-migrations guard.
        report["ok"] = False
        report["problems"] = report["problems"] + [
            "mux scenario journaled no mux_group starts"
        ]
    return report


def reference_counts(run_dir: str, schedule: Dict[str, Any]) -> dict:
    """spec -> result counts from the baseline scenario's results."""
    with open(os.path.join(run_dir, "driver_results.json")) as fh:
        results = json.load(fh)["jobs"]
    out: dict = {}
    for entry in schedule["jobs"]:
        got = results[entry["idem"]]
        if got["status"] != "done":
            raise RuntimeError(
                f"baseline job {entry['idem']} did not complete: "
                f"{got['error']}"
            )
        out[entry["spec"]] = got["result"]
    return out


def check_repro(args: argparse.Namespace, base_dir: str) -> Dict[str, Any]:
    """Same seed, twice, fresh dirs, serial pool: the journal event
    sequences (timestamps masked) must be identical."""
    schedule = build_schedule(
        args.seed, args.jobs, args.max_seconds,
        tenants=getattr(args, "tenants", 0),
    )
    sigs = []
    for i in (1, 2):
        run_dir = os.path.join(base_dir, f"repro{i}")
        os.makedirs(run_dir, exist_ok=True)
        sp = os.path.join(run_dir, "schedule.json")
        with open(sp, "w") as fh:
            json.dump(schedule, fh)
        rc = run_incarnation(
            run_dir, sp, max_inflight=1, wait_s=args.wait_s
        )
        if rc != 0:
            return {"ok": False, "problems": [f"repro pass {i} rc={rc}"]}
        sigs.append(event_signature(journal_history(run_dir)))
    return {
        "ok": sigs[0] == sigs[1],
        "events": len(sigs[0]),
        "problems": (
            [] if sigs[0] == sigs[1] else [
                f"event sequences diverge: {sigs[0]} != {sigs[1]}"
            ]
        ),
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--jobs", type=int, default=3)
    p.add_argument("--scenario", default="all",
                   choices=("all", "baseline", "kill", "die", "torn",
                            "device_lost", "mux", "storm"))
    p.add_argument("--tenants", type=int, default=0,
                   help="seeded multi-tenant mixed-priority traffic: "
                        "every scheduled job gets one of N tenants and "
                        "a priority class; enables the storm scenario "
                        "and the per-class SLO split (ISSUE 18)")
    p.add_argument("--fleet", type=int, default=0,
                   help="front N per-device pools (FleetService); 0 = "
                        "the single-pool service")
    p.add_argument("--mux", type=int, default=0,
                   help="run the mux scenario at K lanes (batching "
                        "scheduler, ServiceConfig.mux_k); 0 = off "
                        "(--scenario mux alone defaults K to 4)")
    p.add_argument("--sessions", type=int, default=0,
                   help="concurrent interactive Explorer sessions "
                        "polling /.status alongside the batch schedule")
    p.add_argument("--base-dir", default=None,
                   help="scenario run dirs land here "
                        "(default runs/service_chaos/seed<N>)")
    p.add_argument("--max-seconds", type=float, default=240.0)
    p.add_argument("--max-inflight", type=int, default=2)
    p.add_argument("--max-restarts", type=int, default=4)
    p.add_argument("--wait-s", type=float, default=300.0)
    p.add_argument("--overload", action="store_true",
                   help="probe Retry-After accuracy with a queue-full burst")
    p.add_argument("--check-repro", action="store_true")
    p.add_argument("--out", default=os.path.join(RUNS, "service_chaos.json"))
    # serve mode (the killable child; internal)
    p.add_argument("--serve", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--run-dir", default=None, help=argparse.SUPPRESS)
    p.add_argument("--schedule", default=None, help=argparse.SUPPRESS)
    p.add_argument("--chaos", default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.serve:
        return serve(args)

    base_dir = args.base_dir or os.path.join(
        RUNS, "service_chaos", f"seed{args.seed}"
    )
    os.makedirs(base_dir, exist_ok=True)
    if args.scenario == "storm" and not args.tenants:
        args.tenants = 12
    schedule = build_schedule(
        args.seed, args.jobs, args.max_seconds, tenants=args.tenants
    )
    line: Dict[str, Any] = {
        "tool": "service_chaos",
        "seed": args.seed,
        "jobs": args.jobs,
        "tenants": args.tenants or None,
        "fleet_devices": args.fleet or None,
        "sessions": args.sessions or None,
        "mux_k": args.mux or None,
        "specs": [j["spec"] for j in schedule["jobs"]],
        "scenarios": {},
        "ok": True,
    }
    if args.check_repro:
        rep = check_repro(args, base_dir)
        line["scenarios"]["repro"] = rep
        line["ok"] = line["ok"] and rep["ok"]
    else:
        if args.scenario == "mux" and not args.mux:
            args.mux = 4
        if args.scenario == "mux":
            names = []  # self-contained: builds its own schedule+baseline
        elif args.scenario == "all":
            names = ["baseline", "kill", "torn"] + (
                ["device_lost"] if args.fleet else []
            ) + (["storm"] if args.tenants else [])
        else:
            names = ["baseline"] + (
                [args.scenario] if args.scenario != "baseline" else []
            )
        reference = None
        kw = dict(
            max_inflight=args.max_inflight,
            max_restarts=args.max_restarts,
            wait_s=args.wait_s,
            fleet=args.fleet,
            sessions=args.sessions,
        )
        for name in names:
            rep = run_scenario(
                name, args.seed, schedule, base_dir,
                reference=reference,
                overload=args.overload and name == "baseline",
                **kw,
            )
            line["scenarios"][name] = rep
            line["ok"] = line["ok"] and rep["ok"]
            if name == "baseline" and rep["ok"]:
                reference = reference_counts(
                    os.path.join(base_dir, "baseline"), schedule
                )
            elif name == "baseline":
                break  # no ground truth; the comparisons are meaningless
        if args.mux and args.scenario in ("all", "mux"):
            rep = run_mux_scenario(
                args.seed, base_dir, args.mux,
                max_seconds=args.max_seconds,
                wait_s=args.wait_s,
                max_restarts=args.max_restarts,
            )
            line["scenarios"]["mux"] = rep
            line["ok"] = line["ok"] and rep["ok"]
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(line, fh, indent=1)
    os.replace(tmp, args.out)
    print(json.dumps(line))
    return 0 if line["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
