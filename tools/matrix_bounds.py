"""Per-config modeled throughput bounds for the bench matrix shapes.

VERDICT r4 item 4 asks for TPU matrix rows >= 100k gen/s each "or a
documented per-config bound". The matrix configs are deep-narrow: their
state spaces are hundreds of levels of two-digit widths, so a
level-synchronous engine is bound by (levels x per-level fixed cost) no
matter how fast each level runs. This tool records each config's level
schedule (one host run on the device engine), pushes it through the
roofline model (tools/roofline.py), and prints the structural bound:

    bound(fixed) = generated / (levels * fixed + traffic_floor)

for the r3-measured 475 ms fixed cost, the attack-1 target (50 ms), and
the attack-2 target (5 ms). A config whose bound at 5 ms is below 100k
gen/s is *structurally* below the verdict line on this engine — the
honest statement is the bound, not a missed target.

One JSON line per config on stdout. Usage:
  python tools/matrix_bounds.py [--cpu]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    else:
        # The axon tunnel wedges rather than failing (CLAUDE.md): probe
        # it in a watchdog subprocess and fall back to CPU, the CLI
        # pattern — this tool's numbers are schedule-derived, so the
        # backend only affects wall-clock, not the bounds.
        from stateright_tpu.backend import ensure_live_backend

        ensure_live_backend()
    from tools.roofline import model_ceiling

    from stateright_tpu.models.increment_lock import PackedIncrementLock
    from stateright_tpu.models.linearizable_register import PackedAbd
    from stateright_tpu.models.paxos import PackedPaxos
    from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister

    configs = [
        ("linearizable-register (ABD) 2c/2s packed", lambda: PackedAbd(2, 2),
         dict(frontier_capacity=1 << 10, table_capacity=1 << 12)),
        ("paxos 2c/3s packed", lambda: PackedPaxos(2, 3),
         dict(frontier_capacity=1 << 12, table_capacity=1 << 16)),
        ("single-copy-register 3c/1s packed", lambda: PackedSingleCopyRegister(3, 1),
         dict(frontier_capacity=1 << 11, table_capacity=1 << 14)),
        ("increment_lock 3t packed", lambda: PackedIncrementLock(3),
         dict(frontier_capacity=1 << 10, table_capacity=1 << 13)),
    ]
    for name, build, kw in configs:
        try:
            checker = build().checker().spawn_xla(**kw)
            while not checker.is_done():
                checker._run_block()
            detail = {
                "actions": checker._A,
                "state_words": checker._W,
                "table_capacity": checker._table.capacity,
                "levels": [{"sec": 0, "levels": checker.level_log}],
            }
            out = model_ceiling(detail)
            gen = checker.state_count()
            levels = len(checker.level_log)
            traffic = out["modeled_sec"]
            row = {
                "config": name,
                "generated": gen,
                "unique": checker.unique_state_count(),
                "levels": levels,
                "widest_level": max((l["frontier"] for l in checker.level_log), default=0),
                "traffic_floor_sec": traffic,
                "bound_at_475ms": round(gen / (levels * 0.475 + traffic), 1),
                "bound_at_50ms": round(gen / (levels * 0.050 + traffic), 1),
                "bound_at_5ms": round(gen / (levels * 0.005 + traffic), 1),
            }
        except Exception as e:
            row = {"config": name, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
