"""Pallas stream-compaction prototype: the grid compaction without a sort.

The engine's largest per-level op is the grid-compaction sort —
(W+1 operands) x (A*F lanes) of ``lax.sort`` — whose only job under the
state-major ("bsearch") flatten is ORDER-PRESERVING stream compaction:
move the ``mask``-selected lanes of ``[P, M]`` planes to the front of a
``[P, cap]`` output. A sort is O(n log^2 n) data passes; a streaming
kernel is O(n): TPU pallas grids execute blocks SEQUENTIALLY on a core,
so the running output position lives in SMEM scratch across grid steps
and survivors land via MXU one-hot contractions + aligned chunk DMAs —
no scatters and no dynamic-offset vector stores (the XLA:TPU scatter
pathologies AND the Mosaic alignment prover, docs/backend_pathologies.md
#2/#6, never enter the picture).

Block scheme (block size B, grid step b; the r5e Mosaic rework — the
original "compact to block front, store at running offset" shape is
exactly the dynamic-offset ``vector_store`` Mosaic rejects, see
docs/backend_pathologies.md #6 and the ops/pallas_compact.py module
docstring for the full constraint story):
  1. load mask block [B], planes block [P, B] (VMEM),
  2. local ranks: inclusive prefix sum as a triangular [B, B] MXU
     contraction (Mosaic has no in-kernel cumsum),
  3. ring-targeted scatter-as-matmul: a [B, 2B] one-hot aims survivor
     s at ring position ``rank[s] + p``; one MXU pass lands every
     survivor in place in a [P, 2B] VMEM ring updated by a full
     aligned read-modify-write,
  4. full B-chunks DMA to the output at chunk-aligned offsets; the
     ring slides by one static B (SMEM carries the running counts).
Lanes past the total survivor count are garbage the caller masks (the
engine already masks by ``n_valid``, same as the sort lowerings).

Correctness is validated in interpret mode on CPU (this file's main());
the kernel ships as ``spawn_xla(compaction="pallas")``, opt-in until
this A/B proves it on chip.
"""

from __future__ import annotations

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


from stateright_tpu.ops.pallas_compact import (  # noqa: E402
    compact_pallas_staged,
)


def _sort_compact(mask, planes, cap: int):
    """The engine's sort-lowering equivalent at the same shapes: stable
    single-key sort carrying every plane (compact_1d's "sort" mode)."""
    import jax
    import jax.numpy as jnp

    key = jnp.where(mask, jnp.int32(0), jnp.int32(1))
    out = jax.lax.sort((key, *[planes[p] for p in range(planes.shape[0])]),
                       num_keys=1, is_stable=True)
    return jnp.stack([o[:cap] for o in out[1:]])


def main() -> None:
    import itertools
    import time

    import jax

    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            ),
        )
    import jax.numpy as jnp

    interpret = jax.default_backend() == "cpu"
    rng = np.random.default_rng(9)

    # --- correctness ----------------------------------------------------
    P, M, cap, B = 8, 1 << 14, 1 << 13, 512
    mask_np = rng.integers(0, 5, M) == 0  # ~20% density, under cap
    planes_np = rng.integers(0, 2**32, (P, M), dtype=np.uint32)
    n = int(mask_np.sum())
    want = planes_np[:, mask_np]
    out_s = compact_pallas_staged(
        jnp.asarray(mask_np), jnp.asarray(planes_np), cap, block=B,
        interpret=interpret,
    )
    got_s = np.asarray(out_s)[:, :n]
    assert np.array_equal(got_s, want), "STAGED MISMATCH"
    print(f"pallas staged compact OK: {n} survivors, HBM out + VMEM ring")
    if interpret:
        return  # interpreter timings are meaningless

    # --- perf A/B vs the sort lowering (host-readback-gated) ------------
    for log2_m, B in itertools.product((20, 22), (512, 1024)):
        M = 1 << log2_m
        cap = M // 4  # VMEM-resident output probe shape
        mask_np = rng.integers(0, 8, M) == 0  # ~12% (rm=8 grid validity)
        planes_np = rng.integers(0, 2**32, (P, M), dtype=np.uint32)
        mask = jnp.asarray(mask_np)
        planes = jnp.asarray(planes_np)

        f_stg = jax.jit(functools.partial(compact_pallas_staged, cap=cap, block=B))
        f_sort = jax.jit(functools.partial(_sort_compact, cap=cap))
        for name, fn in (("staged", f_stg), ("sort", f_sort)):
            try:
                o = fn(mask, planes)
            except Exception as e:  # lowering failures are a result too
                print(f"  M=2^{log2_m} B={B} {name}: FAILED {type(e).__name__}: {e}")
                continue
            nvl = int(np.asarray(mask).sum())
            ok = np.array_equal(np.asarray(o)[:, :nvl], planes_np[:, mask_np])
            t0 = time.monotonic()
            for _ in range(5):
                o = fn(mask, planes)
            np.asarray(o[0][:8])  # readback gates the clock
            dt = (time.monotonic() - t0) / 5
            print(
                f"  M=2^{log2_m} B={B} {name}: {dt * 1e3:8.2f} ms "
                f"({'exact' if ok else 'WRONG'})",
                flush=True,
            )

    # --- the engine shape: M=2^24 grid lanes, cap=2^22 (out in HBM) -----
    # B=512 matches the engine's STPU_PALLAS_BLOCK default (the B=1024
    # sel+tri operands crowd VMEM — see the xla.py comment).
    log2_m, B = 24, 512
    M, cap = 1 << log2_m, 1 << 22
    mask_np = rng.integers(0, 8, M) == 0
    planes_np = rng.integers(0, 2**32, (P, M), dtype=np.uint32)
    mask = jnp.asarray(mask_np)
    planes = jnp.asarray(planes_np)
    f_stg = jax.jit(functools.partial(compact_pallas_staged, cap=cap, block=B))
    f_sort = jax.jit(functools.partial(_sort_compact, cap=cap))
    for name, fn in (("staged", f_stg), ("sort", f_sort)):
        try:
            o = fn(mask, planes)
        except Exception as e:
            print(f"  M=2^{log2_m} B={B} {name}: FAILED {type(e).__name__}: {e}")
            continue
        nvl = int(mask_np.sum())
        ok = np.array_equal(np.asarray(o)[:, :nvl], planes_np[:, mask_np])
        t0 = time.monotonic()
        for _ in range(5):
            o = fn(mask, planes)
        np.asarray(o[0][:8])
        dt = (time.monotonic() - t0) / 5
        print(
            f"  M=2^{log2_m} B={B} {name} (engine shape): {dt * 1e3:8.2f} ms "
            f"({'exact' if ok else 'WRONG'})",
            flush=True,
        )


if __name__ == "__main__":
    main()
