"""Standalone bisector for the delta-structure TPU runtime fault.

Round-5 history: the pre-redesign delta insert (flush as a ``lax.cond``
branch carrying a main-capacity sort) reproducibly crashed the TPU
runtime ("TPU worker crashed — kernel fault") at 2^22 AND 2^27 main
tiers while staying exact on CPU. The redesign (host-invoked
``maintain``) removes that shape; the soak retries it at rm=8/rm=10.
The retry DID fault again (r5e, twice, deterministic, flush already
host-invoked), so THIS tool pins where, coarse-to-fine in one process:
each delta program standalone (insert at empty delta, maintain,
dedup-vs-main) across a ladder of main-tier shapes, then the REAL
engine at the faulting rm=8 shape — lpd=1 (no fused loop) first, then
fused. A fault kills the process, so the first faulting
(program/composition, shape) is the last stage whose "..." line has no
matching "ok" line; a ``timeout`` kill looks the same, so check the
wall clock against the stage budget before calling it a fault (the
engine stages are FULL rm=8 checks — ~minutes on chip, ~an hour on
this 1-core box; shrink with STPU_DIAG_RM=6 or skip with
--no-engine for a quick harness check). A count DRIFT in a surviving
engine stage exits 2 — silent drift is the failure class this tool
exists for.

Usage:
    [STPU_DIAG_RM=N] python tools/delta_diag.py [--cpu] [--no-engine] [max_log2_C]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax

    no_engine = "--no-engine" in sys.argv
    if no_engine:
        sys.argv.remove("--no-engine")
    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            ),
        )
    import jax.numpy as jnp

    from stateright_tpu.ops import deltaset

    max_pow = int(sys.argv[1]) if len(sys.argv) > 1 else 27
    print(f"backend={jax.default_backend()} shapes up to 2^{max_pow}", flush=True)

    rng = np.random.default_rng(3)

    ins = jax.jit(deltaset.insert)

    for pow_c in range(18, max_pow + 1, 3):
        C = 1 << pow_c
        t0 = time.monotonic()
        ds = deltaset.make(C, jnp)
        # Batch sized to half the delta tier (C/16-row tier): big enough
        # to be a realistic level, small enough that the empty-delta
        # insert cannot overflow.
        m = ds.delta_capacity // 2
        hi = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
        lo = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
        vh = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
        act = jnp.ones((m,), bool)

        print(f"[delta_diag] C=2^{pow_c} insert(empty-delta) ...", flush=True)
        ds1, is_new, ovf = ins(ds, hi, lo, vh, vh, act)
        n_new = int(np.asarray(is_new).sum())
        assert not bool(ovf) and n_new > 0, (n_new, bool(ovf))
        print(
            f"[delta_diag] C=2^{pow_c} insert ok: {n_new} new "
            f"({time.monotonic() - t0:.1f}s)",
            flush=True,
        )

        print(f"[delta_diag] C=2^{pow_c} maintain(flush) ...", flush=True)
        t0 = time.monotonic()
        ds2, f_ovf = deltaset.maintain_jit(ds1)
        assert not bool(f_ovf)
        n_main = int(ds2.n_main)
        assert n_main == n_new, (n_main, n_new)
        print(
            f"[delta_diag] C=2^{pow_c} maintain ok: {n_main} main rows "
            f"({time.monotonic() - t0:.1f}s)",
            flush=True,
        )

        print(f"[delta_diag] C=2^{pow_c} insert(post-flush, dup batch) ...", flush=True)
        t0 = time.monotonic()
        # Re-inserting the same batch must find every key in main.
        _, is_new2, ovf2 = ins(ds2, hi, lo, vh, vh, act)
        assert not bool(ovf2) and int(np.asarray(is_new2).sum()) == 0
        print(
            f"[delta_diag] C=2^{pow_c} dedup-vs-main ok "
            f"({time.monotonic() - t0:.1f}s)",
            flush=True,
        )

    print("[delta_diag] ALL SHAPES CLEAN (standalone programs)", flush=True)
    if no_engine:
        return

    # --- engine composition, coarse-to-fine ------------------------------
    # The r5e window proved the fault lives past the standalone layer or
    # in a shape these ladders miss: the rm=8 delta bench faulted twice,
    # deterministically, with the flush already host-invoked. Run the
    # REAL engine at the faulting shape, least-composed first: lpd=1
    # (each level its own dispatch, no fused while_loop), then the fused
    # default. A fault kills the process, so the last line printed is
    # the first faulting composition; counts are checked against the
    # pinned rm=8 totals when a stage survives.
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    # rm=8 is the faulting shape; STPU_DIAG_RM shrinks it for CPU
    # validation of the harness itself and for faster fault iteration.
    # Pinned totals come from bench.py's table — one source of truth.
    from bench import EXPECTED_2PC

    rm = int(os.environ.get("STPU_DIAG_RM", "8"))
    want = EXPECTED_2PC.get(rm)
    f_pow = 19 if rm >= 8 else 17
    t_pow = 22 if rm >= 8 else 20
    for lpd, label in ((1, "engine lpd=1 (no fused loop)"), (32, "engine fused")):
        print(f"[delta_diag] {label} rm={rm} dedup=delta ...", flush=True)
        t0 = time.monotonic()
        ck = (
            PackedTwoPhaseSys(rm)
            .checker()
            .spawn_xla(
                frontier_capacity=1 << f_pow,
                table_capacity=1 << t_pow,
                dedup="delta",
                levels_per_dispatch=lpd,
            )
            .join()
        )
        got = (ck.state_count(), ck.unique_state_count())
        if want and got != want:
            # Silent count drift is THE failure class this tool exists
            # for — it must not be reportable as a clean pass.
            print(
                f"[delta_diag] {label} COUNT DRIFT: gen/uniq {got} "
                f"vs pinned {want} ({time.monotonic() - t0:.1f}s)",
                flush=True,
            )
            sys.exit(2)
        verdict = "EXACT" if want else "unpinned rm"
        print(
            f"[delta_diag] {label} ok: gen/uniq {got} {verdict} "
            f"({time.monotonic() - t0:.1f}s)",
            flush=True,
        )

    print("[delta_diag] ALL CLEAN incl. engine composition", flush=True)


if __name__ == "__main__":
    main()
