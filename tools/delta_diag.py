"""Standalone bisector for the delta-structure TPU runtime fault.

Round-5 history: the pre-redesign delta insert (flush as a ``lax.cond``
branch carrying a main-capacity sort) reproducibly crashed the TPU
runtime ("TPU worker crashed — kernel fault") at 2^22 AND 2^27 main
tiers while staying exact on CPU. The redesign (host-invoked
``maintain``) removes that shape; the soak retries it at rm=8/rm=10.
If the retry faults again, THIS tool pins where: it runs each delta
program (insert at empty delta, insert at near-full delta, maintain)
standalone across a ladder of main-tier shapes, checking results
against numpy on the way, so the first faulting (program, shape) pair
is the last line printed.

Each shape runs in-process (a fault kills the process — run under
``timeout`` and read the log tail). Usage:
    python tools/delta_diag.py [--cpu] [max_log2_C]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            ),
        )
    import jax.numpy as jnp

    from stateright_tpu.ops import deltaset

    max_pow = int(sys.argv[1]) if len(sys.argv) > 1 else 27
    print(f"backend={jax.default_backend()} shapes up to 2^{max_pow}", flush=True)

    rng = np.random.default_rng(3)

    ins = jax.jit(deltaset.insert)

    for pow_c in range(18, max_pow + 1, 3):
        C = 1 << pow_c
        t0 = time.monotonic()
        ds = deltaset.make(C, jnp)
        # Batch sized to half the delta tier (C/16-row tier): big enough
        # to be a realistic level, small enough that the empty-delta
        # insert cannot overflow.
        m = ds.delta_capacity // 2
        hi = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
        lo = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
        vh = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
        act = jnp.ones((m,), bool)

        print(f"[delta_diag] C=2^{pow_c} insert(empty-delta) ...", flush=True)
        ds1, is_new, ovf = ins(ds, hi, lo, vh, vh, act)
        n_new = int(np.asarray(is_new).sum())
        assert not bool(ovf) and n_new > 0, (n_new, bool(ovf))
        print(
            f"[delta_diag] C=2^{pow_c} insert ok: {n_new} new "
            f"({time.monotonic() - t0:.1f}s)",
            flush=True,
        )

        print(f"[delta_diag] C=2^{pow_c} maintain(flush) ...", flush=True)
        t0 = time.monotonic()
        ds2, f_ovf = deltaset.maintain_jit(ds1)
        assert not bool(f_ovf)
        n_main = int(ds2.n_main)
        assert n_main == n_new, (n_main, n_new)
        print(
            f"[delta_diag] C=2^{pow_c} maintain ok: {n_main} main rows "
            f"({time.monotonic() - t0:.1f}s)",
            flush=True,
        )

        print(f"[delta_diag] C=2^{pow_c} insert(post-flush, dup batch) ...", flush=True)
        t0 = time.monotonic()
        # Re-inserting the same batch must find every key in main.
        _, is_new2, ovf2 = ins(ds2, hi, lo, vh, vh, act)
        assert not bool(ovf2) and int(np.asarray(is_new2).sum()) == 0
        print(
            f"[delta_diag] C=2^{pow_c} dedup-vs-main ok "
            f"({time.monotonic() - t0:.1f}s)",
            flush=True,
        )

    print("[delta_diag] ALL SHAPES CLEAN", flush=True)


if __name__ == "__main__":
    main()
