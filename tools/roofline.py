"""Bandwidth accounting + modeled ceiling for the device engine.

Three modes:

``python tools/roofline.py [runs/bench_detail.json]``
    Post-hoc accounting of a measured run (as before): logical bytes per
    stage divided by measured wall-clock, reported against the chip's
    HBM peak. Numbers far below peak mean latency/serialization bound,
    not traffic bound.

``python tools/roofline.py --measured [trace.jsonl] [bench_detail.json]``
    Per-stage WALL-CLOCK from the obs span trace (STPU_TRACE;
    docs/observability.md) next to the modeled ceiling: spans aggregate
    into host-boundary stages (compile-carrying dispatches, steady
    dispatches, overflow-recovery growth/flush work, host-verify) with
    count/total/share per stage. When a bench_detail.json is present
    (second arg, or the default paths) the modeled ceiling for the same
    recorded schedule prints alongside — the gap between measured
    dispatch wall-clock and the modeled traffic floor is the
    optimization headroom, now engine-measured instead of hand-derived.

    The same flag also reads a METRICS TIME-SERIES (``metrics.jsonl``,
    the MetricsRecorder rotation — docs/observability.md "Time series"):
    a .jsonl argument is sniffed by schema, and with no trace at all the
    detail file's recorded ``metrics_series`` path is the fallback
    source. A series yields run-level rates (wall-clock, dispatch/level
    counts, gen/s between samples), not per-stage wall-clock — spans
    wrap each host boundary, samples only bracket quiescent points.
    Precedence when both artifacts exist: the span trace wins; the
    series is the coarse answer for runs that only recorded metrics.

    ``--measured`` also accepts a **service/fleet run dir**: every span
    ``trace.jsonl`` under it (service, per-device pools, per-job workers,
    mux lanes) aggregates into one per-stage report, and the run dir's
    ``journal.jsonl`` (auto-discovered) contributes the job→spec map as
    provenance. Source precedence: an explicit span-trace path wins, then
    a run dir's discovered traces, then the detail file's recorded
    ``trace``, then a metrics series (coarse run-level rates only).

``python tools/roofline.py --phases [trace.jsonl | run_dir]``
    The dispatch-phase profiler report (``spawn_xla(phases=True)`` /
    ``STPU_PHASES=1`` — docs/observability.md "Distributed tracing"):
    aggregates the ``phase:*`` sub-spans under each dispatch into
    host_prep / enqueue / device_compute / readback totals, split
    steady-state vs compile-carrying, with per-bucket rows. Reports the
    measured host-RTT share, device occupancy, and the projected
    pipelined throughput — the wall-clock the same schedule would take
    if host phases overlapped device compute (the pipelining attack's
    headroom: ``max(Σhost, Σdevice)`` vs their sum today).

``python tools/roofline.py --model [runs/bench_detail.json]``
    The DESIGN's traffic-bound ceiling on v5e-1 (VERDICT r4 item 3): for
    each committed level of the recorded schedule, the minimum HBM bytes
    each stage must move, divided by an achievable fraction of peak
    bandwidth, plus per-level dispatch latency and the measured sort
    constant. This is what the engine would run at if every stage hit
    ``EFFICIENCY`` of peak — the gap between this and a measured run is
    the optimization headroom; the stage with the largest modeled share
    is the binding constraint. Overridables (env):
      ROOFLINE_EFFICIENCY   fraction of peak HBM each stage can achieve
                            (default 0.4 — sorts move data ~log passes,
                            gathers stride; 40% of peak is a strong
                            sustained figure for this mix)
      ROOFLINE_SORT_PASSES  effective full-data passes per bitonic-style
                            device sort (default 3; measured two-key sort
                            at 2^22 = 3.3 ms ~= 2.9 passes at peak)
      ROOFLINE_RTT_S        per-dispatch host latency (default 30e-6,
                            measured round 3 over the axon tunnel)

The model is deliberately *optimistic per stage* (logical bytes, no
re-reads beyond declared passes): it is a ceiling, not a prediction.

Stage byte model per level (bucket B, actions A, words W, generated M_l,
table capacity C, candidate cap = B*A/4):
  expand     read frontier B*W*4, write grid B*A*W*4
  fingerprint  read grid, write 2 key lanes: B*A*(W+2)*4
  compact    key sort B*A*8*passes + survivor gather M_l*(W+3)*4
  insert     3-operand sort of [C + cand] rows: (C + B*A/4)*12*passes
  frontier   survivor pull M_l*(W+1)*4
"""

from __future__ import annotations

import json
import os
import statistics
import sys

PEAK_GBPS = 819.0  # TPU v5e HBM
EFFICIENCY = float(os.environ.get("ROOFLINE_EFFICIENCY", "0.4"))
SORT_PASSES = float(os.environ.get("ROOFLINE_SORT_PASSES", "3"))
RTT_S = float(os.environ.get("ROOFLINE_RTT_S", "30e-6"))


def _levels(detail):
    for block in detail.get("levels", []):
        for lv in block.get("levels", []):
            yield lv


def _bucket_for(F: int, floor: int = 64) -> int:
    bucket = floor
    while bucket < 4 * F:
        bucket *= 4
    return bucket


def _table_capacity(detail) -> int:
    """Recorded capacity, else derived from the unique count under the
    sorted set's 3/4-load growth rule (older bench_detail files predate
    the table_capacity key; defaulting to 2^22 would overstate the
    insert stage ~100x on small schedules)."""
    if "table_capacity" in detail:
        return detail["table_capacity"]
    uniq = max(int(detail.get("unique_states", 0)), 1)
    cap = 1 << 10
    while uniq * 4 > cap * 3:
        cap *= 2
    return cap


def model_ceiling(detail) -> dict:
    """Modeled stage seconds for the recorded level schedule on v5e-1."""
    rm = detail.get("rm", 8)
    # Action width: explicit "actions" key wins (non-2pc models);
    # otherwise the 2pc formula from rm.
    A = detail.get("actions") or (2 + 5 * rm)
    W = detail.get("state_words", 2)
    C = _table_capacity(detail)
    bw = PEAK_GBPS * 1e9 * EFFICIENCY
    stages = {"expand": 0.0, "fingerprint": 0.0, "compact": 0.0,
              "insert": 0.0, "frontier": 0.0, "dispatch": 0.0}
    gen_total = 0
    n_levels = 0
    for lv in _levels(detail):
        F = max(int(lv.get("frontier", 0)), 1)
        M = max(int(lv.get("generated", 0)), 1)
        gen_total += M
        n_levels += 1
        B = _bucket_for(F)
        grid = B * A
        stages["expand"] += (B * W + grid * W) * 4 / bw
        stages["fingerprint"] += grid * (W + 2) * 4 / bw
        stages["compact"] += (grid * 8 * SORT_PASSES + M * (W + 3) * 4) / bw
        stages["insert"] += (C + grid // 4) * 12 * SORT_PASSES / bw
        stages["frontier"] += M * (W + 1) * 4 / bw
    # Fused dispatch: one RTT per ~32-level block, not per level.
    stages["dispatch"] = max(1, n_levels / 32) * RTT_S
    total = sum(stages.values())
    return {
        "rm": rm, "levels": n_levels, "generated": gen_total,
        "stage_sec": {k: round(v, 4) for k, v in stages.items()},
        "modeled_sec": round(total, 4),
        "ceiling_states_per_sec": round(gen_total / max(total, 1e-12), 0),
        "binding_stage": max(stages, key=stages.get),
        "assumptions": {
            "efficiency": EFFICIENCY, "sort_passes": SORT_PASSES,
            "rtt_s": RTT_S, "peak_gbps": PEAK_GBPS,
        },
    }


def cost_law_rows(detail) -> list:
    """Predicted-vs-measured cost-law rows from the engine's per-level
    sorted-lane-words telemetry (level rows carry ``lane_words`` /
    ``cand_cap`` / ``bucket`` since the candidate-ladder round — the
    ACTUAL static sort shapes the compiled program ran, so this replaces
    the hand-derived per-level figure the byte model above guesses at).
    One row per dispatch block: the block's wall-clock is the
    tunnel-visible measured unit; its predicted sort seconds are
    lane-words x 4 bytes x SORT_PASSES / achievable bandwidth."""
    bw = PEAK_GBPS * 1e9 * EFFICIENCY
    rows = []
    for block in detail.get("levels", []):
        lvls = block.get("levels", [])
        lw = [l.get("lane_words") for l in lvls]
        if not lvls or any(w is None for w in lw):
            continue
        total_lw = sum(lw)
        rows.append(
            {
                "levels": len(lvls),
                "lane_words": total_lw,
                "cand_caps": sorted({l.get("cand_cap") for l in lvls}),
                "predicted_sort_s": round(total_lw * 4 * SORT_PASSES / bw, 5),
                "measured_s": block.get("sec"),
            }
        )
    return rows


#: Where a detail file lives when unspecified: fresh runs land under
#: runs/ (bench.py), with the legacy repo-root path as fallback.
DEFAULT_DETAIL = ("runs/bench_detail.json", "bench_detail.json")


def _load_default_detail():
    for p in DEFAULT_DETAIL:
        if os.path.exists(p):
            with open(p) as fh:
                return json.load(fh), p
    return None, None


def measured_stages(trace_path: str) -> dict:
    """Aggregates the span JSONL into host-boundary stages: wall-clock
    seconds + event counts per stage, plus a per-bucket dispatch split
    (the bucket ladder's cost profile, engine-measured)."""
    stages = {}
    buckets = {}
    wall = 0.0
    # Rebase multiple appended tracer sessions (bench retries) onto the
    # first session's clock via each trace_start's unix_ts — mirrors
    # obs.export_chrome, so trace_span_sec covers the whole file.
    base_unix = None
    offset = 0.0
    with open(trace_path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            name = rec.get("name")
            if name == "trace_start":
                u = rec.get("attrs", {}).get("unix_ts")
                if u is not None:
                    if base_unix is None:
                        base_unix = u
                    offset = u - base_unix
                continue
            if name is None:
                continue
            attrs = rec.get("attrs", {})
            if name == "dispatch":
                stage = "compile_dispatch" if attrs.get("compile") else "dispatch"
                b = attrs.get("bucket")
                if b is not None and not attrs.get("compile"):
                    row = buckets.setdefault(b, {"count": 0, "sec": 0.0, "levels": 0})
                    row["count"] += 1
                    row["sec"] += rec["dur"]
                    row["levels"] += attrs.get("committed") or 0
            elif name in ("grow_table", "grow_frontier", "delta_flush"):
                stage = "overflow_recovery"
            else:
                stage = name
            row = stages.setdefault(stage, {"count": 0, "sec": 0.0})
            row["count"] += 1
            row["sec"] += rec["dur"]
            wall = max(wall, rec["ts"] + offset + rec["dur"])
    total = sum(r["sec"] for r in stages.values())
    for r in stages.values():
        r["sec"] = round(r["sec"], 4)
        r["share"] = round(r["sec"] / max(total, 1e-12), 3)
    return {
        "trace": trace_path,
        "stages": stages,
        "dispatch_by_bucket": {
            str(b): {**row, "sec": round(row["sec"], 4)}
            for b, row in sorted(buckets.items())
        },
        "instrumented_sec": round(total, 4),
        "trace_span_sec": round(wall, 4),
    }


def discover_traces(run_dir: str) -> list:
    """Every span ``trace.jsonl`` under a service/fleet run dir, sorted
    by relative path (service root first, then per-job worker dirs,
    then fleet pool subtrees) — the same discovery rule as
    ``stateright_tpu.obs.collect.trace_files``, inlined so this tool
    stays import-free of the package."""
    out = []
    for root, _dirs, files in os.walk(run_dir):
        if "trace.jsonl" in files:
            out.append(os.path.join(root, "trace.jsonl"))
    out.sort(key=lambda p: os.path.relpath(p, run_dir))
    return out


def discover_jobs(run_dir: str) -> dict:
    """Auto-discovered journal provenance for a run dir: the job→spec
    map folded from every ``journal.jsonl`` under it (``submitted``
    records; torn/partial lines skipped, same reader tolerance as the
    service's replay)."""
    jobs = {}
    for root, _dirs, files in os.walk(run_dir):
        for name in files:
            if name != "journal.jsonl" and not name.startswith("journal.jsonl."):
                continue
            try:
                with open(os.path.join(root, name)) as fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if not isinstance(rec, dict):
                            continue
                        body = rec.get("rec", rec)
                        if body.get("event") == "submitted" and body.get("job"):
                            jobs[body["job"]] = body.get("spec")
            except OSError:
                continue
    return jobs


def measured_stages_multi(trace_paths: list) -> dict:
    """``measured_stages`` summed across every trace of a run dir (one
    per process: service, workers, mux lanes). Per-file clocks are not
    aligned, so ``trace_span_sec`` is the max single-file span; stage
    seconds/counts and the per-bucket dispatch split sum exactly."""
    if len(trace_paths) == 1:
        return measured_stages(trace_paths[0])
    stages = {}
    buckets = {}
    wall = 0.0
    total = 0.0
    for p in trace_paths:
        one = measured_stages(p)
        for k, row in one["stages"].items():
            agg = stages.setdefault(k, {"count": 0, "sec": 0.0})
            agg["count"] += row["count"]
            agg["sec"] += row["sec"]
        for b, row in one["dispatch_by_bucket"].items():
            agg = buckets.setdefault(b, {"count": 0, "sec": 0.0, "levels": 0})
            for k in agg:
                agg[k] += row[k]
        wall = max(wall, one["trace_span_sec"])
        total += one["instrumented_sec"]
    for r in stages.values():
        r["sec"] = round(r["sec"], 4)
        r["share"] = round(r["sec"] / max(total, 1e-12), 3)
    return {
        "trace": trace_paths,
        "stages": stages,
        "dispatch_by_bucket": {
            b: {**row, "sec": round(row["sec"], 4)}
            for b, row in sorted(buckets.items())
        },
        "instrumented_sec": round(total, 4),
        "trace_span_sec": round(wall, 4),
    }


#: The dispatch-phase profiler's sub-span names, in pipeline order
#: (mirrors XlaChecker.PHASE_NAMES — host_prep/enqueue run on the host
#: before the device, readback after; enqueue carries XLA compile time
#: on fresh programs, which is why compile-carrying dispatches report
#: separately below).
PHASE_NAMES = ("host_prep", "enqueue", "device_compute", "readback")
HOST_PHASES = ("host_prep", "enqueue", "readback")


def phase_report(trace_paths: list) -> dict:
    """Aggregates ``phase:*`` sub-spans (the dispatch-phase profiler,
    ``spawn_xla(phases=True)``/``STPU_PHASES=1``) across one or more
    traces into the pipelining-attack report: per-phase seconds split
    steady vs compile-carrying, per-bucket rows, host-RTT share, device
    occupancy, and the projected pipelined wall-clock — what the same
    steady-state schedule would cost if host phases overlapped device
    compute (``max(Σhost, Σdevice)``)."""
    # Pass 1 accumulates dispatch parents; phase spans are emitted after
    # their parent dispatch span in every tracer session, but keep the
    # two-pass shape so multi-file ordering never matters.
    parents = {}  # span_id -> {"compile": bool, "bucket": int}
    phase_rows = []  # (phase, dur, parent_id, fallback_bucket)
    for path in trace_paths:
        with open(path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                name = rec.get("name")
                if name == "dispatch" and rec.get("span_id"):
                    attrs = rec.get("attrs", {})
                    parents[rec["span_id"]] = {
                        "compile": bool(attrs.get("compile")),
                        "bucket": attrs.get("bucket"),
                    }
                elif isinstance(name, str) and name.startswith("phase:"):
                    attrs = rec.get("attrs", {})
                    phase_rows.append((
                        name[len("phase:"):], rec.get("dur", 0.0),
                        rec.get("parent_id"), attrs.get("bucket"),
                    ))
    if not phase_rows:
        return {"dispatches": 0, "phases": {}}
    zero = lambda: {k: 0.0 for k in PHASE_NAMES}  # noqa: E731
    steady, compile_ = zero(), zero()
    by_bucket = {}
    dispatches = set()
    for phase, dur, parent, bucket in phase_rows:
        if phase not in steady:
            continue
        par = parents.get(parent, {})
        is_compile = par.get("compile", False)
        bucket = par.get("bucket", bucket)
        (compile_ if is_compile else steady)[phase] += dur
        if parent is not None:
            dispatches.add(parent)
        if not is_compile:
            row = by_bucket.setdefault(bucket, zero())
            row[phase] += dur
    s_host = sum(steady[k] for k in HOST_PHASES)
    s_dev = steady["device_compute"]
    s_total = s_host + s_dev
    pipelined = max(s_host, s_dev)
    out = {
        "dispatches": len(dispatches) or len(phase_rows) // len(PHASE_NAMES),
        "phases": {
            "steady": {k: round(v, 4) for k, v in steady.items()},
            "compile_carrying": {k: round(v, 4) for k, v in compile_.items()},
        },
        "by_bucket": {
            str(b): {k: round(v, 4) for k, v in row.items()}
            for b, row in sorted(
                by_bucket.items(), key=lambda kv: (kv[0] is None, kv[0])
            )
        },
        "steady_sec": round(s_total, 4),
        "host_share": round(s_host / max(s_total, 1e-12), 3),
        "device_occupancy": round(s_dev / max(s_total, 1e-12), 3),
        "projected_pipelined_sec": round(pipelined, 4),
        "pipeline_speedup": round(s_total / max(pipelined, 1e-12), 2),
    }
    return out


def _phases_main(args: list) -> None:
    """``--phases``: the dispatch-phase profiler report. Args may be a
    span trace, a run dir (traces auto-discovered), and/or a detail
    JSON (contributes the generated count for projected throughput);
    with none, the default detail file's recorded trace is used."""
    detail = detail_path = None
    traces = []
    for a in args:
        if os.path.isdir(a):
            traces.extend(discover_traces(a))
        elif a.endswith(".jsonl"):
            traces.append(a)
        else:
            with open(a) as fh:
                detail = json.load(fh)
            detail_path = a
    if detail is None:
        detail, detail_path = _load_default_detail()
    if not traces and detail is not None:
        t = detail.get("trace")
        if t and os.path.exists(t):
            traces = [t]
    if not traces:
        print(
            "no trace: run with STPU_TRACE=path STPU_PHASES=1 (or "
            "spawn_xla(trace=..., phases=True)), then pass the trace or "
            "its run dir to tools/roofline.py --phases"
        )
        sys.exit(1)
    out = phase_report(traces)
    out["trace"] = traces if len(traces) > 1 else traces[0]
    if not out["dispatches"]:
        print(json.dumps(out, indent=1))
        print(
            "# trace has no phase:* sub-spans — the profiler is off by "
            "default; rerun with STPU_PHASES=1 (needs STPU_TRACE too)"
        )
        sys.exit(1)
    gen = None
    if detail is not None:
        out["detail"] = detail_path
        gen = sum(int(lv.get("generated", 0)) for lv in _levels(detail))
    if gen:
        out["measured_gen_per_s"] = round(gen / max(out["steady_sec"], 1e-12), 0)
        out["projected_pipelined_gen_per_s"] = round(
            gen / max(out["projected_pipelined_sec"], 1e-12), 0
        )
    print(json.dumps(out, indent=1))
    st = out["phases"]["steady"]
    print(
        f"# {out['dispatches']} profiled dispatches, steady phases: "
        f"host_prep {st['host_prep']:.3f}s + enqueue {st['enqueue']:.3f}s + "
        f"readback {st['readback']:.3f}s (host) vs device_compute "
        f"{st['device_compute']:.3f}s -> host share {out['host_share']:.0%}, "
        f"device occupancy {out['device_occupancy']:.0%}"
    )
    tail = (
        f" ({out.get('measured_gen_per_s', 0)/1e6:.2f} -> "
        f"{out.get('projected_pipelined_gen_per_s', 0)/1e6:.2f} M gen/s)"
        if gen else ""
    )
    print(
        f"# pipelining attack headroom: overlapped host/device wall "
        f"{out['projected_pipelined_sec']:.3f}s vs {out['steady_sec']:.3f}s "
        f"serial today = {out['pipeline_speedup']:.2f}x{tail}"
    )


def _jsonl_kind(path: str) -> str | None:
    """Sniff a .jsonl artifact: "trace" (span lines: name + dur),
    "series" (MetricsRecorder rows: v + metrics), or None."""
    try:
        with open(path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    if "name" in rec and "dur" in rec:
                        return "trace"
                    if "v" in rec and "metrics" in rec:
                        return "series"
    except OSError:
        return None
    return None


def measured_from_series(series_path: str) -> dict:
    """Run-level rates from a metrics time-series (the coarse fallback
    when no span trace exists): wall-clock between the first and last
    sample, dispatch/level/state deltas, and the per-interval gen/s
    profile. The rotation chain (``.K`` ... live) reassembles oldest
    first; torn lines are skipped — same reader contract as
    ``stateright_tpu.obs.read_series``, inlined so this tool stays
    import-free of the package."""
    paths = []
    i = 1
    while os.path.exists(f"{series_path}.{i}"):
        paths.append(f"{series_path}.{i}")
        i += 1
    paths.reverse()
    paths.append(series_path)
    rows = []
    for p in paths:
        try:
            with open(p) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict) and "v" in rec and "metrics" in rec:
                        rows.append(rec)
        except OSError:
            continue
    if not rows:
        return {"series": series_path, "samples": 0}
    first, last = rows[0]["metrics"], rows[-1]["metrics"]
    wall = rows[-1]["unix_ts"] - rows[0]["unix_ts"]
    gen = last.get("state_count", 0) - first.get("state_count", 0)
    rates = []
    for a, b in zip(rows, rows[1:]):
        dt = b["unix_ts"] - a["unix_ts"]
        ds = b["metrics"].get("state_count", 0) - a["metrics"].get("state_count", 0)
        if dt > 0:
            rates.append(ds / dt)
    return {
        "source": "metrics_series",
        "series": series_path,
        "samples": len(rows),
        "wall_s": round(wall, 4),
        "dispatches": last.get("dispatches", 0) - first.get("dispatches", 0),
        "levels_committed": (
            last.get("levels_committed", 0) - first.get("levels_committed", 0)
        ),
        "generated": gen,
        "gen_per_s": round(gen / max(wall, 1e-9), 1),
        "gen_per_s_intervals": {
            "min": round(min(rates), 1) if rates else None,
            "median": round(statistics.median(rates), 1) if rates else None,
            "max": round(max(rates), 1) if rates else None,
        },
        "checkpoints_written": last.get("checkpoints_written", 0),
        "final": {
            k: last.get(k)
            for k in ("engine", "dedup", "depth", "frontier_count",
                      "table_occupancy", "state_count", "unique_state_count")
        },
    }


def _measured_main(args: list) -> None:
    """``--measured``: per-stage wall-clock from the trace, next to the
    modeled ceiling when a detail file for the run is available. A
    metrics time-series (by schema sniff, or the detail file's
    ``metrics_series`` fallback when no trace exists) yields the coarse
    run-level report instead. Precedence: explicit span trace > run-dir
    discovered traces > the detail file's recorded trace > series."""
    detail = detail_path = None
    trace = None
    series = None
    run_dir = None
    dir_traces = []
    for a in args:
        if os.path.isdir(a):
            run_dir = a
            dir_traces = discover_traces(a)
        elif a.endswith(".jsonl"):
            if _jsonl_kind(a) == "series":
                series = a
            else:
                trace = a
        else:
            with open(a) as fh:
                detail = json.load(fh)
            detail_path = a
    if trace is None and len(dir_traces) == 1:
        trace = dir_traces[0]
    elif trace is None and dir_traces:
        out = measured_stages_multi(dir_traces)
        out["run_dir"] = run_dir
        jobs = discover_jobs(run_dir)
        if jobs:
            out["jobs"] = jobs
        if detail is not None:
            out["detail"] = detail_path
            out["model_ceiling"] = model_ceiling(detail)
        print(json.dumps(out, indent=1))
        st = out["stages"]
        steady = st.get("dispatch", {"sec": 0.0, "count": 0})
        comp = st.get("compile_dispatch", {"sec": 0.0, "count": 0})
        print(
            f"# run-dir report: {len(dir_traces)} traces, "
            f"{len(jobs)} journaled jobs; dispatch {steady['sec']:.3f}s "
            f"({steady['count']} calls), compile-carrying {comp['sec']:.3f}s "
            f"({comp['count']} calls)"
        )
        return
    if detail is None:
        detail, detail_path = _load_default_detail()
    if trace is None and detail is not None:
        trace = detail.get("trace")
    if (trace is None or not os.path.exists(trace)) and series is None and (
        detail is not None
    ):
        # Fallback artifact family: the run recorded a metrics series
        # even though no span trace exists.
        ms = detail.get("metrics_series")
        if ms and os.path.exists(ms):
            series = ms
    if (trace is None or not os.path.exists(trace)) and series is not None:
        out = measured_from_series(series)
        if detail is not None:
            out["detail"] = detail_path
            out["model_ceiling"] = model_ceiling(detail)
        print(json.dumps(out, indent=1))
        print(
            f"# metrics-series report ({out.get('samples', 0)} samples): "
            f"{out.get('generated', 0):,} generated over "
            f"{out.get('wall_s', 0.0):.3f}s -> {out.get('gen_per_s', 0.0):,.0f} "
            "gen/s; per-stage wall-clock needs a span trace (STPU_TRACE) — "
            "series samples only bracket quiescent points"
        )
        return
    if trace is None or not os.path.exists(trace):
        print(
            "no trace: pass a span JSONL (tools/roofline.py --measured "
            "trace.jsonl), a metrics series (STPU_METRICS_TO), or run "
            "bench.py with STPU_TRACE set "
            f"(detail file: {detail_path or 'none found'})"
        )
        sys.exit(1)
    out = measured_stages(trace)
    if detail is not None:
        out["detail"] = detail_path
        out["model_ceiling"] = model_ceiling(detail)
    print(json.dumps(out, indent=1))
    st = out["stages"]
    steady = st.get("dispatch", {"sec": 0.0, "count": 0})
    comp = st.get("compile_dispatch", {"sec": 0.0, "count": 0})
    print(
        f"# measured wall-clock by stage: dispatch {steady['sec']:.3f}s "
        f"({steady['count']} calls), compile-carrying {comp['sec']:.3f}s "
        f"({comp['count']} calls), overflow recovery "
        f"{st.get('overflow_recovery', {}).get('sec', 0.0):.3f}s, "
        f"host-verify {st.get('host_verify', {}).get('sec', 0.0):.3f}s"
    )
    if detail is not None:
        mc = out["model_ceiling"]
        gap = steady["sec"] / max(mc["modeled_sec"], 1e-12)
        print(
            f"# modeled ceiling for the recorded schedule: "
            f"{mc['modeled_sec']:.3f}s ({mc['ceiling_states_per_sec']/1e6:.1f} "
            f"M gen/s, binding: {mc['binding_stage']}); measured steady "
            f"dispatch is {gap:.1f}x the modeled floor — that ratio is the "
            "optimization headroom"
        )


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    if "--phases" in sys.argv:
        _phases_main(args)
        return
    if "--measured" in sys.argv:
        _measured_main(args)
        return
    if args:
        path = args[0]
    else:
        _detail, path = _load_default_detail()
        path = path or "runs/bench_detail.json"
    with open(path) as fh:
        detail = json.load(fh)

    if "--model" in sys.argv:
        out = model_ceiling(detail)
        law = cost_law_rows(detail)
        if law:
            levels = [l for b in detail["levels"] for l in b.get("levels", [])]
            # Mirror cost_law_rows' guard: a mixed detail file (a block
            # appended from a pre-ladder run) must degrade, not KeyError.
            per_level = sorted(
                w for l in levels if (w := l.get("lane_words")) is not None
            )
            out["cost_law"] = {
                "rows": law,
                "instrumented_levels": len(per_level),
                "lane_words_total": sum(per_level),
                "lane_words_per_level": {
                    # statistics.median matches bench.py and cand_ab.py.
                    "median": statistics.median(per_level),
                    "mean": round(sum(per_level) / len(per_level)),
                    "max": per_level[-1],
                },
                "predicted_sort_s": round(
                    sum(r["predicted_sort_s"] for r in law), 4
                ),
                "measured_s": round(
                    sum(r["measured_s"] or 0 for r in law), 4
                ),
            }
        print(json.dumps(out, indent=1))
        if law:
            cl = out["cost_law"]
            print(
                f"# engine-measured cost law: {cl['lane_words_total']:,} "
                f"sorted lane-words over {cl['instrumented_levels']} "
                f"instrumented levels (of {out['levels']}) "
                f"(median {cl['lane_words_per_level']['median']:,}/level, "
                f"mean {cl['lane_words_per_level']['mean']:,}/level); "
                f"predicted sort time {cl['predicted_sort_s']:.3f}s vs "
                f"measured {cl['measured_s']:.3f}s"
            )
        ns_gap = 50e6 / max(out["ceiling_states_per_sec"], 1)
        print(
            f"# modeled ceiling {out['ceiling_states_per_sec']/1e6:.1f} M gen/s "
            f"on this schedule (binding: {out['binding_stage']}); "
            f"north star 50M is {ns_gap:.2f}x {'above' if ns_gap > 1 else 'below'} it"
        )
        # The traffic floor above is NOT what measured runs see: round-3
        # on-chip profiling put the per-superstep FIXED cost (kernel
        # launches, XLA:TPU serialization, tiling tax) at ~475 ms — for a
        # 26-level run that is ~12.4 s of the measured 14.8 s, i.e. the
        # engine is fixed-cost-bound, not traffic-bound. This sweep shows
        # what the same schedule delivers as the fixed cost falls (the
        # round-5 attacks: plane-major buffers, fewer fused kernels).
        gen = out["generated"]
        L = out["levels"]
        traffic = out["modeled_sec"]
        print("# fixed-cost sweep (per-level overhead -> ceiling):")
        for label, fixed in [
            ("r3 measured 475 ms", 0.475),
            ("50 ms", 0.050),
            ("5 ms", 0.005),
            ("traffic floor only", 0.0),
        ]:
            total = traffic + L * fixed
            print(
                f"#   {label:>20}: {gen/total/1e6:8.2f} M gen/s "
                f"({total:.3f} s total)"
            )
        return

    rm = detail.get("rm", 8)
    A = 2 + 5 * rm
    W = 2
    C = detail.get("table_capacity", 1 << 22)

    total_bytes = 0.0
    total_sec = 0.0
    gen_total = 0
    for block in detail.get("levels", []):
        sec = block.get("sec", 0.0)
        total_sec += sec
        for lv in block.get("levels", []):
            F = max(int(lv.get("frontier", 0)), 1)
            gen = int(lv.get("generated", 0))
            gen_total += gen
            bucket = _bucket_for(F, floor=1024)
            grid = bucket * A
            M = max(gen, 1)
            expand_b = (bucket * W + grid * W) * 4
            compact_b = grid * 8 + M * (W + 3) * 4
            insert_b = (C + M) * 12
            frontier_b = M * (W + 1) * 4
            total_bytes += expand_b + compact_b + insert_b + frontier_b
    if total_sec == 0:
        print("no measured levels in", path)
        return
    gbps = total_bytes / total_sec / 1e9
    print(
        f"platform={detail.get('platform')} rm={rm} gen={gen_total:,} "
        f"measured={total_sec:.2f}s"
    )
    print(
        f"logical traffic {total_bytes/1e9:.1f} GB -> achieved "
        f"{gbps:.2f} GB/s logical ({100*gbps/PEAK_GBPS:.2f}% of v5e peak; "
        "sort stages move data ~log-n passes, so >15-25% logical is "
        "already traffic-bound)"
    )
    print(
        f"throughput {gen_total/max(total_sec,1e-9)/1e6:.2f} M gen states/s; "
        f"north-star gap { (50e6 * total_sec) / max(gen_total,1):.1f}x"
    )


if __name__ == "__main__":
    main()
