"""Bandwidth accounting for a measured device-engine run.

The checker is sort/bandwidth-bound (no MXU math), so the honest
"roofline" is HBM traffic: for each committed BFS level this tool
computes the LOGICAL bytes each pipeline stage must move at least once

  expand    frontier read + plane-major grid write        (F*W + A*F*W) * 4
  compact   fused-key sort of the grid + candidate pull   (A*F*(4+4)  + M_lanes) * ~1
  insert    sort of [table_bucket + cand] key planes      (C + M) * 12 (3 ops)
  frontier  survivor pull into the next frontier          M * (W+1) * 4

and divides by the measured wall-clock to report achieved GB/s against
the chip's peak (v5e ~819 GB/s HBM). Numbers well below peak mean the
stage is latency/serialization-bound (the scatter story), not traffic-
bound; sort stages legitimately move the data ~log passes, so their
achieved "logical" bandwidth reads low by that factor — the point of the
table is the RATIO between stages and runs, not absolute MFU.

Usage: python tools/roofline.py [bench_detail.json]
"""

from __future__ import annotations

import json
import sys

PEAK_GBPS = 819.0  # TPU v5e HBM


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else "bench_detail.json"
    with open(path) as fh:
        detail = json.load(fh)
    rm = detail.get("rm", 8)
    A = 2 + 5 * rm
    W = 2
    C = detail.get("table_capacity", 1 << 22)

    total_bytes = 0.0
    total_sec = 0.0
    gen_total = 0
    for block in detail.get("levels", []):
        sec = block.get("sec", 0.0)
        total_sec += sec
        for lv in block.get("levels", []):
            F = max(int(lv.get("frontier", 0)), 1)
            gen = int(lv.get("generated", 0))
            gen_total += gen
            # run bucket: next pow4 with 4x headroom (engine policy)
            bucket = 1024
            while bucket < 4 * F:
                bucket *= 4
            grid = bucket * A
            M = max(gen, 1)
            expand_b = (bucket * W + grid * W) * 4
            compact_b = grid * 8 + M * (W + 3) * 4
            insert_b = (C + M) * 12
            frontier_b = M * (W + 1) * 4
            total_bytes += expand_b + compact_b + insert_b + frontier_b
    if total_sec == 0:
        print("no measured levels in", path)
        return
    gbps = total_bytes / total_sec / 1e9
    print(
        f"platform={detail.get('platform')} rm={rm} gen={gen_total:,} "
        f"measured={total_sec:.2f}s"
    )
    print(
        f"logical traffic {total_bytes/1e9:.1f} GB -> achieved "
        f"{gbps:.2f} GB/s logical ({100*gbps/PEAK_GBPS:.2f}% of v5e peak; "
        "sort stages move data ~log-n passes, so >15-25% logical is "
        "already traffic-bound)"
    )
    print(
        f"throughput {gen_total/max(total_sec,1e-9)/1e6:.2f} M gen states/s; "
        f"north-star gap { (50e6 * total_sec) / max(gen_total,1):.1f}x"
    )


if __name__ == "__main__":
    main()
