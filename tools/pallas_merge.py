"""Chip probe/A/B for the pallas streaming merge-insert
(ops/pallas_merge.py, engaged by STPU_SORTEDSET_INSERT=pallas).

Two open questions only silicon can answer (the host-side lowering
sweep already passed — registry #6's pre-flight):
  1. does Mosaic accept the kernel's ARBITRARY-offset input chunk DMAs
     (the compact kernel only ever proved chunk-aligned ones)? If not,
     the documented fallback is align-down + an in-register one-hot
     shift — build it only when this probe demands it;
  2. is the O(C+m) stream actually faster than the two table-scale
     ``lax.sort``s of the shipping insert at engine shapes?

Rows print host-readback-gated timings (the tunnel's
``block_until_ready`` lies for standalone programs — registry #5).

Usage:  python tools/pallas_merge.py [--cpu]
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _sort_insert(table, batch, cap):
    """The shipping insert's table-scale core at the same shapes: the
    (kh, kl, ticket, vh, vl) 3-key merge sort + the keep-compaction
    sort (sortedset.insert's via_sort path, stripped of the wrapper)."""
    import jax
    import jax.numpy as jnp

    m = batch.shape[1]
    full = jnp.uint32(0xFFFFFFFF)
    kh = jnp.concatenate([table[0], batch[0]])
    kl = jnp.concatenate([table[1], batch[1]])
    vh = jnp.concatenate([table[2], batch[2]])
    vl = jnp.concatenate([table[3], batch[3]])
    ticket = jnp.arange(cap + m, dtype=jnp.int32)
    skh, skl, st, svh, svl = jax.lax.sort((kh, kl, ticket, vh, vl), num_keys=3)
    run_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), (skh[1:] != skh[:-1]) | (skl[1:] != skl[:-1])]
    )
    real = ~((skh == full) & (skl == full))
    is_cand = st >= cap
    winner = run_start & is_cand & real
    keep = real & (winner | ~is_cand)
    ckey = jnp.where(keep, jnp.int32(0), jnp.int32(1))
    _, ckh, ckl, cvh, cvl = jax.lax.sort(
        (ckey, skh, skl, svh, svl), num_keys=1, is_stable=True
    )
    _, win_in_order = jax.lax.sort((st, winner.astype(jnp.int32)), num_keys=1)
    return (
        jnp.stack([ckh[:cap], ckl[:cap], cvh[:cap], cvl[:cap]]),
        win_in_order[cap:],
        jnp.sum(keep, dtype=jnp.int32),
    )


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            ),
        )
    import jax.numpy as jnp

    from stateright_tpu.ops.pallas_merge import merge_insert

    interpret = jax.default_backend() == "cpu"
    rng = np.random.default_rng(17)
    FULL = 0xFFFFFFFF

    def mk(C, m, n_t, n_c):
        tk = np.sort(rng.choice(2**40, n_t, replace=False).astype(np.uint64))
        table = np.full((4, C), FULL, np.uint32)
        table[0, :n_t] = (tk >> 16).astype(np.uint32)
        table[1, :n_t] = (tk & 0xFFFF).astype(np.uint32)
        ck = np.sort(rng.choice(2**40, n_c, replace=True).astype(np.uint64))
        batch = np.full((4, m), FULL, np.uint32)
        batch[0, :n_c] = (ck >> 16).astype(np.uint32)
        batch[1, :n_c] = (ck & 0xFFFF).astype(np.uint32)
        return jnp.asarray(table), jnp.asarray(batch)

    # --- correctness (vs the sort core, small shape) --------------------
    B = 512
    C, m = 1 << 13, 1 << 12
    table, batch = mk(C, m, C // 2, m // 2)
    f_mrg = jax.jit(
        functools.partial(merge_insert, block=B, interpret=interpret)
    )
    f_srt = jax.jit(functools.partial(_sort_insert, cap=C))
    mg, kb, nk = f_mrg(table, batch)
    sg, sb, sn = f_srt(table, batch)
    nk, sn = int(nk), int(sn)
    assert nk == sn, (nk, sn)
    assert np.array_equal(
        np.asarray(mg)[:, :nk], np.asarray(sg)[:, :nk]
    ), "merged planes mismatch"
    assert np.array_equal(
        np.asarray(kb), np.asarray(sb).astype(bool)
    ), "is_new mismatch"
    print(f"merge_insert OK vs sort core: n_keep={nk} of C={C}, m={m}")
    if interpret:
        return  # interpreter timings are meaningless

    # --- perf A/B at engine shapes (host-readback-gated) ----------------
    for log2_c, log2_m in ((22, 19), (22, 22), (24, 22)):
        C, m = 1 << log2_c, 1 << log2_m
        table, batch = mk(C, m, (C * 3) // 8, m // 2)
        f_mrg = jax.jit(functools.partial(merge_insert, block=B))
        f_srt = jax.jit(functools.partial(_sort_insert, cap=C))
        for name, fn in (("merge", f_mrg), ("sort2x", f_srt)):
            try:
                o = fn(table, batch)
                int(np.asarray(o[2]).reshape(-1)[0])  # force
                t0 = time.monotonic()
                for _ in range(3):
                    o = fn(table, batch)
                    int(np.asarray(o[2]).reshape(-1)[0])  # readback gate
                dt = (time.monotonic() - t0) / 3
                print(
                    f"  C=2^{log2_c} m=2^{log2_m} {name}: {dt * 1e3:8.2f} ms",
                    flush=True,
                )
            except Exception as e:
                print(
                    f"  C=2^{log2_c} m=2^{log2_m} {name}: FAILED "
                    f"{type(e).__name__}: {str(e)[:300]}",
                    flush=True,
                )


if __name__ == "__main__":
    main()
