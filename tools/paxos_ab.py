"""On-chip paxos A/B: sorted vs hash visited set, count-checked + audited.

VERDICT round-4 item 2: the round-3 on-chip paxos drift (17,198 unique vs
the pinned 16,668, `/root/reference/examples/paxos.rs:321,345`) happened
under the retired round-2 hash engine; the sorted-default engine has never
run paxos on the chip. This tool closes the question decisively:

  - run paxos 2c/3s packed under dedup=sorted (the accelerator default)
  - run it again under dedup=hash (the round-2 structure, the suspect)
  - for each: check the pinned counts (32,971 generated / 16,668 unique)
    and run the host-side duplicate-key audit of the visited planes
    (stateright_tpu/audit.py — duplicate keys prove insert-admission
    corruption; clean-but-short proves lost entries).

One JSON line per run on stdout; progress on stderr. Exit status: 0 when
every run is count-exact with a clean audit, 2 when any run drifted or
audited dirty (the drift IS the signal — it must not read as success),
1 on harness errors. Run under `timeout` (the axon tunnel wedges rather
than failing).

Usage: python tools/paxos_ab.py [--cpu] [--deep]
  --deep additionally runs 2pc rm=6 under hash (the other shape class:
  wide words + a mid-run table growth, the round-3 drift signature).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PINNED = {
    "paxos 2c/3s": (32_971, 16_668),
    "2pc rm=6": (402_306, 50_816),
}


def run_one(name: str, build, dedup: str, **spawn_kwargs) -> dict:
    from stateright_tpu.audit import audit_table

    model = build()
    checker = model.checker().spawn_xla(dedup=dedup, **spawn_kwargs)
    t0 = time.monotonic()
    while not checker.is_done():
        checker._run_block()
    warm = time.monotonic() - t0
    # Second, measured pass on the same model (compiled supersteps cached).
    checker = model.checker().spawn_xla(dedup=dedup, **spawn_kwargs)
    t0 = time.monotonic()
    while not checker.is_done():
        checker._run_block()
    sec = time.monotonic() - t0
    gen, uniq = checker.state_count(), checker.unique_state_count()
    exp = PINNED[name]
    row = {
        "config": name,
        "dedup": dedup,
        "generated": gen,
        "unique": uniq,
        "pinned": list(exp),
        "count_ok": (gen, uniq) == exp,
        "warm_sec": round(warm, 2),
        "measured_sec": round(sec, 3),
        "states_per_sec": round(gen / max(sec, 1e-9), 1),
    }
    try:
        row["audit"] = audit_table(checker)
    except Exception as e:  # diagnostic path must not kill the A/B
        row["audit"] = {"error": f"{type(e).__name__}: {e}"}
    # Per-level telemetry: on a drift, diffing this against the CPU run of
    # the same job pinpoints the first divergent BFS level (and hence the
    # bucket shape whose program is suspect).
    row["levels"] = checker.level_log
    return row


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    platform = jax.devices()[0].platform
    print(f"[paxos_ab] platform={platform}", file=sys.stderr, flush=True)

    from stateright_tpu.models.paxos import PackedPaxos

    jobs = [
        # Ladder is explicit in every job: the round-5 on-chip matrix saw a
        # DEFLATED paxos count (19,024/9,546 — lost states) under the
        # default "jump" ladder while the ramp-pinned flagship was exact in
        # the same tunnel window, so jump-vs-ramp is itself a variable
        # under test here, not a nuisance parameter.
        ("paxos 2c/3s", lambda: PackedPaxos(2, 3), "sorted",
         dict(frontier_capacity=1 << 12, table_capacity=1 << 16,
              ladder="jump")),
        ("paxos 2c/3s", lambda: PackedPaxos(2, 3), "sorted",
         dict(frontier_capacity=1 << 12, table_capacity=1 << 16,
              ladder="ramp")),
        ("paxos 2c/3s", lambda: PackedPaxos(2, 3), "hash",
         # 2^17 at the hash 1/4-load rule avoids a mid-run growth for
         # 16,668 uniques; a SECOND hash run below crosses growth on
         # purpose (the round-3 drift fired on a growth-crossing run).
         dict(frontier_capacity=1 << 12, table_capacity=1 << 17,
              ladder="ramp")),
        ("paxos 2c/3s", lambda: PackedPaxos(2, 3), "hash",
         dict(frontier_capacity=1 << 12, table_capacity=1 << 14,
              ladder="ramp")),
    ]
    if "--deep" in sys.argv:
        from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

        jobs.append(
            ("2pc rm=6", lambda: PackedTwoPhaseSys(6), "hash",
             dict(frontier_capacity=1 << 15, table_capacity=1 << 17))
        )
    clean = True
    for name, build, dedup, kw in jobs:
        print(f"[paxos_ab] {name} dedup={dedup} {kw} ...", file=sys.stderr, flush=True)
        try:
            row = run_one(name, build, dedup, **kw)
            if not (row["count_ok"] and row["audit"].get("ok", False)):
                clean = False
        except Exception as e:
            row = {"config": name, "dedup": dedup,
                   "error": f"{type(e).__name__}: {e}"}
            clean = False
        row["platform"] = platform
        print(json.dumps(row), flush=True)
    if not clean:
        sys.exit(2)


if __name__ == "__main__":
    main()
