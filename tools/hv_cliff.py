"""Characterize the host-verified sampling cliff at 5 clients.

VERDICT r4 weak #6 / item 7: past ``MAX_PATTERNS_EXACT`` (first hit at 5
clients x 2 ops = 1.68e8 interleavings, single-copy register) the device
serializer runs a SAMPLED one-sided pass — True proves serializability,
False means unknown — and every unknown row costs an exact host
confirmation (``_confirm_hv_candidates``). This tool measures the trade
the ``pattern_limit`` knob controls, on a bounded 5c/1s run:

  flagged        rows the sampled pass could not clear
  flag rate      flagged / generated (the predicate's false-alarm rate —
                 5c/1s reaches full coverage with zero violations, so
                 EVERY flag is a false alarm)
  host share     host confirmation seconds / total seconds

One JSON line per pattern_limit on stdout; progress on stderr. Run under
`timeout`; pattern_limit sweeps small->large so a budget kill keeps the
cheap rows.

Usage: python tools/hv_cliff.py [--cpu] [--target N] [--limits a,b,c]
Defaults: target 30,000 generated states; limits 512,4096,20000.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    args = sys.argv[1:]
    if "--cpu" in args:
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    target = 30_000
    limits = [512, 4_096, 20_000]
    if "--target" in args:
        target = int(args[args.index("--target") + 1])
    if "--limits" in args:
        limits = [int(x) for x in args[args.index("--limits") + 1].split(",")]
    platform = jax.devices()[0].platform
    print(f"[hv_cliff] platform={platform} target={target}", file=sys.stderr, flush=True)

    from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister

    for limit in limits:
        print(f"[hv_cliff] pattern_limit={limit} ...", file=sys.stderr, flush=True)
        try:
            model = PackedSingleCopyRegister(5, 1, pattern_limit=limit)
            checker = (
                model.checker()
                .target_state_count(target)
                .spawn_xla(
                    frontier_capacity=1 << 14,
                    table_capacity=1 << 18,
                    host_verified_cap=1 << 14,
                )
            )
            t0 = time.monotonic()
            while not checker.is_done():
                checker._run_block()
            total = time.monotonic() - t0
            s = checker.hv_stats
            gen = checker.state_count()
            row = {
                "config": "single-copy-register 5c/1s packed (bounded)",
                "platform": platform,
                "pattern_limit": limit,
                "generated": gen,
                "unique": checker.unique_state_count(),
                "depth": checker.max_depth(),
                "total_sec": round(total, 2),
                "flagged": int(s["flagged"]),
                "host_checked": int(s["host_checked"]),
                "cleared": int(s["cleared"]),
                "confirmed": int(s["confirmed"]),
                "host_sec": round(s["host_sec"], 2),
                "flag_rate": round(s["flagged"] / max(gen, 1), 5),
                "host_share": round(s["host_sec"] / max(total, 1e-9), 3),
            }
        except Exception as e:
            row = {"pattern_limit": limit, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
