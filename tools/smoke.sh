#!/usr/bin/env bash
# Tier-0 smoke: a <8-minute subset to run BEFORE the ~50-minute full
# suite — the lint gate, the observability schemas (trace/heartbeat/
# metrics/dispatch_log consumers parse these), one fused-vs-single
# exactness pin (the engine's semantic contract), one packed-model
# end-to-end check, a <30s kill-and-resume crash drill (SIGKILL a
# supervised worker, resume from its auto-checkpoint, exact pinned
# counts — the recovery stack's tier-0 proof), the <30s SERVICE
# crash drill (a CheckerService job SIGKILLed mid-superstep requeues,
# resumes from its per-job checkpoint, exact counts + Chrome trace — the
# multi-tenant pool's tier-0 proof), and the <30s SERVICE RESTART drill
# (the service process itself dies right after journaling `started`; the
# restart replays the job journal, kills the orphaned worker, requeues,
# and converges to exact counts — the durability tier's tier-0 proof),
# and the <30s TELEMETRY drill (one packed model with the metrics
# recorder on, /.metrics scraped from a make_app instance and validated
# with the OpenMetrics test parser, counters cross-checked exactly),
# and the <30s FLEET FAILOVER drill (a 2-device FleetService;
# device.lost kills one device's pool mid-job, the victim migrates to
# the survivor and completes bit-identical — the fleet tier's tier-0
# proof), and the <30s MUX BATCHING drill (a mux_k=3 pool runs three
# co-queued same-spec jobs as ONE worker.py --mux invocation — exact
# pinned counts per member, per-lane mux provenance, pool gauges,
# journaled mux_group starts — the batched-scheduling tier's tier-0
# proof), and the <30s TRACE MERGE drill (a phases-profiled packed model
# plus a traced 2-job service round merge via obs/collect.py into one
# Chrome trace: schema valid, monotonic timeline, flow arrows resolve,
# phases partition their dispatch — the distributed-tracing tier's
# tier-0 proof), and the <30s QOS SHED drill (class-aware admission on a
# saturated pool: best_effort sheds first with a measured Retry-After,
# batch sheds at its own threshold, interactive admits until the hard
# cap, quotas/gauges/deadline validation pinned — the QoS tier's tier-0
# proof), and the <30s SYMMETRY drill (device symmetry reduction on one
# packed model: the spec-compiled canonicalization collapses 2pc rm=3's
# 288 states to the pinned 80 equivalence classes, bit-equal to the host
# object-state oracle, with the spec tag in metrics — the symmetry
# tier's tier-0 proof).
# A red here means don't bother starting the full run.
#
# Usage: tools/smoke.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

# Lint stage (stpu-lint, docs/static-analysis.md): the pinned
# backend-miscompile rules enforced over every shipped kernel surface —
# CPU-only, no device, <60 s. The JSON verdict lands in runs/lint.json,
# which bench.py folds into bench_detail.json provenance as lint_ok.
mkdir -p runs
timeout -k 5 60 python tools/stpu_lint.py --json-out runs/lint.json

# Perf-regression gate self-test (tools/bench_regress.py, ISSUE 13): the
# gate proves its three typed verdicts against the committed
# runs/archive trajectory — pass on the real lines, fail on a
# synthetically degraded one, "no_baseline" on an empty dir. Pure JSON,
# no jax, <5 s.
timeout -k 5 60 python tools/bench_regress.py --self-test

exec timeout -k 10 480 python -m pytest \
  tests/test_obs.py \
  tests/test_promexport.py::test_smoke_metrics_endpoint \
  tests/test_fused_dispatch.py::test_fused_matches_single_full_coverage \
  tests/test_packed_increment.py \
  tests/test_supervise.py::test_smoke_kill_resume \
  tests/test_service.py::test_smoke_service_kill_resume \
  tests/test_service.py::test_smoke_fleet_failover \
  tests/test_service.py::test_smoke_qos_shed \
  tests/test_service_durability.py::test_smoke_service_restart_resume \
  tests/test_mux.py::test_smoke_mux \
  tests/test_trace_collect.py::test_smoke_trace_merge \
  tests/test_symmetry.py::test_smoke_symmetry \
  -x -q -p no:cacheprovider "$@"
