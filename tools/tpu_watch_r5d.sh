#!/usr/bin/env bash
# Round-5d tunnel watcher — v2 of tools/tpu_watch_r5c.sh after the
# 04:19 window: the tunnel wedged mid-compile of the delta+pallas stack
# bench and the v1 watcher would have burned every later stage's
# timeout against the dead tunnel before re-probing. Changes:
#   * probe the tunnel BEFORE each stage; if it is down, return to the
#     wait loop instead of running the remaining stages into timeouts
#   * stage-completion markers (.r5d_markers/) so a later window skips
#     what an earlier one finished — short windows make progress
#   * the combined delta+pallas stack bench is split into delta-only,
#     pallas-only, then stack, each committed separately: if a lowering
#     wedges the chip we learn WHICH one, and the winners are
#     attributable (the defaults decision needs per-knob numbers)
#   * the cheap pallas synthetic probe runs first — the pallas kernel
#     has never executed on real silicon and is the prime wedge suspect
# bench.py falls back to CPU when the tunnel dies, so bench stages only
# count as done when the emitted JSON line says tpu.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch_r5d.log
MARK=.r5d_markers
mkdir -p "$MARK"
log() { echo "[watch $(date +%H:%M:%S)] $*" >>"$LOG"; }
probe() { timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; }
commit_stage() {
  local msg=$1 f; shift
  for f in "$@" "$LOG"; do
    git add -f -- "$f" >>"$LOG" 2>&1 || log "artifact missing: $f"
  done
  git commit -q -m "$msg" >>"$LOG" 2>&1 && log "committed: $msg"
}
done_p() { [ -f "$MARK/$1" ]; }
mark() { touch "$MARK/$1"; }

# run_tool NAME TIMEOUT LOGFILE CMD... — marker on rc==0 (the axon
# platform is pinned by sitecustomize, so a tool that ran to rc==0 ran
# on the chip; a wedge times out and leaves no marker).
run_tool() {
  local name=$1 tmo=$2 out=$3; shift 3
  done_p "$name" && { log "skip $name (done)"; return 0; }
  probe || { log "tunnel down before $name; back to wait"; return 1; }
  log "stage $name: $*"
  timeout "$tmo" "$@" >"$out" 2>&1
  local rc=$?
  log "$name rc=$rc: $(tail -c 250 "$out" 2>/dev/null)"
  [ $rc -eq 0 ] && mark "$name"
  commit_stage "TPU r5d $name (rc=$rc)" "$out"
  return 0
}

# run_bench NAME TIMEOUT OUTJSON ENV... — marker needs rc==0 AND a tpu
# JSON line (bench.py silently falls back to a cpu worker otherwise).
run_bench() {
  local name=$1 tmo=$2 out=$3; shift 3
  done_p "$name" && { log "skip $name (done)"; return 0; }
  probe || { log "tunnel down before $name; back to wait"; return 1; }
  log "stage $name: bench.py $*"
  timeout "$tmo" env "$@" python bench.py >"$out" 2>>"$LOG"
  local rc=$?
  log "$name rc=$rc: $(tail -c 300 "$out" 2>/dev/null)"
  if [ $rc -eq 0 ] && grep -q 'spawn_xla, tpu' "$out"; then mark "$name"; fi
  commit_stage "TPU r5d $name (rc=$rc)" "$out" bench_detail.json bench_probe.log
  return 0
}

log "watcher v2 started (pid $$)"
while true; do
  if probe; then
    log "TUNNEL UP — staged pass"
    # 0. pallas synthetic probe — never run on silicon; prime wedge suspect
    run_tool pallas_probe 1200 tpu_pallas_compact.log \
      python tools/pallas_compact.py || { sleep 240; continue; }
    # 1. delta-only bench (headline config, no matrix)
    run_bench bench_delta 2400 bench_r5d_delta.json \
      BENCH_DEDUP=delta BENCH_MATRIX=0 || { sleep 240; continue; }
    # 2. pallas-only bench
    run_bench bench_pallas 2400 bench_r5d_pallas.json \
      STPU_COMPACTION=pallas BENCH_MATRIX=0 || { sleep 240; continue; }
    # 3. full attack stack
    run_bench bench_stack 2400 bench_r5d_stack.json \
      BENCH_DEDUP=delta STPU_COMPACTION=pallas BENCH_MATRIX=0 || { sleep 240; continue; }
    # 4. superstep profile incl. mixed-lowering A/B rows
    run_tool profile 2700 tpu_profile_r5c.log \
      python tools/profile_superstep.py 8 || { sleep 240; continue; }
    # 5. sort-dtype A/B (key packing decision)
    run_tool sortbench 1200 tpu_sortbench.log \
      python tools/sortbench.py 23 || { sleep 240; continue; }
    # 6. engine-level packed-keys A/B
    run_tool packed_ab 2400 tpu_packed_ab.log \
      python tools/packed_ab.py 8 || { sleep 240; continue; }
    # 7. scale soak rm=10/11 + paxos 3c/3s + delta retries
    run_tool soak 7200 tpu_soak_r5d.log \
      python tools/tpu_soak.py --skip-rm9 || { sleep 240; continue; }
    if done_p pallas_probe && done_p bench_delta && done_p bench_pallas \
       && done_p bench_stack && done_p profile && done_p sortbench \
       && done_p packed_ab && done_p soak; then
      log "all stages done; watcher exiting"
      exit 0
    fi
    log "pass finished with unfinished stages; resuming watch"
  else
    log "tunnel down"
  fi
  sleep 240
done
