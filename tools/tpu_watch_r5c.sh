#!/usr/bin/env bash
# Round-5c tunnel watcher. Context: the shrink-exit engine change (new
# fused-program signature) has no chip number yet, and the rm=10/11 +
# paxos 3c/3s soak plus the redesigned-delta retries have never
# completed (two rm=10 attempts froze on tunnel wedges). On recovery:
#   1. bench.py — headline first: the shrink-exit engine's number, with
#      count checks + audit (windows can be short)
#   2. profile_superstep 8 — dispatch-log + mixed-lowering A/Bs
#   3. tpu_soak --skip-rm9 — the queued scale soak + delta retries
# Artifacts commit AFTER EACH STAGE; only files this watcher produced
# are staged.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch_r5c.log
log() { echo "[watch $(date +%H:%M:%S)] $*" >>"$LOG"; }
commit_stage() {
  local msg=$1 f; shift
  for f in "$@" "$LOG"; do
    git add -f -- "$f" >>"$LOG" 2>&1 || log "artifact missing: $f"
  done
  git commit -q -m "$msg" >>"$LOG" 2>&1 && log "committed: $msg"
}
log "watcher started (pid $$)"
while true; do
  if timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; then
    log "TUNNEL UP — stage 1: bench (shrink-exit engine, fresh fused signature)"
    timeout 3600 python bench.py >bench_r5d_out.json 2>>"$LOG"
    rc1=$?
    log "bench rc=$rc1: $(tail -c 300 bench_r5d_out.json 2>/dev/null)"
    commit_stage "TPU r5c: bench with the shrink-exit engine (rc=$rc1)" \
      bench_r5d_out.json bench_detail.json bench_probe.log

    log "stage 1b: attack-stack bench (delta dedup + pallas compaction)"
    BENCH_DEDUP=delta STPU_COMPACTION=pallas BENCH_MATRIX=0 \
      timeout 2400 python bench.py >bench_r5d_stack.json 2>>"$LOG"
    rc1b=$?
    log "stack bench rc=$rc1b: $(tail -c 300 bench_r5d_stack.json 2>/dev/null)"
    commit_stage "TPU r5c: attack-stack bench delta+pallas (rc=$rc1b)" \
      bench_r5d_stack.json

    log "stage 2: sort-dtype A/B (key packing) + pallas compaction A/B + superstep profile"
    timeout 1200 python tools/sortbench.py 23 >tpu_sortbench.log 2>&1
    rc2a=$?
    log "sortbench rc=$rc2a: $(tail -c 200 tpu_sortbench.log 2>/dev/null)"
    timeout 1200 python tools/pallas_compact.py >tpu_pallas_compact.log 2>&1
    rc2p=$?
    log "pallas_compact rc=$rc2p: $(tail -c 200 tpu_pallas_compact.log 2>/dev/null)"
    git add -f tpu_pallas_compact.log >>"$LOG" 2>&1
    timeout 2400 python tools/packed_ab.py 8 >tpu_packed_ab.log 2>&1
    rc2k=$?
    log "packed_ab rc=$rc2k: $(tail -c 300 tpu_packed_ab.log 2>/dev/null)"
    git add -f tpu_packed_ab.log >>"$LOG" 2>&1
    timeout 2700 python tools/profile_superstep.py 8 >tpu_profile_r5c.log 2>&1
    rc2=$?
    log "profile rc=$rc2"
    commit_stage "TPU r5c: sortbench dtype A/B + superstep profile (rc=$rc2a/$rc2)" \
      tpu_sortbench.log tpu_profile_r5c.log

    log "stage 3: scale soak rm=10/11 + paxos 3c/3s + delta retries"
    timeout 7200 python tools/tpu_soak.py --skip-rm9 >tpu_soak_r5d.log 2>&1
    rc3=$?
    log "soak rc=$rc3: $(tail -c 300 tpu_soak_r5d.log 2>/dev/null)"
    commit_stage "TPU r5c: scale soak rm=10/11 + paxos 3c/3s + delta retries (rc=$rc3)" \
      tpu_soak_r5d.log

    if [ "$rc1" -eq 0 ] && [ "$rc2" -eq 0 ] && [ "$rc3" -eq 0 ]; then
      log "all stages done; watcher exiting"
      exit 0
    fi
    log "a stage failed; resuming watch"
  else
    log "tunnel down"
  fi
  sleep 240
done
