"""Engine-level A/B: u64-packed sort lanes vs u32 pairs, on this backend.

Each variant runs in its own SUBPROCESS: STPU_SORTEDSET_KEYS is a
trace-time constant (the documented process-restart A/B convention) and
packed mode needs ``jax_enable_x64`` enabled before first backend use —
neither may leak into the other variant. The child runs a full
count-checked 2pc rm=N check on the sorted engine (warm pass compiles,
measured pass times) and prints one JSON line; the parent just relays.

Usage: python tools/packed_ab.py [rm] [--cpu]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
import jax
if {cpu!r} == "cpu":
    jax.config.update("jax_platforms", "cpu")
else:
    jax.config.update("jax_compilation_cache_dir", {repo!r} + "/.jax_cache")
if os.environ.get("STPU_SORTEDSET_KEYS") == "packed":
    jax.config.update("jax_enable_x64", True)
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
sys.path.insert(0, {repo!r})
from bench import EXPECTED_2PC as EXPECTED

rm = {rm}
fcap, tcap = 1 << 19, 1 << 22
if {cpu!r} == "cpu":
    rm = min(rm, 6)
    fcap, tcap = 1 << 15, 1 << 17
m = PackedTwoPhaseSys(rm)
t0 = time.monotonic()
m.checker().spawn_xla(dedup="sorted", frontier_capacity=fcap, table_capacity=tcap).join()
warm = time.monotonic() - t0
c = m.checker().spawn_xla(dedup="sorted", frontier_capacity=fcap, table_capacity=tcap)
t0 = time.monotonic()
c.join()
dt = time.monotonic() - t0
want = EXPECTED.get(rm)
ok = want is None or (c.state_count(), c.unique_state_count()) == want
print(json.dumps({{
    "keys": os.environ.get("STPU_SORTEDSET_KEYS", "pair"),
    "rm": rm, "warm_s": round(warm, 2), "measured_s": round(dt, 3),
    "gen_per_s": round(c.state_count() / dt, 1),
    "gen": c.state_count(), "uniq": c.unique_state_count(),
    "count_ok": bool(ok),
}}))
"""


def main() -> None:
    cpu = "cpu" if "--cpu" in sys.argv else "tpu"
    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
    rm = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    code = CHILD.format(repo=REPO, cpu=cpu, rm=rm)
    for keys in ("pair", "packed"):
        env = dict(os.environ)
        env["STPU_SORTEDSET_KEYS"] = keys
        env["STPU_SORTEDSET_VALUES"] = "sort"
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            capture_output=True,
            text=True,
            timeout=2400,
        )
        line = (proc.stdout.strip().splitlines() or ["(no output)"])[-1]
        print(line, flush=True)
        if proc.returncode != 0:
            print(
                json.dumps(
                    {"keys": keys, "error": proc.stderr.strip()[-400:]}
                ),
                flush=True,
            )


if __name__ == "__main__":
    main()
