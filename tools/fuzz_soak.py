"""Extended randomized differential soak (a driver, not a test).

Runs the suite's differential-fuzz logic at many more seeds for a
wall-clock budget: random graphs across all engine configurations (exact
count agreement), plus device-serializer fuzz vs the host backtracking testers
at several (threads, ops, spec, consistency) shapes. Any disagreement is a
real bug; the run prints one PASS/FAIL line per batch and a final summary.

Usage: python tools/fuzz_soak.py [budget_seconds] [seed_base]
(CPU backend forced; seed_base defaults to 10000 — pass a different base
to cover fresh graphs/histories instead of repeating the standard run).
"""

from __future__ import annotations

import os
import random
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def graph_batch(seed0: int, n: int) -> int:
    import jax

    from stateright_tpu.core import Property
    from stateright_tpu.parallel import default_mesh
    from stateright_tpu.test_util import DGraph, PackedDGraph

    KW = dict(frontier_capacity=1 << 10, table_capacity=1 << 13)
    mesh = default_mesh(8) if len(jax.devices()) >= 8 else None
    for seed in range(seed0, seed0 + n):
        rng = random.Random(seed)
        g = DGraph.with_property(
            Property.sometimes("unreachable", lambda _m, _s: False)
        )
        n_nodes = rng.randint(4, 40)
        for _ in range(rng.randint(1, 6)):
            g = g.with_path(
                [rng.randrange(n_nodes) for _ in range(rng.randint(1, 7))]
            )
        oracle = g.checker().spawn_bfs().join()
        expect = (
            oracle.state_count(),
            oracle.unique_state_count(),
            oracle.max_depth(),
        )
        dev = PackedDGraph(g).checker().spawn_xla(**KW).join()
        got = (dev.state_count(), dev.unique_state_count(), dev.max_depth())
        assert got == expect, f"seed {seed}: xla {got} != oracle {expect}"
        srt = PackedDGraph(g).checker().spawn_xla(dedup="sorted", **KW).join()
        got = (srt.state_count(), srt.unique_state_count(), srt.max_depth())
        assert got == expect, f"seed {seed}: xla-sorted {got} != oracle {expect}"
        # A tiny delta tier (MIN_DELTA=4) forces the in-kernel flush path
        # on nearly every level even for these small graphs.
        from stateright_tpu.ops import deltaset

        saved_min = deltaset.MIN_DELTA
        deltaset.MIN_DELTA = 4
        try:
            dlt = (
                PackedDGraph(g)
                .checker()
                .spawn_xla(dedup="delta", **dict(KW, table_capacity=1 << 11))
                .join()
            )
        finally:
            deltaset.MIN_DELTA = saved_min
        got = (dlt.state_count(), dlt.unique_state_count(), dlt.max_depth())
        assert got == expect, f"seed {seed}: xla-delta {got} != oracle {expect}"
        if mesh is not None and seed % 4 == 0:
            sh = PackedDGraph(g).checker().spawn_xla(mesh=mesh, **KW).join()
            got = (sh.state_count(), sh.unique_state_count(), sh.max_depth())
            assert got == expect, f"seed {seed}: sharded {got} != {expect}"
        if mesh is not None and seed % 4 == 2:
            sh = (
                PackedDGraph(g)
                .checker()
                .spawn_xla(mesh=mesh, dedup="sorted", **KW)
                .join()
            )
            got = (sh.state_count(), sh.unique_state_count(), sh.max_depth())
            assert got == expect, f"seed {seed}: sharded-sorted {got} != {expect}"
        if seed % 8 == 0:
            par = g.checker().threads(3).spawn_bfs().join()
            got = (par.state_count(), par.unique_state_count(), par.max_depth())
            assert got == expect, f"seed {seed}: threads {got} != {expect}"
        if seed % 8 == 4:
            # Job-market parallel DFS (round 4): full-coverage COUNTS are
            # engine-invariant (the fuzz graphs carry an undiscoverable
            # property, so every run sweeps the space); max_depth is
            # first-visit depth — visit-order-dependent under DFS — and is
            # only bounded below by the BFS eccentricity.
            pdf = g.checker().threads(3).spawn_dfs().join()
            got = (pdf.state_count(), pdf.unique_state_count())
            assert got == expect[:2], f"seed {seed}: threads-dfs {got} != {expect[:2]}"
            assert pdf.max_depth() >= expect[2], (
                f"seed {seed}: threads-dfs depth {pdf.max_depth()} < BFS {expect[2]}"
            )
    return n


def semantics_batch(seed0: int, trials: int) -> int:
    sys.path.insert(0, os.path.join(_REPO, "tests"))
    from test_device_semantics import (
        _device_verdicts,
        _random_events,
        _replay,
    )

    import numpy as np

    from stateright_tpu.actor.register import history_codecs
    from stateright_tpu.actor.write_once_register import wo_history_codecs
    from stateright_tpu.semantics.device import DeviceRegister, DeviceWORegister
    from stateright_tpu.semantics.linearizability import LinearizabilityTester
    from stateright_tpu.semantics.register import (
        Read,
        ReadOk,
        Register,
        Write,
        WriteOk,
    )
    from stateright_tpu.semantics.sequential_consistency import (
        SequentialConsistencyTester,
    )
    from stateright_tpu.semantics.write_once_register import (
        Read as WORead,
        ReadOk as WOReadOk,
        WORegister,
        Write as WOWrite,
        WriteFail,
        WriteOk as WOWriteOk,
    )

    total = 0
    # (4, 2) exercises the round-4 CHUNKED exact path (369,600 patterns
    # under lax.scan); fewer trials — each history is ~200x a 3x2 check.
    for T, M in ((2, 2), (3, 2), (2, 3), (3, 3), (4, 2)):
        t_trials = trials if T < 4 else max(2, trials // 20)
        for spec_name in ("register", "wo"):
            for real_time in (True, False):
                rng = random.Random(seed0 * 7919 + T * 100 + M * 10 + real_time)
                values = [None] + [chr(ord("A") + k) for k in range(T)]
                if spec_name == "register":
                    op_code, _, ret_code, _ = history_codecs(values)
                    ops_of = lambda: [Read()] + [Write(v) for v in values[1:]]
                    rets_of = lambda op: [WriteOk()] + [ReadOk(v) for v in values]
                    spec = DeviceRegister()
                    base = Register(None)
                else:
                    op_code, _, ret_code, _ = wo_history_codecs(values)
                    ops_of = lambda: [WORead()] + [WOWrite(v) for v in values[1:]]
                    rets_of = lambda op: [WOWriteOk(), WriteFail()] + [
                        WOReadOk(v) for v in values
                    ]
                    spec = DeviceWORegister()
                    base = WORegister(None)
                make = (
                    (lambda: LinearizabilityTester(base.clone()))
                    if real_time
                    else (lambda: SequentialConsistencyTester(base.clone()))
                )
                testers = [
                    _replay(_random_events(rng, T, M, ops_of, rets_of), make())
                    for _ in range(t_trials)
                ]
                got = _device_verdicts(
                    testers, T, M, 3, 3, op_code, ret_code, spec, real_time
                )
                want = np.array(
                    [h.serialized_history() is not None for h in testers]
                )
                assert (got == want).all(), (
                    f"{spec_name} T={T} M={M} rt={real_time}: "
                    f"{int(np.sum(got != want))} disagreements"
                )
                total += t_trials
    return total


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    budget = float(sys.argv[1]) if len(sys.argv) > 1 else 1800.0
    seed_base = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    t0 = time.monotonic()
    graphs = sems = batch = 0
    while time.monotonic() - t0 < budget:
        graphs += graph_batch(seed_base + batch * 16, 16)
        sems += semantics_batch(seed_base + batch, 60)
        batch += 1
        print(
            f"[fuzz_soak] batch {batch}: {graphs} graphs, {sems} histories, "
            f"{time.monotonic()-t0:.0f}s — all engines agree",
            flush=True,
        )
    print(
        f"[fuzz_soak] DONE: {graphs} random graphs x 7 engine configs and {sems} "
        f"random histories x device-vs-host serializers, zero disagreements "
        f"in {time.monotonic()-t0:.0f}s",
        flush=True,
    )


if __name__ == "__main__":
    main()
