#!/usr/bin/env bash
# Round-5b tunnel watcher: the interactive session already landed the
# scatter-miscompile fix, the lowering-default A/Bs, the green bench, and
# the rm=9 soak. What remains on tunnel recovery, in priority order:
#   1. scale soak rm=10/11 + paxos 3c/3s (sorted structure; the delta
#      structure faults the TPU runtime and stays chip-blocked)
#   2. final bench.py — platform-resolved jump primary off the warm cache
# Artifacts commit AFTER EACH STAGE; only files this watcher produced are
# staged.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch_r5b.log
log() { echo "[watch $(date +%H:%M:%S)] $*" >>"$LOG"; }
commit_stage() {
  local msg=$1 f; shift
  for f in "$@" "$LOG"; do
    git add -f -- "$f" >>"$LOG" 2>&1 || log "artifact missing: $f"
  done
  git commit -q -m "$msg" >>"$LOG" 2>&1 && log "committed: $msg"
}
log "watcher started (pid $$)"
while true; do
  if timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; then
    log "TUNNEL UP — stage 1: bench (headline first: the grid-sort and
    cand-cap changes are unmeasured on chip; windows can be short)"
    timeout 3600 python bench.py >bench_r5_final.json 2>>"$LOG"
    rc1=$?
    log "bench rc=$rc1: $(tail -c 300 bench_r5_final.json 2>/dev/null)"
    commit_stage "TPU r5: bench with derived-parent grid sort + snug cand caps (rc=$rc1)" \
      bench_r5_final.json bench_detail.json bench_probe.log

    log "stage 2: scale soak (rm=10/11 + paxos 3c/3s, sorted; delta retries last)"
    timeout 5400 python tools/tpu_soak.py --skip-rm9 >tpu_soak_r5b.log 2>&1
    rc2=$?
    log "soak rc=$rc2: $(tail -c 300 tpu_soak_r5b.log 2>/dev/null)"
    commit_stage "TPU r5 stage 4 (resumed): scale soak rm=10/11 + paxos 3c/3s + delta retries (rc=$rc2)" \
      tpu_soak_r5b.log

    if [ "$rc1" -eq 0 ] && [ "$rc2" -eq 0 ]; then
      log "all stages done; watcher exiting"
      exit 0
    fi
    log "a stage failed; resuming watch"
  else
    log "tunnel down"
  fi
  sleep 240
done
