"""Localize the on-chip paxos count drift to a specific program shape.

Round-5 on-chip finding (tpu_paxos_ab.jsonl): paxos 2c/3s drifts on TPU
under BOTH visited-set structures and BOTH ladders, while the same engine
is count-exact on CPU and 2pc is count-exact on the same chip:

  - sorted+ramp inflates to 33,752/17,198 — byte-distinct table keys
    (audit clean), the exact totals the round-3 HASH engine produced,
    so the divergence is upstream of the insert;
  - sorted+jump (which replays levels in larger reused buckets)
    under-generates from identical frontier widths (899 gen from 297
    rows where the oracle makes 925 from 286) — the expansion itself
    computes differently at some bucket shapes.

This tool bisects by stage and shape:

  capture (CPU): run the level-synchronous engine one level per
    dispatch, snapshotting the exact frontier rows fed to each level and
    the successor grid + validity the CPU program computes from them.

  replay (TPU): feed the captured frontiers to the same jitted
    programs the engine builds — fingerprint, bare expand (vmap of
    packed_step), expand+transpose+reshape (the engine's fused "rows"
    layout), and the "planes" layout variant — at several bucket
    capacities, and bit-compare against the CPU truth.

A mismatch names the level, bucket, stage, lane, and word — the shape
to pin and the lowering to avoid (the method that found the XLA:CPU
transpose-into-vmap miscompile, xla.py:_build_superstep_planes).

Usage:
  python tools/paxos_diag.py capture        # CPU; writes paxos_diag.npz
  python tools/paxos_diag.py replay         # on the chip; reads the npz
  python tools/paxos_diag.py replay --cpu   # control: must be all-zero
Run replay under `timeout` — the axon tunnel wedges rather than failing.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NPZ = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "paxos_diag.npz")
# Levels around the first observed divergences (frontier widths 26..867).
CAPTURE_DEPTHS = tuple(range(4, 11))
REPLAY_CAPS = (64, 256, 1024, 2048, 4096)


def _step3(model):
    import jax.numpy as jnp

    def step3(words):
        out = model.packed_step(words)
        if len(out) == 3:
            return out
        nxt, valid = out
        return nxt, valid, jnp.zeros_like(valid)

    return step3


def capture() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from stateright_tpu.models.paxos import PackedPaxos
    from stateright_tpu.ops import fphash

    model = PackedPaxos(2, 3)
    ck = model.checker().spawn_xla(
        frontier_capacity=1 << 12, table_capacity=1 << 16,
        dedup="sorted", ladder="ramp", levels_per_dispatch=1,
    )
    step3 = _step3(model)
    expand = jax.jit(lambda f: jax.vmap(step3)(f))
    out: dict = {}
    while not ck.is_done():
        depth = ck._depth
        n = ck._frontier_count
        if depth in CAPTURE_DEPTHS and n > 0:
            rows = np.asarray(ck._frontier)[:n]
            nxt, valid, _ = expand(jnp.asarray(rows))
            fhi, flo = fphash.fingerprint_words(jnp.asarray(rows), jnp)
            out[f"frontier_{depth}"] = rows
            out[f"nxt_{depth}"] = np.asarray(nxt)
            out[f"valid_{depth}"] = np.asarray(valid)
            out[f"fhi_{depth}"] = np.asarray(fhi)
            out[f"flo_{depth}"] = np.asarray(flo)
        ck._run_block()
    assert (ck.state_count(), ck.unique_state_count()) == (32_971, 16_668), (
        ck.state_count(), ck.unique_state_count())
    out["depths"] = np.asarray(
        [d for d in CAPTURE_DEPTHS if f"frontier_{d}" in out], np.int32)
    np.savez_compressed(NPZ, **out)
    print(f"captured {len(out['depths'])} levels -> {NPZ}; "
          f"counts exact on {jax.default_backend()}")


def replay() -> None:
    import jax

    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(os.path.dirname(NPZ), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import jax.numpy as jnp

    from stateright_tpu.models.paxos import PackedPaxos
    from stateright_tpu.ops import fphash

    model = PackedPaxos(2, 3)
    A, W = model.max_actions, model.state_words
    step3 = _step3(model)
    data = np.load(NPZ)
    print(f"platform={jax.devices()[0].platform} A={A} W={W}", flush=True)

    # The engine's two expand lowerings, at fixed bucket f_cap
    # (xla.py:_build_superstep_planes step 2-3).
    def grid_rows(f):
        nxt, valid, _ = jax.vmap(step3)(f)  # [F, A, W]
        return jnp.transpose(nxt, (2, 1, 0)).reshape(W, A * f.shape[0]), valid

    def grid_planes(f):
        nxt, valid, _ = jax.vmap(step3, out_axes=(2, 0, 0))(f)  # [A, W, F]
        return jnp.transpose(nxt, (1, 0, 2)).reshape(W, A * f.shape[0]), valid

    fails = 0
    for depth in data["depths"]:
        rows = data[f"frontier_{depth}"]
        n = rows.shape[0]
        want_nxt = data[f"nxt_{depth}"]          # [n, A, W]
        want_valid = data[f"valid_{depth}"]
        want_fhi, want_flo = data[f"fhi_{depth}"], data[f"flo_{depth}"]
        for cap in REPLAY_CAPS:
            if cap < n:
                continue
            pad = np.zeros((cap, W), np.uint32)
            pad[:n] = rows
            f = jnp.asarray(pad)

            fhi, flo = jax.jit(lambda x: fphash.fingerprint_words(x, jnp))(f)
            bad = int(np.sum((np.asarray(fhi)[:n] != want_fhi)
                             | (np.asarray(flo)[:n] != want_flo)))
            if bad:
                fails += 1
                print(f"FAIL fp      depth={depth} cap={cap}: {bad}/{n} lanes")

            nxt, valid, _ = jax.jit(lambda x: jax.vmap(step3)(x))(f)
            bad_v = int(np.sum(np.asarray(valid)[:n] != want_valid))
            bad_w = int(np.sum(np.asarray(nxt)[:n] != want_nxt))
            if bad_v or bad_w:
                fails += 1
                print(f"FAIL expand  depth={depth} cap={cap}: "
                      f"{bad_v} valid lanes, {bad_w} words differ")
                _detail(np.asarray(nxt)[:n], want_nxt,
                        np.asarray(valid)[:n], want_valid)

            for name, fn in (("grid-rows", grid_rows),
                             ("grid-planes", grid_planes)):
                grid, valid = jax.jit(fn)(f)
                g = np.asarray(grid).reshape(W, A, cap)
                got = np.transpose(g[:, :, :n], (2, 1, 0))  # [n, A, W]
                bad_v = int(np.sum(np.asarray(valid)[:n] != want_valid))
                bad_w = int(np.sum(got != want_nxt))
                if bad_v or bad_w:
                    fails += 1
                    print(f"FAIL {name} depth={depth} cap={cap}: "
                          f"{bad_v} valid lanes, {bad_w} words differ")
                    _detail(got, want_nxt, np.asarray(valid)[:n], want_valid)
            print(f"done depth={depth} cap={cap}", flush=True)
    print(f"{'CLEAN' if fails == 0 else f'{fails} FAILING (stage, shape) pairs'}")
    sys.exit(0 if fails == 0 else 2)


def _detail(got, want, got_valid, want_valid, k: int = 5) -> None:
    """First few mismatching (state, action) sites, valid-lane and word."""
    dv = np.argwhere(got_valid != want_valid)
    for s, a in dv[:k]:
        print(f"    valid[{s},{a}]: got {got_valid[s, a]} want {want_valid[s, a]}")
    dw = np.argwhere((got != want).any(axis=2) & want_valid.astype(bool))
    for s, a in dw[:k]:
        ws = np.argwhere(got[s, a] != want[s, a]).ravel()
        print(f"    nxt[{s},{a}] words {ws.tolist()}: "
              f"got {[hex(int(got[s, a, w])) for w in ws[:4]]} "
              f"want {[hex(int(want[s, a, w])) for w in ws[:4]]}")


def main() -> None:
    if "capture" in sys.argv:
        capture()
    elif "replay" in sys.argv:
        replay()
    else:
        print(__doc__)
        sys.exit(1)


if __name__ == "__main__":
    main()
