"""Sort-lowering dtype A/B: would 64-bit key packing pay on this backend?

BASELINE.md "Next attacks" #3: the engine's dominant per-level ops are
multi-operand ``lax.sort`` calls over u32 planes — the insert's merge
sort is (key_hi, key_lo, ticket, val_hi, val_lo) with num_keys=3, and
the grid compaction is (key, state_word x W) with num_keys=1. If XLA
sorts one u64 operand materially faster than two u32 operands, packing
(hi, lo) -> u64 halves the operand count of the hot sorts; if it
doesn't (a u64 lane is the same 8 bytes through the permutation
network), the attack is dead and the engine keeps its u32 planes.

This tool measures exactly that trade, including the pack/unpack
shifts the engine would have to add. Timings are HOST-READBACK-GATED:
on the axon tunnel ``block_until_ready`` can return early for small
standalone programs (BASELINE.md "untrustworthy microbench" note), so
every timed loop ends with an ``np.asarray`` of a slice of the final
output — a real device-to-host copy that cannot complete before the
producing computation does.

Usage: python tools/sortbench.py [log2_m] [--cpu]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        sys.argv.remove("--cpu")
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            ),
        )
    # x64 must be on before first backend use so u64 lanes exist at all.
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    log2_m = int(sys.argv[1]) if len(sys.argv) > 1 else 23
    m = 1 << log2_m
    print(
        f"backend={jax.default_backend()} m=2^{log2_m} "
        f"(merge-sort shape of a 2^{log2_m - 1} table + 2^{log2_m - 1} cand)",
        flush=True,
    )

    rng = np.random.default_rng(7)
    hi = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
    lo = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
    vh = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
    vl = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
    ticket = jnp.arange(m, dtype=jnp.int32)

    def timed(name, fn, *args, n=3):
        fn(*args)  # compile + warm
        t0 = time.monotonic()
        out = None
        for _ in range(n):
            out = fn(*args)
        # Host readback gates the clock (see module docstring).
        first = out[0] if isinstance(out, (tuple, list)) else out
        np.asarray(first[:8])
        dt = (time.monotonic() - t0) / n
        print(f"  {name:<46} {dt * 1e3:9.2f} ms", flush=True)
        return dt

    # --- the insert merge-sort shape -----------------------------------
    print("insert merge sort (2-lane key + ticket + 2-lane value):", flush=True)

    @jax.jit
    def sort_u32(hi, lo, ticket, vh, vl):
        return jax.lax.sort((hi, lo, ticket, vh, vl), num_keys=3)

    t_u32 = timed("u32 5-operand num_keys=3 (shipping)", sort_u32, hi, lo, ticket, vh, vl)

    @jax.jit
    def sort_u64(hi, lo, ticket, vh, vl):
        # Includes the pack/unpack the engine would pay.
        k64 = (hi.astype(jnp.uint64) << 32) | lo.astype(jnp.uint64)
        v64 = (vh.astype(jnp.uint64) << 32) | vl.astype(jnp.uint64)
        sk, st, sv = jax.lax.sort((k64, ticket, v64), num_keys=1)
        return (
            (sk >> 32).astype(jnp.uint32),
            sk.astype(jnp.uint32),
            st,
            (sv >> 32).astype(jnp.uint32),
            sv.astype(jnp.uint32),
        )

    t_u64 = timed("u64 3-operand num_keys=1 (packed keys+values)", sort_u64, hi, lo, ticket, vh, vl)

    @jax.jit
    def sort_u64_key_only(hi, lo, ticket, vh, vl):
        k64 = (hi.astype(jnp.uint64) << 32) | lo.astype(jnp.uint64)
        sk, st, svh, svl = jax.lax.sort((k64, ticket, vh, vl), num_keys=1)
        return (sk >> 32).astype(jnp.uint32), sk.astype(jnp.uint32), st, svh, svl

    timed("u64 key, u32 values 4-operand", sort_u64_key_only, hi, lo, ticket, vh, vl)

    @jax.jit
    def sort_stable2(hi, lo, ticket, vh, vl):
        # Ticket demoted from key to payload via stability: inputs are in
        # ticket order, so a stable 2-key sort elects the same winners.
        return jax.lax.sort((hi, lo, ticket, vh, vl), num_keys=2, is_stable=True)

    timed("u32 5-operand num_keys=2 stable (ticket demoted)", sort_stable2, hi, lo, ticket, vh, vl)

    # --- single-key payload movement (compaction-sort shape) -----------
    print("compaction sort (1 i32 key + W payload lanes):", flush=True)
    key = jnp.asarray(rng.integers(0, 2, m, dtype=np.int32))
    W = 5
    planes = [
        jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32)) for _ in range(W)
    ]

    @jax.jit
    def comp_u32(key, *planes):
        return jax.lax.sort((key, *planes), num_keys=1, is_stable=True)

    t_c32 = timed(f"i32 key + {W} u32 payload (shipping)", comp_u32, key, *planes)

    @jax.jit
    def comp_u64(key, *planes):
        # Pair adjacent planes into u64 payloads (one leftover u32 lane).
        packed = [
            (planes[i].astype(jnp.uint64) << 32) | planes[i + 1].astype(jnp.uint64)
            for i in range(0, W - 1, 2)
        ]
        rest = list(planes[W - W % 2 :])
        out = jax.lax.sort((key, *packed, *rest), num_keys=1, is_stable=True)
        unpacked = []
        for p in out[1 : 1 + len(packed)]:
            unpacked.append((p >> 32).astype(jnp.uint32))
            unpacked.append(p.astype(jnp.uint32))
        return (out[0], *unpacked, *out[1 + len(packed) :])

    t_c64 = timed(f"i32 key + {(W + 1) // 2} u64-paired payload", comp_u64, key, *planes)

    print(
        f"verdict: merge u64/u32 = {t_u64 / t_u32:.2f}x, "
        f"compaction paired/u32 = {t_c64 / t_c32:.2f}x "
        f"(<1 means packing wins)",
        flush=True,
    )


if __name__ == "__main__":
    main()
