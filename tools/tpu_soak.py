"""Device-scale soak: full-coverage runs at rm=9/10/11 + paxos 3c/3s.

VERDICT round-4 item 5 / SURVEY §7 hard part 1: prove the visited-set
architecture (delta flushes, table growth, 2^27-row planes in HBM) at
>= 10^8 generated states, with run-to-run count stability and the host
duplicate-key audit as the corruption guard. Extracted from
tpu_plan.sh's stage-5 heredoc so the r5 watcher can run it standalone.

Run under `timeout` — the axon tunnel wedges rather than failing.
Usage: python tools/tpu_soak.py [--cpu] [--quick]
  --quick runs a single rm=7 soak (CPU smoke / script validation) instead
  of the full rm=9/10/11 ladder.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    print(f"[soak] platform={jax.devices()[0].platform}", flush=True)

    def soak(name, build, runs=2, budget_s=900, audit=True, **kw):
        results = []
        for i in range(runs):
            model = build()
            # Announce BEFORE the first device call: the tunnel wedges
            # (blocks forever) rather than failing, and twice now an
            # rm=10 soak froze with zero output — the starting line is
            # what localizes the hang to a config + run.
            print(f"[soak] {name} run {i} starting ({kw})", flush=True)
            c = model.checker().spawn_xla(**kw)
            t0 = time.monotonic()
            last_hb = t0
            while not c.is_done() and time.monotonic() - t0 < budget_s:
                c._run_block()
                now = time.monotonic()
                if now - last_hb > 60:
                    print(
                        f"[soak] {name} run {i} heartbeat: "
                        f"gen={c.state_count():,} uniq={c.unique_state_count():,} "
                        f"depth={c.max_depth()} t={now - t0:.0f}s",
                        flush=True,
                    )
                    last_hb = now
            dt = time.monotonic() - t0
            results.append(
                (c.state_count(), c.unique_state_count(), c.max_depth(), c.is_done())
            )
            print(
                f"[soak] {name} run {i}: gen={c.state_count():,} "
                f"uniq={c.unique_state_count():,} depth={c.max_depth()} "
                f"done={c.is_done()} in {dt:.1f}s "
                f"({c.state_count()/max(dt,1e-9):,.0f} gen/s) "
                f"table=2^{c._table.capacity.bit_length()-1}",
                flush=True,
            )
            if audit and i == runs - 1:
                try:
                    from stateright_tpu.audit import audit_table

                    print(f"[soak] {name} audit: {audit_table(c)}", flush=True)
                except Exception as e:
                    print(f"[soak] {name} audit ERRORED: {e}", flush=True)
        # Only completed runs have comparable totals: a budget-truncated
        # run stops at an arbitrary point, so comparing them would read
        # healthy truncation jitter as the corruption signal.
        done_runs = [r for r in results if r[3]]
        if len(done_runs) >= 2:
            stable = len(set(done_runs)) == 1
            print(
                f"[soak] {name}: counts {'STABLE' if stable else 'UNSTABLE'} "
                f"across {len(done_runs)} completed runs",
                flush=True,
            )
        elif not done_runs:
            print(f"[soak] {name}: TRUNCATED (no completed run) — stability n/a", flush=True)

    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    if "--quick" in sys.argv:
        soak(
            "2pc rm=7 (quick)",
            lambda: PackedTwoPhaseSys(7),
            frontier_capacity=1 << 17,
            table_capacity=1 << 19,
        )
        return
    # Unique-state growth is ~5.9x per RM (8,832 @ rm=5 ... 1,745,408 @
    # rm=8): rm=9 ~ 10M uniques, rm=10 ~ 60M. Pre-size tables — every
    # growth step at this scale is a recompile.
    if "--skip-rm9" not in sys.argv:
        soak(
            "2pc rm=9",
            lambda: PackedTwoPhaseSys(9),
            frontier_capacity=1 << 20,
            table_capacity=1 << 24,
        )
    # The delta structure is chip-blocked this round: its compiled program
    # reproducibly faults the TPU runtime ("TPU worker process crashed —
    # kernel fault") at BOTH rm=8 shapes (profile A/B, table 2^22) and
    # rm=10 shapes (this soak, table 2^27), while the same program is
    # exact on CPU — so scale is not the trigger, the program shape is.
    # Pass --delta to retry it; the default soaks the flat sorted
    # structure, which the rm=9 stage just proved at 10^8 states.
    dedup_big = "delta" if "--delta" in sys.argv else "sorted"
    soak(
        "2pc rm=10",
        lambda: PackedTwoPhaseSys(10),
        budget_s=1200,
        frontier_capacity=1 << 21,
        table_capacity=1 << 27,
        dedup=dedup_big,
    )
    # rm=11 (~360M uniques) exceeds full coverage in budget; a bounded run
    # still measures steady-state gen/s at 2^28 table scale. Audit skipped:
    # a partial-coverage readback of 2^28 planes is minutes of transfer.
    soak(
        "2pc rm=11 (bounded)",
        lambda: PackedTwoPhaseSys(11),
        runs=1,
        budget_s=900,
        audit=False,
        frontier_capacity=1 << 22,
        table_capacity=1 << 28,
        dedup=dedup_big,
    )
    from stateright_tpu.models.paxos import PackedPaxos

    soak(
        "paxos 3c/3s",
        lambda: PackedPaxos(3, 3),
        budget_s=1200,
        frontier_capacity=1 << 19,
        table_capacity=1 << 25,
    )

    # LAST, because the pre-redesign delta faulted the TPU runtime and a
    # residual fault must not cost the stages above: the delta structure
    # under its round-5 host-invoked-flush protocol, at rm=8 (vs the 8.7s
    # sorted number) and rm=10 (the regime it exists for).
    if "--no-delta-retry" not in sys.argv:
        soak(
            "2pc rm=8 delta (flush-protocol retry)",
            lambda: PackedTwoPhaseSys(8),
            frontier_capacity=1 << 19,
            table_capacity=1 << 22,
            dedup="delta",
        )
        soak(
            "2pc rm=10 delta (flush-protocol retry)",
            lambda: PackedTwoPhaseSys(10),
            runs=1,
            budget_s=1200,
            frontier_capacity=1 << 21,
            table_capacity=1 << 27,
            dedup="delta",
        )


if __name__ == "__main__":
    main()
