#!/usr/bin/env bash
# Background tunnel watcher: probe the axon TPU tunnel until it answers,
# then run the staged measurement plan (tools/tpu_plan.sh) once and exit.
# All output -> tpu_watch.log. Probe itself is cheap (one import attempt);
# the heavy stages only start after a successful probe.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch.log
log() { echo "[tpu_watch $(date +%H:%M:%S)] $*" >>"$LOG"; }

log "watcher started (pid $$)"
attempt=0
while true; do
  attempt=$((attempt + 1))
  if timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; then
    log "probe $attempt: TUNNEL UP — launching tpu_plan.sh"
    bash tools/tpu_plan.sh >>"$LOG" 2>&1
    rc=$?
    log "tpu_plan.sh finished rc=$rc"
    exit $rc
  fi
  log "probe $attempt: tunnel down"
  sleep 540
done
