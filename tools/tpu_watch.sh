#!/usr/bin/env bash
# Background tunnel watcher: probe the axon TPU tunnel until it answers,
# then run the staged measurement plan (tools/tpu_plan.sh). A plan run that
# fails (tunnel dropped mid-way) goes back to probing; a successful plan
# ends the watch. All output -> tpu_watch.log. Probes are cheap (one import
# attempt under a 60s watchdog, every 4 min).
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch.log
log() { echo "[tpu_watch $(date +%H:%M:%S)] $*" >>"$LOG"; }

log "watcher started (pid $$)"
attempt=0
while true; do
  attempt=$((attempt + 1))
  if timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; then
    log "probe $attempt: TUNNEL UP — launching tpu_plan.sh"
    bash tools/tpu_plan.sh >>"$LOG" 2>&1
    rc=$?
    log "tpu_plan.sh finished rc=$rc"
    if [ "$rc" -eq 0 ]; then
      exit 0
    fi
    log "plan failed; resuming probe loop"
  else
    log "probe $attempt: tunnel down"
  fi
  sleep 240
done
