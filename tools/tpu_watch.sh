#!/usr/bin/env bash
# Tunnel watcher — the ONE parameterized replacement for the per-round
# copies (tpu_watch_r3b/r4/r5/r5b/r5c/r5d/r5e.sh, now deleted): probe the
# axon TPU tunnel until it answers, then run a staged measurement plan,
# committing each stage's artifacts as it lands (a measurement that is not
# in git did not happen — tunnel windows can be short).
#
# Usage:
#   tools/tpu_watch.sh [-l LOG] [-m MARKDIR] [-s STALL_S] [-n] [STAGE...]
#
#   STAGE = "name,timeout_s,outfile,command ..."   (first 3 fields
#           comma-separated; the rest is the command line, spaces fine)
#   -l LOG      watch log                 (default runs/tpu_watch.log)
#   -m MARKDIR  stage-done marker dir     (default runs/.watch_markers —
#               reuse one dir across windows so finished stages stay
#               finished; point different plans at different dirs)
#   -s STALL_S  heartbeat stall leash, seconds (default 1500 — must
#               out-wait a HEALTHY steady dispatch: a fused device call
#               covers up to 32 BFS levels between beats. Deliberately
#               LOOSER than bench.py's own BENCH_STALL_S=1200, so a
#               bench stage's better-informed inner watchdog always
#               fires first and this outer kill is the backstop)
#   -n          do not git-commit stage artifacts
#
# With no stages, the default plan is a single stage running the staged
# measurement script:  plan,7200,runs/tpu_plan.log,bash tools/tpu_plan.sh
#
# The bare stage name "soak_resume" is a built-in alias for the SUPERVISED
# rm=10 soak (python tools/soak.py --config rm10 --audit): the worker
# auto-checkpoints, and after a wedge the soak's own supervisor resumes it
# from the latest valid checkpoint rotation (docs/observability.md
# "Recovery").
#
# Wedge detection is HEARTBEAT-AWARE (stateright_tpu/obs/heartbeat.py,
# docs/observability.md): every stage runs with STPU_HEARTBEAT pointed at
# a per-stage file the engines rewrite around each device dispatch. A
# beat stale past STALL_S while the engine is mid-dispatch is a wedged
# tunnel — the stage is killed immediately instead of idling out its full
# hard timeout; a beat flagged compile=true gets a 3x leash (XLA compiles
# over the tunnel legitimately run minutes). Stages that never beat
# (non-engine tools) fall back to the hard timeout alone.
#
# Example (a bench A/B plus a profile pass):
#   tools/tpu_watch.sh \
#     "bench_jump,2400,runs/bench_jump.json,env BENCH_LADDER=jump python bench.py" \
#     "bench_ramp,2400,runs/bench_ramp.json,env BENCH_LADDER=ramp python bench.py" \
#     "profile,2700,runs/profile.log,python tools/profile_superstep.py 8"
set -u
cd "$(dirname "$0")/.."

LOG=runs/tpu_watch.log
MARK=runs/.watch_markers
STALL_S=1500
COMMIT=1
while getopts "l:m:s:n" opt; do
  case "$opt" in
    l) LOG=$OPTARG ;;
    m) MARK=$OPTARG ;;
    s) STALL_S=$OPTARG ;;
    n) COMMIT=0 ;;
    *) exit 2 ;;
  esac
done
shift $((OPTIND - 1))

STAGES=("$@")
if [ ${#STAGES[@]} -eq 0 ]; then
  STAGES=("plan,7200,runs/tpu_plan.log,bash tools/tpu_plan.sh")
fi

# Built-in stage aliases: a bare "soak_resume" expands to the SUPERVISED
# rm=10 soak (tools/soak.py) — the worker auto-checkpoints and the soak's
# own supervisor resumes it after a wedge, so this outer watcher only
# backstops a dead supervisor. (soak.py reuses the stage's STPU_HEARTBEAT
# for its worker, so hb_stale below still sees real engine liveness.)
# A bare "service_chaos" expands to the seeded durable-service chaos
# harness (tools/service_chaos.py: baseline + SIGKILL-restart + torn-
# journal scenarios, exactly-once + bit-identical counts, SLO line to
# runs/service_chaos.json — bench_detail's "journal" provenance).
# A bare "bench_regress" expands to the perf-regression gate
# (tools/bench_regress.py): the freshest runs/bench_detail.json judged
# against the archived runs/archive/BENCH_r*.json trajectory + the chaos
# SLO line — schedule it right after a bench stage so the window
# self-judges (typed verdict JSON to runs/regress.json; no device).
# A bare "fleet_chaos" expands to the FLEET chaos sweep (ISSUE 15):
# a seeded 2-device FleetService schedule with interactive sessions,
# full-fleet SIGKILL-restart, torn journal, AND a device.lost kill —
# exactly-once + bit-identical across migrations, per-device SLOs in
# runs/service_chaos.json's "fleet" dicts.
# A bare "sym_ab" expands to the on-chip symmetry A/B (docs/symmetry.md):
# BENCH_SYM=1 bench.py runs one shipped spec full-space vs reduced on
# the tunnel — the runtime verdict on whether the in-superstep
# canonicalization network is free against the table sorts it shrinks
# (the sym dict lands in runs/bench_detail.json; bench_regress gates it
# once banked).
# A bare "qos_chaos" expands to the multi-tenant QoS sweep (ISSUE 18):
# a seeded mixed-priority tenant schedule with the tenant.storm burst,
# mid-storm SIGKILL + restart, the per-class shed/Retry-After probe —
# exactly-once, no priority inversion, per-class p50/p99 SLOs in
# runs/service_chaos.json's "classes" dicts.
for i in "${!STAGES[@]}"; do
  if [ "${STAGES[$i]}" = "soak_resume" ]; then
    STAGES[$i]="soak_resume,14400,runs/soak_resume.log,python tools/soak.py --config rm10 --audit"
  elif [ "${STAGES[$i]}" = "service_chaos" ]; then
    STAGES[$i]="service_chaos,1800,runs/service_chaos.log,python tools/service_chaos.py --seed 42 --jobs 3"
  elif [ "${STAGES[$i]}" = "fleet_chaos" ]; then
    STAGES[$i]="fleet_chaos,2400,runs/fleet_chaos.log,python tools/service_chaos.py --seed 42 --jobs 4 --fleet 2 --sessions 4"
  elif [ "${STAGES[$i]}" = "sym_ab" ]; then
    STAGES[$i]="sym_ab,3600,runs/sym_ab.log,env BENCH_SYM=1 BENCH_MATRIX=0 python bench.py"
  elif [ "${STAGES[$i]}" = "qos_chaos" ]; then
    STAGES[$i]="qos_chaos,2400,runs/qos_chaos.log,python tools/service_chaos.py --seed 42 --jobs 6 --tenants 12 --scenario storm --overload"
  elif [ "${STAGES[$i]}" = "bench_regress" ]; then
    # Outfile is a LOG, not runs/regress.json: the stage runner's stdout
    # redirect truncates its outfile at start, which would destroy the
    # previous atomically-written verdict if the stage dies early — the
    # tool itself owns runs/regress.json via tmp+os.replace.
    STAGES[$i]="bench_regress,300,runs/bench_regress.log,python tools/bench_regress.py"
  fi
done

mkdir -p runs "$MARK"
log() { echo "[tpu_watch $(date +%H:%M:%S)] $*" >>"$LOG"; }
probe() { timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; }
done_p() { [ -f "$MARK/$1" ]; }
mark() { touch "$MARK/$1"; }

commit_stage() {
  [ "$COMMIT" -eq 1 ] || return 0
  local msg=$1 f; shift
  local have=()
  for f in "$@" "$LOG"; do
    [ -e "$f" ] && have+=("$f") || log "artifact missing: $f"
  done
  [ ${#have[@]} -gt 0 ] || return 0
  git add -f -- "${have[@]}" >>"$LOG" 2>&1
  # Pathspec-limited: a stage commit must carry ONLY its artifacts —
  # never whatever else happens to be sitting in the index.
  git commit -q -m "$msg" -- "${have[@]}" >>"$LOG" 2>&1 && log "committed: $msg"
}

# hb_stale FILE START_EPOCH — rc 0 (kill it) when the stage's heartbeat
# exists, postdates the stage start, and is stale past its leash WHILE
# the engine is mid-dispatch. Stale in phase="idle" is host-side work
# (audits, witness reconstruction), not the tunnel — the hard timeout
# governs there. The verdict itself is the LIBRARY's
# (stateright_tpu/supervise.py heartbeat_verdict — the same code bench.py
# runs), so the protocol table lives in exactly one place; startup grace
# is infinite here because this watcher's hard timeout governs pre-beat.
hb_stale() {
  python - "$1" "$2" "$STALL_S" <<'EOF'
import sys, traceback
try:
    sys.path.insert(0, ".")
    from stateright_tpu.supervise import heartbeat_verdict
    path, start, stall = sys.argv[1], float(sys.argv[2]), float(sys.argv[3])
    verdict = heartbeat_verdict(
        path, started_wall=start, elapsed_s=0.0, stall_s=stall,
        startup_grace_s=float("inf"),
    )
except Exception:
    # rc 3 = "verdict unavailable", distinct from rc 1 = "not stale":
    # an import/protocol error must be LOGGED, not silently read as a
    # healthy worker for the rest of the stage.
    traceback.print_exc()
    sys.exit(3)
sys.exit(0 if verdict else 1)
EOF
  local rc=$?
  [ "$rc" -eq 3 ] && log "hb_stale ERROR (verdict unavailable; only the hard timeout governs this poll)"
  return "$rc"
}

# run_stage NAME TIMEOUT OUT CMD... — marker on rc==0; bench.py stages
# additionally need a tpu JSON line (bench.py silently falls back to a
# cpu worker otherwise). Returns 1 when the tunnel dropped (re-probe).
run_stage() {
  local name=$1 tmo=$2 out=$3; shift 3
  done_p "$name" && { log "skip $name (done)"; return 0; }
  probe || { log "tunnel down before $name; back to wait"; return 1; }
  local hb="runs/heartbeat.$name.json"
  local start; start=$(date +%s)
  log "stage $name (timeout ${tmo}s, stall ${STALL_S}s): $*"
  # setsid: the stage leads its own process group, so a kill takes the
  # whole tree — bench.py's worker grandchild must not survive holding
  # the device (and beating the heartbeat) after its parent dies.
  STPU_HEARTBEAT="$hb" setsid "$@" >"$out" 2>&1 &
  local pid=$!
  local rc=""
  while kill -0 "$pid" 2>/dev/null; do
    sleep 15
    if [ $(($(date +%s) - start)) -ge "$tmo" ]; then
      log "$name: hard timeout ${tmo}s; killing group"
      kill -- -"$pid" 2>/dev/null; sleep 2; kill -9 -- -"$pid" 2>/dev/null
      rc=124; break
    fi
    if hb_stale "$hb" "$start"; then
      log "$name: heartbeat stale mid-dispatch (wedged tunnel); killing group"
      kill -- -"$pid" 2>/dev/null; sleep 2; kill -9 -- -"$pid" 2>/dev/null
      rc=125; break
    fi
  done
  if [ -z "$rc" ]; then wait "$pid"; rc=$?; fi
  log "$name rc=$rc: $(tail -c 250 "$out" 2>/dev/null)"
  case "$*" in
    *bench.py*)
      # The marker needs the REAL backend, not the worker label: the
      # axon plugin can probe ok while yielding a CPU device, and a
      # tpu-labeled line banking CPU numbers must not finish the stage
      # (the reason bench.py's primary line carries "backend").
      [ "$rc" -eq 0 ] && grep -q '"backend": "tpu"' "$out" && mark "$name"
      # The per-level detail is the analysis artifact; a bench number
      # without it is half a measurement (every prior watcher committed
      # these two with the stage, by force past the runs/* ignore).
      commit_stage "TPU watch $name (rc=$rc)" "$out" \
        runs/bench_detail.json runs/bench_probe.log
      ;;
    *)
      [ "$rc" -eq 0 ] && mark "$name"
      commit_stage "TPU watch $name (rc=$rc)" "$out"
      ;;
  esac
  return 0
}

all_done() {
  local s name
  for s in "${STAGES[@]}"; do
    IFS=, read -r name _ <<<"$s"
    done_p "$name" || return 1
  done
  return 0
}

log "watcher started (pid $$, ${#STAGES[@]} stages, stall ${STALL_S}s)"
while true; do
  if probe; then
    log "TUNNEL UP — staged pass"
    for s in "${STAGES[@]}"; do
      IFS=, read -r name tmo out cmd <<<"$s"
      # shellcheck disable=SC2086 — the command line is intentionally split
      run_stage "$name" "$tmo" "$out" $cmd || break
    done
    if all_done; then
      log "all stages done; watcher exiting"
      exit 0
    fi
    log "pass finished with unfinished stages; resuming watch"
  else
    log "tunnel down"
  fi
  sleep 240
done
