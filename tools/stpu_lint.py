#!/usr/bin/env python
"""stpu-lint wrapper: ``python tools/stpu_lint.py [args]`` ==
``python -m stateright_tpu.analysis [args]`` from anywhere.

The analyzer mechanically enforces the pinned backend-miscompile rules
(docs/static-analysis.md) over every shipped kernel surface: CPU-only,
no device access, <60 s on the 1-core CI box. ``tools/smoke.sh`` runs it
as the tier-0 ``lint`` stage with ``--json-out runs/lint.json``, which
``bench.py`` folds into ``bench_detail.json`` provenance as ``lint_ok``.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from stateright_tpu.analysis import main  # noqa: E402 (path bootstrap)

if __name__ == "__main__":
    sys.exit(main())
