"""Engine-level A/B for the in-program candidate-width ladder.

Grid: ``cand_ladder`` 3 (on) vs 1 (off) x dedup sorted/delta x bucket
ladder ramp/jump, each a full count-checked 2pc check (warm pass
compiles, measured pass times). Every variant runs in its own
SUBPROCESS under a hard timeout (hang-proof over the axon tunnel;
``STPU_CAND_LADDER`` rides the documented process-restart convention
even though it is spawn-arg-plumbed, so a wedged child can't poison the
next variant). The parent pairs on/off rows and reports:

- ``median_lane_ratio``: ladder-off / ladder-on sorted-lane-words at the
  MEDIAN level (the acceptance metric for BASELINE.md attack #2 — the
  round-5 cost law says per-level time ~ lane-words x log^2 n, so this
  ratio is the engine-measured win, provable on 1-core CPU);
- ``dispatches_equal``: the ladder must add ZERO host dispatches (the
  shrink-exit chip lesson: ~150 ms/RTT over the tunnel);
- ``warm_ratio`` / ``measured_ratio``: wall-clock on/off (warm includes
  the K-branch fused compiles — the compile-budget guard).

Usage: python tools/cand_ab.py [rm] [--cpu] [--quick]
  --quick: the sorted structure only (4 children instead of 8).
Per-child timeout: ``CAND_AB_TIMEOUT_S`` (default 550 s — well under the
watcher stage's 2400 s budget / 4 quick children, so one wedged child
surfaces as its own ``error`` row instead of killing the whole stage).
On CPU the persistent compile cache is skipped so warm_ratio prices the
K-branch compiles honestly; rm clamps to 6 there (the acceptance mix).
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
import jax
if {cpu!r} == "cpu":
    jax.config.update("jax_platforms", "cpu")
else:
    jax.config.update("jax_compilation_cache_dir", {repo!r} + "/.jax_cache")
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from bench import EXPECTED_2PC as EXPECTED

rm = {rm}
fcap, tcap = 1 << 19, 1 << 22
if {cpu!r} == "cpu":
    rm = min(rm, 6)
    # Snug table for the rm=6 mix: 2^17 holds the 50,816 uniques inside
    # the 3/4-load rule with no growth recompiles, so the insert's
    # table-scale term doesn't drown the candidate-scale one the ladder
    # attacks.
    fcap, tcap = 1 << 17, 1 << 17
kw = dict(dedup={dedup!r}, ladder={ladder!r}, frontier_capacity=fcap,
          table_capacity=tcap)
m = PackedTwoPhaseSys(rm)
t0 = time.monotonic()
m.checker().spawn_xla(**kw).join()
warm = time.monotonic() - t0
c = m.checker().spawn_xla(**kw)
t0 = time.monotonic()
c.join()
dt = time.monotonic() - t0
want = EXPECTED.get(rm)
ok = want is None or (c.state_count(), c.unique_state_count()) == want
print(json.dumps({{
    # The REAL backend, not the requested label: the axon plugin can
    # probe ok while yielding a CPU device, and a chip-verdict log full
    # of silent XLA:CPU numbers is worse than no log (the bench.py
    # lesson from this same round).
    "backend": jax.default_backend(),
    "cand_ladder": c._cand_ladder_k, "dedup": {dedup!r}, "ladder": {ladder!r},
    "rm": rm, "warm_s": round(warm, 2), "measured_s": round(dt, 3),
    "gen_per_s": round(c.state_count() / dt, 1),
    "gen": c.state_count(), "uniq": c.unique_state_count(),
    "count_ok": bool(ok),
    "dispatches": len(c.dispatch_log), "retries": c.cand_retries,
    "lane_words": [r["lane_words"] for r in c.level_log],
    "cand_caps": [r["cand_cap"] for r in c.level_log],
}}))
"""


def _run_variant(cpu: str, rm: int, dedup: str, ladder: str, k: str) -> dict:
    env = dict(os.environ)
    env["STPU_CAND_LADDER"] = k
    code = CHILD.format(repo=REPO, cpu=cpu, rm=rm, dedup=dedup, ladder=ladder)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            capture_output=True,
            text=True,
            timeout=int(os.environ.get("CAND_AB_TIMEOUT_S", "550")),
        )
    except subprocess.TimeoutExpired:
        return {"dedup": dedup, "ladder": ladder, "cand_ladder": int(k),
                "error": "timeout (wedged?)"}
    line = (proc.stdout.strip().splitlines() or [""])[-1]
    if proc.returncode != 0 or not line.startswith("{"):
        return {"dedup": dedup, "ladder": ladder, "cand_ladder": int(k),
                "error": proc.stderr.strip()[-400:]}
    return json.loads(line)


def main() -> None:
    cpu = "cpu" if "--cpu" in sys.argv else "tpu"
    quick = "--quick" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    rm = int(args[0]) if args else 8
    grid = (
        # --quick: the sorted structure only (the watcher's chip stage —
        # delta pairs wait on the registry-#4 fault localization).
        [("sorted", "ramp"), ("sorted", "jump")]
        if quick
        else [(d, l) for d in ("sorted", "delta") for l in ("ramp", "jump")]
    )
    for dedup, ladder in grid:
        pair = {}
        for k in ("3", "1"):
            row = _run_variant(cpu, rm, dedup, ladder, k)
            print(json.dumps(row), flush=True)
            pair[k] = row
        on, off = pair["3"], pair["1"]
        if "error" in on or "error" in off:
            continue
        med_on = statistics.median(on["lane_words"])
        med_off = statistics.median(off["lane_words"])
        print(
            json.dumps(
                {
                    "pair": f"{dedup}/{ladder}",
                    "backends": sorted(
                        {on.get("backend"), off.get("backend")} - {None}
                    ),
                    "median_lane_ratio": round(med_off / max(med_on, 1), 2),
                    "median_lane_words": {"off": med_off, "on": med_on},
                    "total_lane_ratio": round(
                        sum(off["lane_words"])
                        / max(sum(on["lane_words"]), 1),
                        2,
                    ),
                    "dispatches_equal": on["dispatches"] == off["dispatches"],
                    "retries_on": on["retries"],
                    "counts_ok": on["count_ok"] and off["count_ok"]
                    and (on["gen"], on["uniq"]) == (off["gen"], off["uniq"]),
                    "warm_ratio": round(
                        on["warm_s"] / max(off["warm_s"], 1e-9), 2
                    ),
                    "measured_ratio": round(
                        on["measured_s"] / max(off["measured_s"], 1e-9), 2
                    ),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
