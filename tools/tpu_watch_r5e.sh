#!/usr/bin/env bash
# Round-5e tunnel watcher — v3, replacing tools/tpu_watch_r5d.sh after
# the 06:12 window taught three things:
#   * the delta structure STILL faults the TPU runtime post-redesign
#     (registry #4 status note) — benching it is a guaranteed ~15-min
#     crash loop per pass, so the delta/stack benches are DROPPED and
#     `tools/delta_diag.py` (the standalone program bisector) runs
#     instead: one window of diag beats five windows of crashes;
#   * the pallas kernel was rebuilt for Mosaic (no cumsum, no
#     dynamic-offset vector stores — registry #6); the probe + the
#     pallas bench are the decisive first-silicon rows;
#   * bench.py + spawn_xla now resolve planes-only compaction requests
#     sanely on the CPU fallback, so a dead tunnel no longer turns the
#     pallas stage into a crash.
# Markers are SHARED with v2 (.r5d_markers/) so a stage an earlier
# window finished stays finished.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_watch_r5e.log
MARK=.r5d_markers
mkdir -p "$MARK"
log() { echo "[watch $(date +%H:%M:%S)] $*" >>"$LOG"; }
probe() { timeout 60 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu', ds" >>"$LOG" 2>&1; }
commit_stage() {
  local msg=$1 f; shift
  for f in "$@" "$LOG"; do
    git add -f -- "$f" >>"$LOG" 2>&1 || log "artifact missing: $f"
  done
  git commit -q -m "$msg" >>"$LOG" 2>&1 && log "committed: $msg"
}
done_p() { [ -f "$MARK/$1" ]; }
mark() { touch "$MARK/$1"; }

# run_tool NAME TIMEOUT LOGFILE CMD... — marker on rc==0 (the axon
# platform is pinned by sitecustomize, so a tool that ran to rc==0 ran
# on the chip; a wedge times out and leaves no marker).
run_tool() {
  local name=$1 tmo=$2 out=$3; shift 3
  done_p "$name" && { log "skip $name (done)"; return 0; }
  probe || { log "tunnel down before $name; back to wait"; return 1; }
  log "stage $name: $*"
  timeout "$tmo" "$@" >"$out" 2>&1
  local rc=$?
  log "$name rc=$rc: $(tail -c 250 "$out" 2>/dev/null)"
  [ $rc -eq 0 ] && mark "$name"
  commit_stage "TPU r5e $name (rc=$rc)" "$out"
  return 0
}

# run_bench NAME TIMEOUT OUTJSON ENV... — marker needs rc==0 AND a tpu
# JSON line (bench.py silently falls back to a cpu worker otherwise).
run_bench() {
  local name=$1 tmo=$2 out=$3; shift 3
  done_p "$name" && { log "skip $name (done)"; return 0; }
  probe || { log "tunnel down before $name; back to wait"; return 1; }
  log "stage $name: bench.py $*"
  timeout "$tmo" env "$@" python bench.py >"$out" 2>>"$LOG"
  local rc=$?
  log "$name rc=$rc: $(tail -c 300 "$out" 2>/dev/null)"
  if [ $rc -eq 0 ] && grep -q 'spawn_xla, tpu' "$out"; then mark "$name"; fi
  commit_stage "TPU r5e $name (rc=$rc)" "$out" bench_detail.json bench_probe.log
  return 0
}

log "watcher v3 started (pid $$)"
while true; do
  if probe; then
    log "TUNNEL UP — staged pass"
    # 0. pallas synthetic probe — the reworked kernel's first silicon
    run_tool pallas_probe2 1500 tpu_pallas_compact2.log \
      python tools/pallas_compact.py || { sleep 240; continue; }
    # 0b. merge-insert probe: correctness vs the sort core, then the
    #     O(C+m)-vs-sort A/B; answers the arbitrary-offset-DMA question
    run_tool merge_probe 1800 tpu_pallas_merge.log \
      python tools/pallas_merge.py || { sleep 240; continue; }
    # 1. pallas bench (headline config, no matrix)
    run_bench bench_pallas2 2400 bench_r5e_pallas.json \
      STPU_COMPACTION=pallas BENCH_MATRIX=0 || { sleep 240; continue; }
    # 2. superstep profile incl. mixed-lowering A/B rows (delta last)
    run_tool profile 2700 tpu_profile_r5c.log \
      python tools/profile_superstep.py 8 || { sleep 240; continue; }
    # 3. sort-dtype A/B (key packing decision)
    run_tool sortbench 1200 tpu_sortbench.log \
      python tools/sortbench.py 23 || { sleep 240; continue; }
    # 4. engine-level packed-keys A/B
    run_tool packed_ab 2400 tpu_packed_ab.log \
      python tools/packed_ab.py 8 || { sleep 240; continue; }
    # 4b. in-program candidate-ladder A/B (rm=8, sorted x ramp/jump;
    #     the switch branches carry the [table ‖ cand] merge sort — the
    #     registry-#4-adjacent shape — so this stage is ALSO the runtime
    #     fault probe the TPU lowering pre-flight cannot give; delta
    #     pairs stay out until delta_diag localizes the registry-#4 fault)
    run_tool cand_ab 2400 tpu_cand_ab.log \
      python tools/cand_ab.py 8 --quick || { sleep 240; continue; }
    # 5. delta-fault bisect: standalone programs across the shape ladder
    run_tool delta_diag 2400 tpu_delta_diag.log \
      python tools/delta_diag.py 22 || { sleep 240; continue; }
    # 6. scale soak rm=10/11 + paxos 3c/3s, sorted structure only (the
    #    delta retries are pointless until the diag localizes the fault)
    run_tool soak 7200 tpu_soak_r5e.log \
      python tools/tpu_soak.py --skip-rm9 --no-delta-retry || { sleep 240; continue; }
    if done_p pallas_probe2 && done_p bench_pallas2 && done_p profile \
       && done_p sortbench && done_p packed_ab && done_p delta_diag \
       && done_p soak; then
      log "all stages done; watcher exiting"
      exit 0
    fi
    log "pass finished with unfinished stages; resuming watch"
  else
    log "tunnel down"
  fi
  sleep 240
done
