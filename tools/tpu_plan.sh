#!/usr/bin/env bash
# The TPU measurement plan, one command, each stage under its own watchdog.
# Run when the axon tunnel recovers (probe first). Ordered so the single
# most important artifact — the committed primary bench number — lands
# FIRST: tunnel windows have been short and rare, and a window spent on the
# microbench with the tunnel dropping before the bench would repeat the
# round-2 failure. Stages:
#  1. probe            — is the chip reachable at all?
#  2. bench            — the full primary metric + config matrix.
#  3. microbench       — dispatch RTT, superstep compile/steady per bucket,
#                        hashset insert vs two-key sort (the hash-scatter vs
#                        sort-dedup design decision), compaction styles.
#  4. pallas check     — does the opt-in Pallas insert lower on hardware?
#  5. soak             — device-scale full-coverage runs, stability-checked.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
log() { echo "[tpu_plan $(date +%H:%M:%S)] $*"; }

log "stage 1: probe"
if ! timeout 60 python -c "import jax; ds=jax.devices(); print(ds); assert ds[0].platform=='tpu'"; then
  log "tunnel not reachable; aborting"
  exit 1
fi

log "stage 2: full bench (the primary artifact)"
python bench.py

log "stage 3: microbench (results -> runs/tpu_microbench.log)"
timeout 1800 python tools/microbench.py 6 2>&1 | tee runs/tpu_microbench.log

# (stage 4, the compiled-Pallas insert probe, ran 2026-07-31 and the kernel
# failed to lower — tpu_pallas.log; kernel removed per the keep-or-kill rule.)

log "stage 5: device-scale soak (results -> runs/tpu_soak.log)"
# Two runs per config: full-coverage counts must be stable run-to-run.
timeout 3600 python - <<'EOF' 2>&1 | tee runs/tpu_soak.log
import os, time
import jax
jax.config.update("jax_compilation_cache_dir", os.path.abspath(".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

def soak(name, build, runs=2, budget_s=900, **kw):
    import jax.numpy as jnp
    results = []
    for i in range(runs):
        model = build()
        c = model.checker().spawn_xla(**kw)
        t0 = time.monotonic()
        while not c.is_done() and time.monotonic() - t0 < budget_s:
            c._run_block()
        dt = time.monotonic() - t0
        results.append((c.state_count(), c.unique_state_count(), c.max_depth(), c.is_done()))
        print(f"[soak] {name} run {i}: gen={c.state_count():,} uniq={c.unique_state_count():,} "
              f"depth={c.max_depth()} done={c.is_done()} in {dt:.1f}s "
              f"({c.state_count()/max(dt,1e-9):,.0f} gen/s) table=2^{c._table.capacity.bit_length()-1}",
              flush=True)
    stable = len(set(results)) == 1
    print(f"[soak] {name}: counts {'STABLE' if stable else 'UNSTABLE'} across {runs} runs", flush=True)

from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
# Unique-state growth is ~5.9x per RM (8,832 @ rm=5 ... 1,745,408 @ rm=8):
# rm=9 ~ 10M uniques, rm=10 ~ 60M. The sorted set runs at 3/4 load, so
# rm=10 needs a 2^27-row table (2.1 GB of planes in HBM) up front —
# pre-size it: every growth step at this scale is a recompile.
soak("2pc rm=9", lambda: PackedTwoPhaseSys(9),
     frontier_capacity=1 << 20, table_capacity=1 << 24)
# rm=10 runs the delta structure explicitly — bounding the per-level sort
# to the delta tier instead of the 2^27-row main table is exactly the
# regime it was built for; rm=9 stays on the accelerator default for the
# sorted-vs-delta contrast.
soak("2pc rm=10", lambda: PackedTwoPhaseSys(10), budget_s=1200,
     frontier_capacity=1 << 21, table_capacity=1 << 27, dedup="delta")
# rm=11 (~360M uniques) exceeds full coverage in budget; a bounded run
# still measures steady-state gen/s at 2^28 table scale (4.3 GB planes).
soak("2pc rm=11 (bounded)", lambda: PackedTwoPhaseSys(11), runs=1,
     budget_s=900, frontier_capacity=1 << 22, table_capacity=1 << 28,
     dedup="delta")
from stateright_tpu.models.paxos import PackedPaxos
soak("paxos 3c/3s", lambda: PackedPaxos(3, 3), budget_s=1200,
     frontier_capacity=1 << 19, table_capacity=1 << 25)
EOF

log "done; see BENCH output above, runs/bench_detail.json, runs/bench_probe.log, runs/tpu_soak.log"
