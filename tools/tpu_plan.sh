#!/usr/bin/env bash
# The TPU measurement plan, one command, each stage under its own watchdog.
# Run when the axon tunnel recovers (probe first). Stages:
#  1. probe            — is the chip reachable at all?
#  2. microbench       — dispatch RTT, superstep compile/steady per bucket,
#                        hashset insert vs two-key sort (the hash-scatter vs
#                        sort-dedup design decision), compaction styles.
#  3. pallas check     — does the opt-in Pallas insert lower on hardware?
#  4. bench            — the full primary metric + config matrix.
set -u
cd "$(dirname "$0")/.."
log() { echo "[tpu_plan $(date +%H:%M:%S)] $*"; }

log "stage 1: probe"
if ! timeout 60 python -c "import jax; ds=jax.devices(); print(ds); assert ds[0].platform=='tpu'"; then
  log "tunnel not reachable; aborting"
  exit 1
fi

log "stage 2: microbench (results -> tpu_microbench.log)"
timeout 1800 python tools/microbench.py 6 2>&1 | tee tpu_microbench.log

log "stage 3: compiled Pallas insert probe"
timeout 600 python - <<'EOF' 2>&1 | tee tpu_pallas.log
import numpy as np
import jax, jax.numpy as jnp
from stateright_tpu.ops import hashset
from stateright_tpu.ops.pallas_hashset import insert_pallas
hs = hashset.make(1 << 16, jnp)
rng = np.random.default_rng(0)
m = 256
hi = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
lo = jnp.asarray(rng.integers(1, 2**32, m, dtype=np.uint32))
act = jnp.ones((m,), bool)
try:
    hs2, is_new, ovf = insert_pallas(hs, hi, lo, hi, lo, act, interpret=False)
    ref, ref_new, ref_ovf = hashset.insert(hs, hi, lo, hi, lo, act)
    ok = bool(jnp.all(is_new == ref_new)) and not bool(jnp.any(ovf))
    print("pallas compiled insert:", "MATCHES XLA insert" if ok else "DIVERGES")
except Exception as e:
    print(f"pallas compiled insert FAILED to lower/run: {type(e).__name__}: {e}")
EOF

log "stage 4: full bench"
python bench.py
log "done; see BENCH output above, bench_detail.json, bench_probe.log"
