"""Supervised device soaks: rm=10+ full-coverage runs that SURVIVE wedges.

The ROADMAP soak goal (item 2: rm=10-12 at >= 10^8-10^9 generated states)
needs runs that outlive the axon tunnel's signature failure — wedging
forever mid-dispatch. ``tools/tpu_soak.py`` (the round-5 in-process soak
ladder) loses the whole search to one wedge; this driver runs ONE soak
config per invocation through the crash-recovery supervisor
(``stateright_tpu/supervise.py``):

- the worker (``--worker``) checks the config's model with in-loop
  auto-checkpointing (rotated, atomic, self-verifying —
  ``stateright_tpu/checkpoint.py``) and the heartbeat the supervisor
  injects via ``STPU_HEARTBEAT``;
- the parent watches heartbeat phase+staleness (wedged tunnel vs long XLA
  compile), kills the worker's process group on a wedge, and relaunches it
  RESUMING from the latest valid checkpoint rotation — a wedge costs one
  checkpoint interval, not the run;
- ``--cpu-fallback`` adds a final CPU attempt (hard timeout only) after
  the retries are spent.

Usage:
  python tools/soak.py [--config quick|rm9|rm10|rm11|paxos33] [--cpu]
                       [--budget-s N] [--retries N] [--every SPEC]
                       [--keep K] [--dedup D] [--cpu-fallback]

Artifacts land under ``runs/soak/`` (checkpoint rotations, worker stdout);
the final worker line is JSON with generated/unique/depth/done + resume
provenance. Exit code 0 = the supervised run reached full coverage (or its
state target). Under ``tools/tpu_watch.sh`` use the built-in
``soak_resume`` stage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SOAK_DIR = os.path.join(REPO, "runs", "soak")

#: name -> (model factory import spec, spawn kwargs, default budget_s).
#: Capacities follow tools/tpu_soak.py: pre-sized so growth recompiles
#: never interrupt the steady state.
CONFIGS = {
    "quick": ("2pc", 7, dict(frontier_capacity=1 << 17, table_capacity=1 << 19), 900),
    "rm9": ("2pc", 9, dict(frontier_capacity=1 << 20, table_capacity=1 << 24), 1800),
    "rm10": ("2pc", 10, dict(frontier_capacity=1 << 21, table_capacity=1 << 27), 2400),
    "rm11": ("2pc", 11, dict(frontier_capacity=1 << 22, table_capacity=1 << 28), 1800),
    "paxos33": ("paxos", (3, 3), dict(frontier_capacity=1 << 19, table_capacity=1 << 25), 2400),
}


def _build_model(kind, arg):
    if kind == "2pc":
        from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

        return PackedTwoPhaseSys(arg)
    from stateright_tpu.models.paxos import PackedPaxos

    return PackedPaxos(*arg)


def _parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--config", default="rm10", choices=sorted(CONFIGS))
    p.add_argument("--cpu", action="store_true", help="pin the worker to CPU")
    p.add_argument("--budget-s", type=float, default=None,
                   help="worker wall-clock budget (default per config)")
    p.add_argument("--retries", type=int, default=3)
    p.add_argument("--every", default="60s",
                   help="checkpoint cadence: N levels or 'Ns' seconds")
    p.add_argument("--keep", type=int, default=3, help="checkpoint rotations")
    p.add_argument("--dedup", default=None, help="visited-set structure override")
    p.add_argument("--stall-s", type=float, default=900.0)
    p.add_argument("--startup-grace-s", type=float, default=900.0)
    p.add_argument("--cpu-fallback", action="store_true",
                   help="one final CPU attempt after retries are spent")
    p.add_argument("--audit", action="store_true",
                   help="run the duplicate-key table audit at completion")
    # worker-mode internals
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--resume", default=None, help=argparse.SUPPRESS)
    return p.parse_args(argv)


def _worker(args) -> int:
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    kind, marg, kw, default_budget = CONFIGS[args.config]
    budget_s = args.budget_s if args.budget_s is not None else default_budget
    ck = os.path.join(SOAK_DIR, f"{args.config}.npz")
    print(
        f"[soak] worker config={args.config} platform="
        f"{jax.devices()[0].platform} resume={args.resume} budget={budget_s:.0f}s",
        flush=True,
    )
    spawn_kw = dict(
        kw,
        checkpoint_to=ck,
        checkpoint_every=args.every,
        checkpoint_keep=args.keep,
    )
    if args.dedup:
        spawn_kw["dedup"] = args.dedup
    if args.resume:
        spawn_kw["checkpoint"] = args.resume
    model = _build_model(kind, marg)
    c = model.checker().spawn_xla(**spawn_kw)
    start_depth = c._depth
    # Throughput baseline: a resume restores state_count, but only states
    # generated by THIS attempt happened inside dt (bench's _run_check
    # subtracts the same states0).
    gen0 = c.state_count()
    t0 = time.monotonic()
    last_hb = t0
    while not c.is_done() and time.monotonic() - t0 < budget_s:
        c._run_block()
        now = time.monotonic()
        if now - last_hb > 60:
            print(
                f"[soak] {args.config} progress: gen={c.state_count():,} "
                f"uniq={c.unique_state_count():,} depth={c.max_depth()} "
                f"t={now - t0:.0f}s",
                flush=True,
            )
            last_hb = now
    dt = time.monotonic() - t0
    # One last checkpoint at the final quiescent point: a budget-truncated
    # soak hands its successor exactly where it stopped.
    c.save_checkpoint(ck, keep=args.keep)
    audit = None
    if args.audit and c.is_done():
        try:
            from stateright_tpu.audit import audit_table

            audit = audit_table(c)
        except Exception as e:  # pragma: no cover - diagnostic path
            audit = {"error": f"{type(e).__name__}: {e}"}
    m = c.metrics()
    print(
        json.dumps(
            {
                "config": args.config,
                "backend": jax.default_backend(),
                "generated": c.state_count(),
                "unique": c.unique_state_count(),
                "max_depth": c.max_depth(),
                "done": c.is_done(),
                "sec": round(dt, 1),
                "generated_this_attempt": c.state_count() - gen0,
                "gen_per_sec": round(
                    (c.state_count() - gen0) / max(dt, 1e-9), 1
                ),
                "resumed_from": args.resume,
                "start_depth": start_depth,
                "checkpoints_written": m["checkpoints_written"],
                "last_checkpoint_level": m["last_checkpoint_level"],
                "audit": audit,
            }
        ),
        flush=True,
    )
    return 0 if c.is_done() else 1


def main(argv=None) -> int:
    args = _parse_args(argv)
    if args.worker:
        return _worker(args)

    from stateright_tpu import supervise as sup

    os.makedirs(SOAK_DIR, exist_ok=True)
    ck = os.path.join(SOAK_DIR, f"{args.config}.npz")
    kind, marg, kw, default_budget = CONFIGS[args.config]
    budget_s = args.budget_s if args.budget_s is not None else default_budget

    def _log(msg):
        print(f"[soak] {msg}", file=sys.stderr, flush=True)

    def _argv(cpu):
        base = [sys.executable, os.path.abspath(__file__), "--worker",
                "--config", args.config, "--every", args.every,
                "--keep", str(args.keep),
                "--budget-s", str(budget_s)]
        if args.dedup:
            base += ["--dedup", args.dedup]
        if args.audit:
            base += ["--audit"]
        if cpu:
            base += ["--cpu"]
        return base

    # A COMPLETED checkpoint is not resumable work: resuming it would
    # instantly report done=true with zero states explored this run —
    # stale data dressed as a fresh successful soak. Clear every rotation
    # and re-measure. (A PARTIAL checkpoint must survive: a restarted
    # tpu_watch.sh stage resumes exactly there — that is the point.)
    from stateright_tpu.checkpoint import latest_valid_checkpoint, rotations

    done_path, done_meta = latest_valid_checkpoint(ck, with_meta=True)
    if done_meta is not None and done_meta.get("done", False):
        _log(f"clearing completed checkpoint {done_path}; re-measuring fresh")
        for f in rotations(ck):
            try:
                os.unlink(f)
            except OSError:
                pass

    def make_argv(attempt, resume):
        return _argv(args.cpu) + (["--resume", resume] if resume else [])

    def fallback_argv(attempt, resume):
        return _argv(True) + (["--resume", resume] if resume else [])

    # Nested supervision: under tools/tpu_watch.sh the stage's own
    # STPU_HEARTBEAT is reused as the worker's beat file, so the outer
    # watcher (looser leash) sees the same liveness this parent does.
    hb = os.environ.get("STPU_HEARTBEAT") or os.path.join(
        SOAK_DIR, f"{args.config}.heartbeat.json"
    )
    if args.cpu:
        # No tunnel, no wedge: only the hard timeout supervises a CPU
        # soak, and an outer watcher must not read CPU-paced beats
        # (bench.py's CPU fallback does the same).
        os.environ.pop("STPU_HEARTBEAT", None)
    res = sup.supervise(
        make_argv,
        checkpoint=ck,
        retries=args.retries,
        backoff_s=10.0,
        heartbeat=None if args.cpu else hb,
        timeout_s=budget_s + max(600.0, budget_s),
        stall_s=args.stall_s,
        startup_grace_s=args.startup_grace_s,
        stdout_path=lambda attempt: os.path.join(
            SOAK_DIR, f"{args.config}.worker{attempt}.out"
        ),
        fallback_make_argv=fallback_argv if args.cpu_fallback else None,
        fallback_timeout_s=budget_s + max(600.0, budget_s),
        log=_log,
        cwd=REPO,
    )
    for i, (att, resume) in enumerate(zip(res.attempts, res.resumed_from)):
        _log(
            f"attempt {i}: rc={att.rc} killed={att.killed} "
            f"{att.seconds:.0f}s resume={resume}"
        )
    if res.final is not None and res.final.stdout_path:
        try:
            with open(res.final.stdout_path) as fh:
                sys.stdout.write(fh.read())
        except OSError:
            pass
    _log(f"supervised soak {'OK' if res.ok else 'FAILED'} "
         f"({len(res.attempts)} attempts, fallback={res.used_fallback})")
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
