#!/usr/bin/env python
"""Post-mortem trace bundle for a service/fleet run dir.

One command snapshots everything a post-mortem needs into a single
self-contained directory:

- ``trace.merged.json`` — the whole run's merged distributed-trace
  timeline (``stateright_tpu.obs.collect``: every ``trace.jsonl`` under
  the run dir on one Chrome/Perfetto time axis, per-process tracks, flow
  arrows per trace id);
- ``journals/`` — every job journal (``journal.jsonl`` + rotations) and
  the fleet routing journal (``fleet.jsonl``), preserving relative
  paths, so replay forensics work offline;
- ``heartbeats/`` — the last heartbeat file of every worker
  (``hb.json``/``mux-hb.json``) — what the watchdog saw at death;
- ``metrics/`` — per-job metrics time-series rotations
  (``metrics.jsonl*``);
- ``lint.json`` — the flight-check verdict (``--lint`` path, default
  ``runs/lint.json``, skipped silently when absent);
- ``manifest.json`` — the inventory: source run dir, file lists, merged
  trace ids, and event counts.

Pure host-side file copying — no jax, no device, safe on a box whose
tunnel just wedged. Usage::

    python tools/trace_bundle.py runs/fleet            # -> runs/fleet-bundle/
    python tools/trace_bundle.py runs/svc --out /tmp/b --lint runs/lint.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stateright_tpu.obs import collect as collect_mod  # noqa: E402

#: (bundle subdir, filename predicate) — what the walker snapshots.
_JOURNALS = ("journal.jsonl", "fleet.jsonl")
_HEARTBEATS = ("hb.json", "mux-hb.json", "heartbeat.json")


def _is_journal(name: str) -> bool:
    # journal.jsonl, journal.jsonl.1.. (rotations), fleet.jsonl(.N)
    base = name.split(".jsonl")[0] + ".jsonl"
    return base in _JOURNALS and name.startswith(base.split(".jsonl")[0])


def _is_metrics(name: str) -> bool:
    return name == "metrics.jsonl" or name.startswith("metrics.jsonl.")


def bundle(run_dir: str, out_dir: str,
           lint_path: str = os.path.join("runs", "lint.json")) -> dict:
    """Builds the bundle; returns the manifest dict (also written to
    ``<out_dir>/manifest.json``)."""
    if not os.path.isdir(run_dir):
        raise SystemExit(f"not a run dir: {run_dir}")
    os.makedirs(out_dir, exist_ok=True)

    copied = {"journals": [], "heartbeats": [], "metrics": []}
    for root, _dirs, files in os.walk(run_dir):
        # Never walk into a previous bundle nested in the run dir.
        if os.path.abspath(root).startswith(os.path.abspath(out_dir)):
            continue
        for name in files:
            if _is_journal(name):
                kind = "journals"
            elif name in _HEARTBEATS:
                kind = "heartbeats"
            elif _is_metrics(name):
                kind = "metrics"
            else:
                continue
            src = os.path.join(root, name)
            rel = os.path.relpath(src, run_dir)
            dst = os.path.join(out_dir, kind, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            try:
                shutil.copy2(src, dst)
            except OSError:
                continue  # a file swept mid-walk is not fatal
            copied[kind].append(rel)

    trace_obj = collect_mod.collect(run_dir)
    trace_out = os.path.join(out_dir, "trace.merged.json")
    with open(trace_out, "w") as fh:
        json.dump(trace_obj, fh)

    lint_copied = False
    if lint_path and os.path.exists(lint_path):
        try:
            shutil.copy2(lint_path, os.path.join(out_dir, "lint.json"))
            lint_copied = True
        except OSError:
            pass

    manifest = {
        "run_dir": os.path.abspath(run_dir),
        "trace_files": trace_obj["otherData"]["trace_files"],
        "trace_ids": trace_obj["otherData"]["traces"],
        "trace_events": len(trace_obj["traceEvents"]),
        "journals": sorted(copied["journals"]),
        "heartbeats": sorted(copied["heartbeats"]),
        "metrics": sorted(copied["metrics"]),
        "lint": lint_copied,
    }
    with open(os.path.join(out_dir, "manifest.json"), "w") as fh:
        json.dump(manifest, fh, indent=2)
    return manifest


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", help="service/fleet run dir to snapshot")
    ap.add_argument("--out", default=None,
                    help="bundle dir (default: <run_dir>-bundle)")
    ap.add_argument("--lint", default=os.path.join("runs", "lint.json"),
                    help="lint verdict JSON to include (skipped if absent)")
    args = ap.parse_args(argv)
    out = args.out or (args.run_dir.rstrip("/\\") + "-bundle")
    manifest = bundle(args.run_dir, out, lint_path=args.lint)
    print(json.dumps({
        "bundle": os.path.abspath(out),
        "trace_events": manifest["trace_events"],
        "trace_ids": len(manifest["trace_ids"]),
        "journals": len(manifest["journals"]),
        "heartbeats": len(manifest["heartbeats"]),
        "metrics": len(manifest["metrics"]),
        "lint": manifest["lint"],
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
