"""Explorer: an interactive web service for walking the state space.

Mirrors ``/root/reference/src/checker/explorer.rs``: ``serve()`` wraps a
demand-driven checker (``spawn_on_demand``) with a small HTTP API —

- ``GET /.status`` → :class:`StatusView` JSON (done, model name, counts,
  properties with encoded discovery paths, recent path snapshot)
  (explorer.rs:156-176);
- ``GET /.states/{fp}/{fp}/…`` → a list of ``StateView`` JSON objects: one
  per action available in the state reached by replaying the fingerprint
  path, including "ignored" actions (``next_state`` → None), and asks the
  checker to expand each child on demand (explorer.rs:209-312);
- ``POST /.runtocompletion`` → unblocks the checker (explorer.rs:178-187) —

plus the service/telemetry surface this framework adds on top of the
reference contract: ``GET /.pool`` (full pool status), ``GET /.metrics``
(OpenMetrics exposition of session + pool + every job;
``stateright_tpu/obs/promexport.py``), ``GET /.jobs/{id}/metrics.json``
(windowed metrics time-series) and ``GET /.jobs/{id}/trace.json``
(Perfetto export), and ``GET /.dash`` — the live pool dashboard
(``ui/dash.htm``; docs/observability.md "Dashboard") — plus the
single-page UI in ``stateright_tpu/ui/`` (an original implementation;
the reference vendors a Knockout.js app with the same HTTP contract). UI
files are read from ``./ui/`` if present (dev mode, like
explorer.rs:118-131) else from the installed package.

The app logic lives in :class:`ExplorerApp`, framework-free and directly
callable — tests drive it without a live server, as the reference's tests
call actix handlers directly (explorer.rs:314-588). The HTTP layer is a
thin stdlib ``ThreadingHTTPServer`` handler; all checker access is
serialized by a lock since the demand-driven engine is single-threaded.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path as FsPath
from typing import Any, List, Optional, Tuple
from urllib.parse import parse_qs

from ..core import Expectation
from ..fingerprint import fingerprint
from ..obs import heartbeat as hb_mod
from ..obs import promexport
from ..obs.timeseries import SCHEMA_VERSION
from .path import Path

_UI_DIR = FsPath(__file__).resolve().parent.parent / "ui"
_UI_FILES = {
    "/": ("index.htm", "text/html"),
    "/app.css": ("app.css", "text/css"),
    "/app.js": ("app.js", "text/javascript"),
    "/.dash": ("dash.htm", "text/html"),
    "/dash.js": ("dash.js", "text/javascript"),
}

#: Default/maximum rows a windowed series request returns (the dashboard
#: polls with small windows; an unbounded ?n= must not stream a soak's
#: whole rotation chain through one poll).
_SERIES_WINDOW = 256
_SERIES_WINDOW_MAX = 4096

#: serde renders Rust unit variants with their name (explorer.rs:13 via
#: lib.rs:317), and the UI switches on these strings (ui/app.js:38-43).
_EXPECTATION_NAMES = {
    Expectation.ALWAYS: "Always",
    Expectation.SOMETIMES: "Sometimes",
    Expectation.EVENTUALLY: "Eventually",
}


class Snapshot:
    """Most-recent-path visitor state, re-armed every 4 seconds
    (explorer.rs:63-78, 90-96): between re-arms only the first visited path
    is kept, so the "recent path" display is a cheap sample, not a log."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed = True
        self.actions: Optional[List[Any]] = None

    def visit(self, path: Path) -> None:
        with self._lock:
            if not self._armed:
                return
            self._armed = False
            self.actions = path.into_actions()

    def rearm(self) -> None:
        with self._lock:
            self._armed = True


class ExplorerApp:
    """The Explorer's request handlers, independent of any HTTP machinery.

    The Explorer is ONE CLIENT of the :class:`~stateright_tpu.service`
    pool: ``make_app``/``serve`` register the interactive checker as a
    service job, so it is admission-controlled and counted alongside batch
    tenants, and ``/.status`` carries the pool gauges under ``"pool"``."""

    def __init__(self, checker, snapshot: Optional[Snapshot] = None,
                 service=None, job=None):
        self._checker = checker
        self._snapshot = snapshot or Snapshot()
        self._lock = threading.Lock()
        self._service = service
        self._job = job
        # Live metrics ring for the interactive session: batch jobs have
        # a recorded metrics.jsonl under their job dir, but this app's
        # own checker runs in-process — each /.jobs/{id}/metrics.json
        # poll appends one live sample, so a polling dashboard builds the
        # series it charts (docs/observability.md "Dashboard").
        self._series: deque = deque(maxlen=_SERIES_WINDOW_MAX)
        self._series_seq = 0
        self._series_epoch = time.monotonic()

    # --- handlers ---------------------------------------------------------

    def status(self) -> dict:
        """``GET /.status`` (explorer.rs:156-176)."""
        with self._lock:
            checker = self._checker
            recent = self._snapshot.actions
            out = {
                "done": checker.is_done(),
                "model": type(checker.model()).__name__,
                "state_count": checker.state_count(),
                "unique_state_count": checker.unique_state_count(),
                "max_depth": checker.max_depth(),
                "properties": self._properties(),
                "recent_path": repr(recent) if recent is not None else None,
                # The unified obs snapshot (docs/observability.md): on the
                # device backend this is the full engine registry
                # (occupancy, dispatch/growth counters); host backends
                # report the base counters.
                "metrics": checker.metrics(),
                # Recovery state: the last auto/manual checkpoint this
                # checker wrote ({path, depth, states, unique, unix_ts}),
                # or None — so a wedged interactive session is diagnosable
                # (and resumable) from the outside.
                "last_checkpoint": getattr(checker, "_last_checkpoint", None),
                # Liveness: seconds since this checker's heartbeat file
                # was last rewritten (host-side mtime read), or None when
                # the heartbeat protocol is off — a wedging session is
                # visible from the status surface without tailing files.
                "heartbeat_age_s": self._heartbeat_age(checker),
            }
            # Service client fields (additive — the pre-service keys above
            # are unchanged for existing consumers): this session's pool
            # job id, whether it is served degraded (host fallback while
            # the device breaker is open), and the pool-wide gauges.
            if self._service is not None:
                out["job"] = self._job.id if self._job is not None else None
                out["degraded"] = (
                    self._job.degraded if self._job is not None else False
                )
                out["pool"] = self._service.gauges()
            return out

    def run_to_completion(self) -> None:
        """``POST /.runtocompletion`` (explorer.rs:178-187). Kicks the
        engine forward so progress is visible from subsequent ``/.status``
        polls even though this server has no background workers."""
        with self._lock:
            self._checker.run_to_completion()

    def drive(self, max_count: int = 1500) -> None:
        """Advance an unblocked checker by one block (the in-process
        equivalent of the reference's background worker threads)."""
        with self._lock:
            if not self._checker.is_done():
                self._checker._run_block(max_count)

    def states(self, fingerprints_str: str) -> Tuple[int, Any]:
        """``GET /.states{fingerprints}`` (explorer.rs:209-312). Returns
        ``(http_status, body)``; 404 bodies are error strings."""
        fingerprints_str = fingerprints_str.rstrip("/")
        parts = fingerprints_str.split("/")
        fingerprints: List[int] = []
        for part in parts:
            if not part:
                continue
            try:
                fingerprints.append(int(part))
            except ValueError:
                return 404, f"Unable to parse fingerprints {fingerprints_str}"
        # All but the leading empty segment must have parsed
        # (explorer.rs:233-240).
        if len(fingerprints) + 1 != len(parts):
            return 404, f"Unable to parse fingerprints {fingerprints_str}"

        with self._lock:
            model = self._checker.model()
            results = []
            # The device-backed checker keys pending work by DEVICE
            # fingerprint, which only the packed codec can compute — it
            # takes the states themselves (batched: one device dispatch per
            # request); the host checker takes host fps one at a time.
            check_states = getattr(self._checker, "check_states", None)
            if not fingerprints:
                inits = list(model.init_states())
                if check_states is not None:
                    check_states(inits)
                for state in inits:
                    fp = fingerprint(state)
                    if check_states is None:
                        self._checker.check_fingerprint(fp)
                    results.append(
                        self._state_view(model, None, None, state, [fp])
                    )
                return 200, results

            last_state = Path.final_state(model, fingerprints)
            if last_state is None:
                return (
                    404,
                    f"Unable to find state following fingerprints {fingerprints_str}",
                )
            actions: List[Any] = []
            model.actions(last_state, actions)
            # check_fingerprint below can add discoveries, so evaluate the
            # property triples once after all expansions, then share them
            # across views (the reference rebuilds them per view,
            # explorer.rs:256-301; once per request is observably the same).
            views = []
            for action in actions:
                outcome = model.format_step(last_state, action)
                state = model.next_state(last_state, action)
                if state is not None:
                    fp = fingerprint(state)
                    if check_states is None:
                        self._checker.check_fingerprint(fp)
                    views.append((action, outcome, state, fp))
                else:
                    # "Action ignored" is still returned — useful for
                    # debugging (explorer.rs:292-300).
                    views.append((action, None, None, None))
            if check_states is not None:
                check_states([s for _, _, s, _ in views if s is not None])
            properties = self._properties()
            for action, outcome, state, fp in views:
                if state is not None:
                    view = self._state_view(
                        model,
                        model.format_action(action),
                        outcome,
                        state,
                        fingerprints + [fp],
                        properties=properties,
                    )
                else:
                    view = {
                        "action": model.format_action(action),
                        "properties": properties,
                    }
                results.append(view)
            return 200, results

    def close(self) -> None:
        """Releases this session's pool slot (``max_sessions`` admission).
        ``serve()`` calls this at server shutdown; embedders that build
        apps against a long-lived shared service must call it too, or the
        session occupies a slot forever."""
        if self._service is not None and self._job is not None:
            self._service.release_interactive(self._job)

    def pool(self) -> Tuple[int, Any]:
        """``GET /.pool`` — the full service status surface (pool gauges +
        per-job snapshots); 404 without a service."""
        if self._service is None:
            return 404, "no service attached"
        return 200, self._service.metrics()

    def job_trace(self, job_id: str) -> Tuple[int, Any]:
        """``GET /.jobs/{id}/trace.json`` — the job's span trace as
        Perfetto-loadable Chrome trace JSON (``obs.export_chrome``). A 200
        body is the exported file's raw bytes (already JSON): the export
        is mtime-cached service-side, and re-parsing it per poll just to
        re-serialize would cost O(trace) each request."""
        if self._service is None:
            return 404, "no service attached"
        try:
            path = self._service.job_trace_chrome(job_id)
        except KeyError:
            return 404, f"unknown job {job_id}"
        if path is None:
            return 404, f"job {job_id} has no span trace"
        with open(path, "rb") as fh:
            return 200, fh.read()

    def merged_trace(self) -> Tuple[int, Any]:
        """``GET /.trace.json`` — the service/fleet's whole merged
        distributed-trace timeline (``obs.collect`` over the run dir: one
        Chrome trace, per-process tracks, flow arrows per trace id). Like
        :meth:`job_trace`, the 200 body is the mtime-cached export's raw
        bytes."""
        if self._service is None:
            return 404, "no service attached"
        merger = getattr(self._service, "merged_trace_chrome", None)
        if merger is None:
            return 404, "service has no merged trace surface"
        path = merger()
        if path is None:
            return 404, "no span traces in the run dir (tracing off?)"
        with open(path, "rb") as fh:
            return 200, fh.read()

    def metrics_text(self) -> str:
        """``GET /.metrics`` — the OpenMetrics exposition of this session
        plus (when service-backed) the pool gauges and every pool job's
        engine snapshot, labeled ``job``/``engine``/``dedup``
        (``stateright_tpu/obs/promexport.py``; docs/observability.md
        "/.metrics"). Counters match ``checker.metrics()`` exactly —
        pinned by tests/test_promexport.py and the smoke stage's scrape."""
        samples: List[promexport.Sample] = [promexport.build_info_sample()]
        with self._lock:
            own = self._checker.metrics()
        own_label = self._job.id if self._job is not None else "interactive"
        samples += promexport.engine_samples(own, {"job": own_label})
        if self._service is not None:
            gauges = self._service.gauges()
            devices = gauges.get("devices") or {}
            if devices:
                # A fleet: pool families render ONLY as per-device
                # labeled rows (an unlabeled aggregate repeating them
                # would double PromQL sums — the per-device sums ARE the
                # aggregates). Fleet-scoped state exports under its own
                # stpu_fleet_* families: the fleet counters (submit
                # dedup/rejection happen BEFORE any pool sees them, so
                # per-device rows can't carry them), the fleet breaker
                # verdict, fleet.jsonl position, and device counts.
                from ..service.fleet import FLEET_COUNTERS

                agg_keys = set().union(
                    *(d.keys() for d in devices.values())
                )
                samples += promexport.pool_samples(
                    {
                        k: v for k, v in gauges.items()
                        if k not in agg_keys
                        or k in ("breaker", "journal")
                        or k in FLEET_COUNTERS
                    },
                    prefix="stpu_fleet",
                )
                for device, dev_gauges in devices.items():
                    samples += promexport.pool_samples(
                        dev_gauges, {"device": device}
                    )
            else:
                samples += promexport.pool_samples(gauges)
            for job in self._service.jobs():
                if self._job is not None and job.id == self._job.id:
                    continue  # this session's checker is already rendered
                m = job.metrics()
                if m is not None:
                    samples += promexport.engine_samples(m, {"job": job.id})
        return promexport.render_openmetrics(samples)

    def job_metrics(self, job_id: str, window: Optional[int] = None) -> Tuple[int, Any]:
        """``GET /.jobs/{id}/metrics.json`` — the job's windowed metrics
        time-series as ``{"job", "window", "rows"}``, rows oldest first.
        Batch jobs serve their recorded per-job ``metrics.jsonl``; this
        session's own interactive checker serves a live ring that grows
        one sample per poll (see ``__init__``)."""
        # Clamp into [1, max]: a zero/negative ?n= must not bypass the
        # window and stream a soak's whole rotation chain in one poll.
        window = max(1, min(window or _SERIES_WINDOW, _SERIES_WINDOW_MAX))
        if self._job is not None and job_id == self._job.id or (
            self._service is None and job_id == "interactive"
        ):
            # Sample + append + snapshot under ONE lock hold: the server
            # is threading, and concurrent polls racing the deque would
            # tear the snapshot and interleave seq out of order.
            with self._lock:
                m = self._checker.metrics()
                # Monotonic row seq (the recorder contract) — NOT the ring
                # length, which pins at maxlen once the deque fills.
                seq = self._series_seq
                self._series_seq += 1
                self._series.append(
                    {
                        "v": SCHEMA_VERSION,
                        "unix_ts": time.time(),
                        "t": round(time.monotonic() - self._series_epoch, 6),
                        "seq": seq,
                        "kind": "live",
                        "metrics": m,
                    }
                )
                rows = list(self._series)[-window:]
            return 200, {"job": job_id, "window": window, "rows": rows}
        if self._service is None:
            return 404, "no service attached"
        try:
            rows = self._service.job_metrics_series(job_id, window=window)
        except KeyError:
            return 404, f"unknown job {job_id}"
        if rows is None:
            return 404, f"job {job_id} has no metrics series"
        return 200, {"job": job_id, "window": window, "rows": rows}

    # --- helpers ----------------------------------------------------------

    def _heartbeat_age(self, checker) -> Optional[float]:
        hb = getattr(checker, "_heartbeat", None)
        if hb is None:
            return None
        age = hb_mod.age_s(hb.path)
        return None if age is None else round(age, 3)

    def _properties(self) -> List[Tuple[str, str, Optional[str]]]:
        """(expectation, name, encoded discovery path) triples
        (explorer.rs:187-205)."""
        checker = self._checker
        discoveries = checker.discoveries()
        return [
            (
                _EXPECTATION_NAMES[p.expectation],
                p.name,
                discoveries[p.name].encode() if p.name in discoveries else None,
            )
            for p in checker.model().properties()
        ]

    def _state_view(
        self, model, action, outcome, state, fps: List[int], properties=None
    ) -> dict:
        view = {
            "state": _pretty(state),
            "fingerprint": str(fps[-1]),
            "properties": self._properties() if properties is None else properties,
        }
        if action is not None:
            view["action"] = action
        if outcome is not None:
            view["outcome"] = outcome
        # Replaying the whole path (required to build the Path that as_svg
        # consumes) is only worth it when the model actually overrides the
        # core no-op as_svg (core.py:90).
        from ..core import Model as _BaseModel

        if type(model).as_svg is not _BaseModel.as_svg:
            try:
                svg = model.as_svg(Path.from_fingerprints(model, fps))
            except Exception:
                svg = None
            if svg is not None:
                view["svg"] = svg
        return view


def _pretty(state: Any) -> str:
    """A multi-line state rendering (the analogue of Rust's ``{:#?}``,
    explorer.rs:49)."""
    try:
        import pprint

        return pprint.pformat(state, width=60)
    except Exception:
        return repr(state)


def serve(builder, addresses, engine: str = "auto", service=None,
          **spawn_kwargs):
    """Starts the Explorer web service; blocks forever (checker.rs:137-144).

    ``addresses`` is a ``"host:port"`` string or ``(host, port)`` tuple.
    ``engine`` selects the demand-driven backend: ``"host"`` (the Python
    oracle), ``"xla"`` (the device engine,
    :class:`~stateright_tpu.checker.device_on_demand.DeviceOnDemandChecker`),
    or ``"auto"`` — xla whenever the model is packed, like the reference
    Explorer wrapping its real engine (explorer.rs:81-103). ``service`` is
    the :class:`~stateright_tpu.service.CheckerService` pool to join as a
    client (one is created when omitted); while its breaker is open,
    ``"auto"``/``"xla"`` sessions degrade to the host engine with
    ``degraded: true`` in ``/.status``. Returns the checker (for tests
    that build the service without blocking, use :func:`make_app`).
    """
    app, checker = make_app(
        builder, engine=engine, service=service, **spawn_kwargs
    )
    host, port = _parse_address(addresses)

    class Handler(_ExplorerHandler):
        explorer_app = app

    server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=_rearm_loop, args=(app,), daemon=True).start()
    threading.Thread(target=_drive_loop, args=(app,), daemon=True).start()
    print(f"Exploring. http://{host}:{port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    finally:
        app.close()  # release the pool session slot
    return checker


def make_app(builder, engine: str = "auto", service=None, **spawn_kwargs):
    """Builds the Explorer app + demand-driven checker without binding a
    socket (the test entry point, mirroring explorer.rs:314-351). See
    :func:`serve` for ``engine``; ``spawn_kwargs`` reach the device
    checker (capacities etc.).

    The checker is registered as one interactive job of ``service`` (a
    default :class:`~stateright_tpu.service.CheckerService` when omitted —
    construction is thread-free until batch jobs are submitted), so the
    Explorer is admission-controlled (``AdmissionError`` past
    ``max_sessions``) and the pool gauges ride in ``/.status``. With the
    service's breaker open, device-engine requests are served DEGRADED on
    the host on-demand engine — the service owns the device, and an open
    breaker means it is not handing it to anyone."""
    from ..service import CheckerService
    from ..xla import is_packed

    if service is None:
        service = CheckerService()
    # Admission BEFORE construction: building the device backend allocates
    # device-resident buffers, which is exactly the spend the session cap
    # exists to gate.
    service.check_session_capacity()
    snapshot = Snapshot()
    degraded = False
    wants_device = engine == "xla" or (
        engine == "auto" and is_packed(builder._model)
    )
    if wants_device and service.degraded:
        wants_device, degraded = False, True
    if wants_device:
        from .device_on_demand import DeviceOnDemandChecker

        # The snapshot visitor would force one-level dispatches in batch
        # mode; the device Explorer favors the fused run-to-completion and
        # leaves the recent-path panel to the host backend.
        checker = DeviceOnDemandChecker(builder, **spawn_kwargs)
    else:
        if spawn_kwargs and not degraded:
            raise TypeError(
                f"spawn kwargs {sorted(spawn_kwargs)} only apply to the "
                "device engine; this model resolves to the host backend"
            )
        # A degraded session silently drops the device-engine capacities —
        # the host oracle has none to size.
        checker = builder.visitor(snapshot.visit).spawn_on_demand()
    job = service.register_interactive(checker, degraded=degraded)
    return ExplorerApp(checker, snapshot, service=service, job=job), checker


def _rearm_loop(app: ExplorerApp) -> None:
    while True:
        time.sleep(4)
        app._snapshot.rearm()


def _drive_loop(app: ExplorerApp) -> None:
    """Advances the checker once unblocked — the reference's worker threads
    do this; here a single background thread suffices."""
    while True:
        time.sleep(0.05)
        app.drive()


def _parse_address(addresses) -> Tuple[str, int]:
    if isinstance(addresses, (tuple, list)):
        host, port = addresses
        return str(host), int(port)
    host, _, port = str(addresses).rpartition(":")
    return host or "localhost", int(port)


class _ExplorerHandler(BaseHTTPRequestHandler):
    explorer_app: ExplorerApp = None  # injected by serve()

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload: Any) -> None:
        self._send(code, json.dumps(payload).encode(), "application/json")

    def do_GET(self):  # noqa: N802 (stdlib API)
        path, _, query = self.path.partition("?")
        if path == "/.status":
            self._send_json(200, self.explorer_app.status())
        elif path == "/.metrics":
            body = self.explorer_app.metrics_text().encode()
            self._send(200, body, promexport.CONTENT_TYPE)
        elif path == "/.pool":
            code, body = self.explorer_app.pool()
            if code == 200:
                self._send_json(200, body)
            else:
                self._send(code, str(body).encode(), "text/plain")
        elif path.startswith("/.jobs/") and path.endswith("/metrics.json"):
            job_id = path[len("/.jobs/"):-len("/metrics.json")]
            try:
                window = int(parse_qs(query).get("n", [0])[0]) or None
            except ValueError:
                window = None
            code, body = self.explorer_app.job_metrics(job_id, window)
            if code == 200:
                self._send_json(200, body)
            else:
                self._send(code, str(body).encode(), "text/plain")
        elif path.startswith("/.jobs/") and path.endswith("/trace.json"):
            job_id = path[len("/.jobs/"):-len("/trace.json")]
            code, body = self.explorer_app.job_trace(job_id)
            if code == 200:
                self._send(200, body, "application/json")
            else:
                self._send(code, str(body).encode(), "text/plain")
        elif path == "/.trace.json":
            code, body = self.explorer_app.merged_trace()
            if code == 200:
                self._send(200, body, "application/json")
            else:
                self._send(code, str(body).encode(), "text/plain")
        elif path.startswith("/.states"):
            code, body = self.explorer_app.states(path[len("/.states"):])
            if code == 200:
                self._send_json(200, body)
            else:
                self._send(code, str(body).encode(), "text/plain")
        elif path in _UI_FILES:
            name, content_type = _UI_FILES[path]
            dev = FsPath("./ui") / name
            f = dev if dev.exists() else _UI_DIR / name
            if f.exists():
                self._send(200, f.read_bytes(), content_type)
            else:
                self._send(404, b"missing UI file", "text/plain")
        else:
            self._send(404, b"not found", "text/plain")

    def do_POST(self):  # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        if path == "/.runtocompletion":
            self.explorer_app.run_to_completion()
            self._send_json(200, None)
        else:
            self._send(404, b"not found", "text/plain")
