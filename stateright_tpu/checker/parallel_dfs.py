"""Parallel DFS with dynamic work sharing — the reference's default CLI
checker discipline (``/root/reference/src/checker/dfs.rs``).

Structure mirrors the reference faithfully:

- a shared **job market** of pending-stack segments with a low-water mark:
  a worker whose local stack still has work splits it and re-stocks the
  market whenever the market runs below ``n`` jobs (the job market of
  dfs.rs:92-215);
- every worker runs plain LIFO exploration over its local stack
  (dfs.rs:230-407), against one **shared** visited set / parent map — the
  role the reference gives its concurrent DashMap (dfs.rs:29-31);
- discovery races are benign and first-wins (dfs.rs:291-306 lets worker
  threads race on the discovery slot; here the merge is under one lock);
- termination: market empty AND every worker idle, or every property has a
  discovery, or a state/depth target trips.

Concurrency medium: ``threading`` against plain dict/set — under CPython
these are the exact analogue of the reference's shared concurrent map (the
interpreter serializes the primitive operations; the lock guards the
check-then-act sequences). This host is the correctness/semantics engine:
like the multiprocess BFS (``parallel_host.py``), throughput parallelism in
this framework is the device engine's job (``xla.py``); this engine exists
so every reference checker discipline has a working counterpart (the
``threads(n)`` + DFS combination the round-3 verdict flagged).

Semantics notes, shared with the reference's parallel DFS:

- full-coverage ``state_count``/``unique_state_count`` are exact and
  engine-invariant (every unique state expands exactly once, so generated =
  sum of reachable out-degrees + inits);
- visit ORDER is scheduling-dependent, so early-exit timing and
  eventually-property false-negative patterns (ebits travel with the first
  visit) vary run-to-run exactly as the reference's racing threads do;
  full-coverage counts do not.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..core import Model
from ..fingerprint import fingerprint
from .base import Checker
from .parallel_host import _eval_properties
from .path import Path


class ParallelDfsChecker(Checker):
    """Job-market parallel DFS behind ``threads(n)`` + ``spawn_dfs()``."""

    #: A worker splits its stack back into the market whenever the market
    #: holds fewer jobs than this multiple of the worker count
    #: (dfs.rs:92-215's low-water mark).
    MARKET_LOW_FACTOR = 1

    def __init__(self, builder):
        if builder._visitor is not None:
            raise ValueError(
                "threads(n)>1 with a visitor is unsupported: visitors observe "
                "per-state paths sequentially. Drop the visitor or threads()."
            )
        self._model: Model = builder._model
        self._n = max(2, builder._thread_count or 0)
        self._symmetry = builder._symmetry
        self._target_state_count = builder._target_state_count
        self._target_max_depth = builder._target_max_depth
        self._properties = self._model.properties()
        self._prop_names = [p.name for p in self._properties]
        self._ebits0 = frozenset(
            i
            for i, p in enumerate(self._properties)
            if p.expectation.name == "EVENTUALLY"
        )

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._market: List[List[tuple]] = []  # jobs: stack segments
        self._idle = 0
        self._stop = False
        self._done_event = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started_threads = False

        self._visited: set = set()  # representative fingerprints
        self._parents: Dict[int, Optional[int]] = {}
        self._discoveries: Dict[int, int] = {}  # prop index -> witness fp
        self._max_depth = 0
        self._target_reached = False
        self._exhausted = False

        init_states = [
            s for s in self._model.init_states() if self._model.within_boundary(s)
        ]
        self._state_count = len(init_states)
        self._unique_count = 0
        seed: List[tuple] = []
        for s in init_states:
            fp = fingerprint(s)
            rfp = self._rep_fp(s, fp)
            if rfp not in self._visited:
                self._visited.add(rfp)
                self._unique_count += 1
            if fp not in self._parents:
                self._parents[fp] = None
            # EVERY init seeds an entry — duplicates included — exactly as
            # the sequential oracle enqueues them (search.py), so
            # full-coverage state_count stays engine-invariant.
            seed.append((s, fp, self._ebits0, 1))
        if seed:
            # One seed job per worker where possible, so exploration fans
            # out immediately.
            k = max(1, len(seed) // self._n)
            self._market = [seed[i : i + k] for i in range(0, len(seed), k)]
        else:
            self._exhausted = True
            self._done_event.set()

    def _rep_fp(self, state, fp: int) -> int:
        if self._symmetry is None:
            return fp
        return fingerprint(self._symmetry(state))

    # --- worker ------------------------------------------------------------

    def _worker(self) -> None:
        model = self._model
        properties = self._properties
        market_low = self.MARKET_LOW_FACTOR * self._n
        try:
            while True:
                with self._cv:
                    while not self._market and not self._stop:
                        self._idle += 1
                        if self._idle == self._n:
                            # Market empty and every peer waiting: the
                            # search is exhausted (dfs.rs's all-idle
                            # termination).
                            self._exhausted = True
                            self._stop = True
                            self._done_event.set()
                            self._cv.notify_all()
                            self._idle -= 1
                            return
                        self._cv.wait()
                        self._idle -= 1
                    if self._stop:
                        return
                    stack = self._market.pop()

                pops = 0
                while stack:
                    if self._stop:
                        return
                    # Re-stock an under-supplied market from the local
                    # stack (share the OLDEST entries — the widest
                    # subtrees — like the reference's bottom-of-stack
                    # splits). Probed every few pops so the hot loop pays
                    # one condition-variable acquire per batch, not per
                    # state.
                    pops += 1
                    # Unlocked fullness pre-check (benign stale read under
                    # CPython, ADVICE r4): a full market skips the cv
                    # acquire entirely; the locked re-check stays
                    # authoritative.
                    if (
                        len(stack) > 1
                        and pops % 8 == 1
                        and len(self._market) < market_low
                    ):
                        with self._cv:
                            if len(self._market) < market_low:
                                half = stack[: len(stack) // 2]
                                del stack[: len(stack) // 2]
                                self._market.append(half)
                                self._cv.notify()
                    state, fp, ebits, depth = stack.pop()
                    if (
                        self._target_max_depth is not None
                        and depth >= self._target_max_depth
                    ):
                        with self._lock:
                            if depth > self._max_depth:
                                self._max_depth = depth
                        continue
                    local_disc: Dict[int, int] = {}
                    ebits = _eval_properties(
                        model, properties, state, fp, ebits, local_disc
                    )
                    with self._cv:
                        if depth > self._max_depth:
                            self._max_depth = depth
                        for i, wfp in local_disc.items():
                            self._discoveries.setdefault(i, wfp)
                        if len(self._discoveries) == len(properties):
                            # Discoveries exist for every property (trivially
                            # so with zero properties): stop BEFORE expanding,
                            # as the oracle does (search.py, bfs.rs:326-328).
                            self._stop = True
                            self._done_event.set()
                            self._cv.notify_all()
                            return
                    # Expansion (dfs.rs:330-381 analogue) — model callbacks
                    # and fingerprinting run outside any lock.
                    actions: List[Any] = []
                    model.actions(state, actions)
                    succs: List[tuple] = []
                    is_terminal = True
                    for action in actions:
                        nxt = model.next_state(state, action)
                        if nxt is None:
                            continue
                        if not model.within_boundary(nxt):
                            continue
                        is_terminal = False
                        nfp = fingerprint(nxt)
                        succs.append((nxt, nfp, self._rep_fp(nxt, nfp)))
                    term_disc: Dict[int, int] = {}
                    if is_terminal:
                        # Unmet eventually-bits at a terminal state are
                        # counterexamples (dfs.rs:374-381 analogue).
                        for i in ebits:
                            term_disc.setdefault(i, fp)
                    # One consolidated shared-state section per expanded
                    # state: counters, visited-insert, parents, terminal
                    # discoveries, then the stop conditions — in the
                    # oracle's order (target is checked AFTER the full
                    # expansion, with every discovery already flushed).
                    fresh_entries: List[tuple] = []
                    with self._cv:
                        self._state_count += len(succs)
                        for nxt, nfp, rfp in succs:
                            if rfp not in self._visited:
                                self._visited.add(rfp)
                                self._unique_count += 1
                                if nfp not in self._parents:
                                    self._parents[nfp] = fp
                                fresh_entries.append(
                                    (nxt, nfp, ebits, depth + 1)
                                )
                        for i, wfp in term_disc.items():
                            self._discoveries.setdefault(i, wfp)
                        all_found = properties and len(self._discoveries) == len(
                            properties
                        )
                        hit_target = (
                            self._target_state_count is not None
                            and self._state_count >= self._target_state_count
                        )
                        if hit_target:
                            self._target_reached = True
                        if hit_target or all_found:
                            self._stop = True
                            self._done_event.set()
                            self._cv.notify_all()
                            return
                    stack.extend(fresh_entries)
        except Exception:
            # A model-callback failure must not hang join(): surface it.
            import traceback

            with self._cv:
                self._failure = traceback.format_exc()
                self._stop = True
                self._done_event.set()
                self._cv.notify_all()

    _failure: Optional[str] = None

    # --- engine hooks ------------------------------------------------------

    def _start(self) -> None:
        if self._started_threads:
            return
        self._started_threads = True
        for k in range(self._n):
            t = threading.Thread(
                target=self._worker, name=f"dfs-worker-{k}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def _run_block(self, max_count: int = 1500) -> None:
        """Waits for ~max_count new unique states (or completion) so
        ``report()`` gets progress snapshots at the usual granularity."""
        if self.is_done():
            return
        self._start()
        with self._lock:
            baseline = self._unique_count
        while not self._done_event.is_set():
            with self._lock:
                if self._unique_count >= baseline + max_count:
                    return
            self._done_event.wait(0.05)
        if self._failure is not None:
            raise RuntimeError(
                f"parallel DFS worker failed:\n{self._failure}"
            )

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._done_event.set()
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass

    # --- Checker API -------------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        with self._lock:
            return self._state_count

    def unique_state_count(self) -> int:
        with self._lock:
            return self._unique_count

    def max_depth(self) -> int:
        with self._lock:
            return self._max_depth

    def is_done(self) -> bool:
        if not self._started_threads:
            return False
        if self._done_event.is_set():
            if self._failure is not None:
                raise RuntimeError(
                    f"parallel DFS worker failed:\n{self._failure}"
                )
            return True
        return False

    def discoveries(self) -> Dict[str, Path]:
        with self._lock:
            found = dict(self._discoveries)
            parents = dict(self._parents)
        out: Dict[str, Path] = {}
        for i, fp in found.items():
            chain = [fp]
            cur = fp
            while True:
                parent = parents.get(cur)
                if parent is None:
                    break
                chain.append(parent)
                cur = parent
            chain.reverse()
            out[self._properties[i].name] = Path.from_fingerprints(
                self._model, chain
            )
        return out
