"""Demand-driven checker: computes nothing until asked.

Mirrors ``/root/reference/src/checker/on_demand.rs``: a BFS-like engine that
starts with only the initial states pending and **blocks until asked**.
``check_fingerprint(fp)`` (ControlFlow::CheckFingerprint,
on_demand.rs:165-203, 460-465) evaluates and expands exactly the pending
frontier entry with that fingerprint; ``run_to_completion()``
(ControlFlow::RunToCompletion) unblocks the engine fully, after which it
behaves like the batch BFS checker. The Explorer is built on this so the UI
only computes the states the user clicks.

Design delta from the reference: the reference fans control messages over an
mpsc channel to waiting worker threads and reuses ``check_block`` — one
click expands up to 1500 states of the clicked subtree
(on_demand.rs:209-218). This engine is in-process; ``block_size`` picks the
granularity: at the reference's 1500 a click pre-computes the clicked
subtree block exactly as upstream does, while the Explorer spawns with
``block_size=1`` (expand exactly the clicked entry — the demand-driven
contract its UI counts rely on). A ``join()`` before
``run_to_completion()`` would deadlock in the reference (workers wait on
the channel forever); here it raises instead of hanging.
"""

from __future__ import annotations

from collections import deque

from .search import SearchChecker


class OnDemandChecker(SearchChecker):
    """Spawned via ``CheckerBuilder.spawn_on_demand()`` (checker.rs:163-171)."""

    def __init__(self, builder, block_size: int = 1):
        super().__init__(builder, lifo=False)
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self._waiting = True
        self._block_size = block_size

    # --- control flow (checker.rs:259-266) --------------------------------

    def check_fingerprint(self, fingerprint: int) -> None:
        """Evaluates and expands the pending frontier entry with this
        fingerprint, if any (on_demand.rs:460-465), then — with
        ``block_size > 1`` — BFS-expands the clicked subtree up to
        ``block_size`` states total, the reference's ``check_block`` reuse
        (on_demand.rs:209-218). Unknown or already processed fingerprints
        are ignored, as in the reference."""
        if not self._waiting:
            return
        for i, entry in enumerate(self._pending):
            if entry[1] == fingerprint:
                del self._pending[i]
                # Entries the expansion prepends (BFS mode appendlefts
                # successors; each is unique — search.py dedups via
                # _generated before enqueueing) are the clicked subtree's
                # next frontier. Pop them straight off the deque into a
                # local BFS queue (reversed: appendleft stores siblings
                # newest-leftmost) and expand within the block budget;
                # a False from _evaluate_and_expand stops the block
                # immediately, like _run_block and the reference's
                # check_block.
                subtree = deque()

                def pull_new(before_len):
                    added = len(self._pending) - before_len
                    subtree.extend(
                        reversed([self._pending.popleft() for _ in range(added)])
                    )

                before = len(self._pending)
                keep_going = self._evaluate_and_expand(*entry)
                pull_new(before)
                expanded = 1
                while subtree and keep_going and expanded < self._block_size:
                    e = subtree.popleft()
                    before = len(self._pending)
                    keep_going = self._evaluate_and_expand(*e)
                    pull_new(before)
                    expanded += 1
                # Unexpanded subtree entries rejoin the frontier in their
                # original newest-leftmost layout.
                self._pending.extendleft(subtree)
                return

    def run_to_completion(self) -> None:
        """Unblocks the engine; subsequent ``join()``/``report()`` drive it
        to completion like a batch BFS (on_demand.rs:193-198)."""
        self._waiting = False

    # --- Checker API adjustments ------------------------------------------

    def _run_block(self, max_count: int = 1500) -> None:
        if self._waiting:
            return  # computes nothing until asked (on_demand.rs:165-203)
        super()._run_block(max_count)

    def is_done(self) -> bool:
        if self._waiting:
            # While demand-driven, the search is done when every property
            # has a discovery, the driven frontier ran dry, or a target was
            # hit — never merely because the un-driven frontier is non-empty.
            return (
                not self._pending
                or self._target_reached
                or (
                    bool(self._properties)
                    and len(self._discoveries) == len(self._properties)
                )
            )
        return super().is_done()

    def join(self) -> "OnDemandChecker":
        if self._waiting and not self.is_done():
            # The reference would block forever here (workers wait on the
            # control channel); fail loudly instead.
            raise RuntimeError(
                "join() on an on-demand checker that was never unblocked; "
                "call run_to_completion() first"
            )
        return super().join()
