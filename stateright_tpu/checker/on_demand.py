"""Demand-driven checker: computes nothing until asked.

Mirrors ``/root/reference/src/checker/on_demand.rs``: a BFS-like engine that
starts with only the initial states pending and **blocks until asked**.
``check_fingerprint(fp)`` (ControlFlow::CheckFingerprint,
on_demand.rs:165-203, 460-465) evaluates and expands exactly the pending
frontier entry with that fingerprint; ``run_to_completion()``
(ControlFlow::RunToCompletion) unblocks the engine fully, after which it
behaves like the batch BFS checker. The Explorer is built on this so the UI
only computes the states the user clicks.

Design delta from the reference: the reference fans control messages over an
mpsc channel to waiting worker threads and reuses ``check_block`` (so one
click may expand up to 1500 states of the clicked subtree); this engine is
in-process and expands exactly the requested entry per request — the
demand-driven contract the Explorer actually relies on. A ``join()`` before
``run_to_completion()`` would deadlock in the reference (workers wait on the
channel forever); here it raises instead of hanging.
"""

from __future__ import annotations

from .search import SearchChecker


class OnDemandChecker(SearchChecker):
    """Spawned via ``CheckerBuilder.spawn_on_demand()`` (checker.rs:163-171)."""

    def __init__(self, builder):
        super().__init__(builder, lifo=False)
        self._waiting = True

    # --- control flow (checker.rs:259-266) --------------------------------

    def check_fingerprint(self, fingerprint: int) -> None:
        """Evaluates and expands the pending frontier entry with this
        fingerprint, if any (on_demand.rs:460-465). Unknown or already
        processed fingerprints are ignored, as in the reference."""
        if not self._waiting:
            return
        for i, entry in enumerate(self._pending):
            if entry[1] == fingerprint:
                del self._pending[i]
                self._evaluate_and_expand(*entry)
                return

    def run_to_completion(self) -> None:
        """Unblocks the engine; subsequent ``join()``/``report()`` drive it
        to completion like a batch BFS (on_demand.rs:193-198)."""
        self._waiting = False

    # --- Checker API adjustments ------------------------------------------

    def _run_block(self, max_count: int = 1500) -> None:
        if self._waiting:
            return  # computes nothing until asked (on_demand.rs:165-203)
        super()._run_block(max_count)

    def is_done(self) -> bool:
        if self._waiting:
            # While demand-driven, the search is done when every property
            # has a discovery, the driven frontier ran dry, or a target was
            # hit — never merely because the un-driven frontier is non-empty.
            return (
                not self._pending
                or self._target_reached
                or (
                    bool(self._properties)
                    and len(self._discoveries) == len(self._properties)
                )
            )
        return super().is_done()

    def join(self) -> "OnDemandChecker":
        if self._waiting and not self.is_done():
            # The reference would block forever here (workers wait on the
            # control channel); fail loudly instead.
            raise RuntimeError(
                "join() on an on-demand checker that was never unblocked; "
                "call run_to_completion() first"
            )
        return super().join()
