"""Host-side BFS/DFS search engines — the CPU correctness oracle.

These faithfully implement the semantics of the reference's worker loops
(``/root/reference/src/checker/bfs.rs:225-383`` and ``dfs.rs:230-407``):
exact state/unique counts, visit order, eventually-bit propagation with the
documented cycle/DAG-join false negatives, boundary filtering, early exit
once every property has a discovery, and target state/depth bounds.

The reference splits BFS and DFS into two files differing only in frontier
discipline and witness bookkeeping; here one engine is parameterized by both.
The reference's job-market/work-stealing machinery (bfs.rs:89-211) is a CPU
threading artifact and is intentionally absent: the parallel engine in this
framework is the XLA frontier expansion (``stateright_tpu/xla.py``), for
which this module is the differential-testing oracle.

Unlike the reference (where only DFS honors symmetry reduction, dfs.rs:357),
both disciplines support it here; BFS keeps witness paths valid by keying
dedup on representative fingerprints while chaining parent pointers through
the pre-canonicalized fingerprints (same trick as dfs.rs:363-366).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import Expectation, Model
from ..fingerprint import fingerprint
from .base import Checker
from .path import Path
from .visitor import CheckerVisitor


class SearchChecker(Checker):
    """Sequential explicit-state search over a model's state graph."""

    def __init__(self, builder, *, lifo: bool):
        self._model: Model = builder._model
        self._lifo = lifo
        self._symmetry: Optional[Callable[[Any], Any]] = builder._symmetry
        self._target_state_count: Optional[int] = builder._target_state_count
        self._target_max_depth: Optional[int] = builder._target_max_depth
        self._visitor: Optional[CheckerVisitor] = builder._visitor
        self._properties = self._model.properties()

        init_states = [
            s for s in self._model.init_states() if self._model.within_boundary(s)
        ]
        self._state_count = len(init_states)
        self._max_depth = 0
        # Dedup keys: representative fingerprints when symmetry is enabled
        # (dfs.rs:357-362), plain state fingerprints otherwise.
        self._generated: set = set()
        # BFS-style predecessor map over *actual* fingerprints, for witness
        # reconstruction (bfs.rs:29-30, 430-459). Populated in both
        # disciplines so discoveries() is uniform.
        self._parents: Dict[int, Optional[int]] = {}
        self._ebits0 = frozenset(
            i
            for i, p in enumerate(self._properties)
            if p.expectation == Expectation.EVENTUALLY
        )
        # Pending entries: (state, fingerprint, ebits, depth). Depth counts
        # states on the path, starting at 1 for init states (bfs.rs:79-85).
        self._pending = deque()
        for s in init_states:
            fp = fingerprint(s)
            rep_fp = self._rep_fp(s, fp)
            self._generated.add(rep_fp)
            if fp not in self._parents:
                self._parents[fp] = None
            self._pending.append((s, fp, self._ebits0, 1))
        # Discoveries: property name -> witness fingerprint (path built from
        # the parent chain on demand, as in bfs.rs:407-417).
        self._discoveries: Dict[str, int] = {}
        self._exhausted = False
        self._target_reached = False

    # --- engine ----------------------------------------------------------

    def _rep_fp(self, state: Any, fp: int) -> int:
        if self._symmetry is None:
            return fp
        return fingerprint(self._symmetry(state))

    def _run_block(self, max_count: int = 1500) -> None:
        """Process up to ``max_count`` pending states (bfs.rs:225-383)."""
        while max_count > 0:
            max_count -= 1
            if not self._pending:
                self._exhausted = True
                return
            # Both disciplines pop from the right (bfs.rs:252 pop_back,
            # dfs.rs:254 pop); BFS enqueues children on the left
            # (bfs.rs:367 push_front) and DFS on the right (dfs.rs:391 push),
            # reproducing the reference's exact visit order.
            if not self._evaluate_and_expand(*self._pending.pop()):
                return

    def _evaluate_and_expand(self, state, state_fp, ebits, depth) -> bool:
        """Evaluate properties on one dequeued state and push its successors.

        The body of the reference's hot loop (bfs.rs:252-381), shared by the
        batch engines and the demand-driven checker. Returns False when the
        block should stop (all properties discovered, or target state count
        reached)."""
        model = self._model
        properties = self._properties

        if depth > self._max_depth:
            self._max_depth = depth
        if self._target_max_depth is not None and depth >= self._target_max_depth:
            return True

        if self._visitor is not None:
            self._visitor.visit(model, self._reconstruct_path(state_fp))

        # Property evaluation on the dequeued state (bfs.rs:279-328).
        is_awaiting_discoveries = False
        for i, prop in enumerate(properties):
            if prop.name in self._discoveries:
                continue
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, state):
                    self._discoveries[prop.name] = state_fp
                else:
                    is_awaiting_discoveries = True
            elif prop.expectation == Expectation.SOMETIMES:
                if prop.condition(model, state):
                    self._discoveries[prop.name] = state_fp
                else:
                    is_awaiting_discoveries = True
            else:
                # Eventually-property discoveries only materialize at
                # terminal states, so this property is still awaiting one
                # regardless of whether it holds here (bfs.rs:309-323).
                is_awaiting_discoveries = True
                if prop.condition(model, state):
                    ebits = ebits - {i}
        if not is_awaiting_discoveries:
            # Discoveries exist for every property. Like the reference
            # (bfs.rs:326-328), this is detected after visiting the
            # dequeued state, so one state is evaluated even when there
            # are zero properties.
            return False

        # Expansion (bfs.rs:330-381).
        is_terminal = True
        actions: List[Any] = []
        model.actions(state, actions)
        for action in actions:
            next_state = model.next_state(state, action)
            if next_state is None:
                continue
            if not model.within_boundary(next_state):
                continue
            self._state_count += 1
            next_fp = fingerprint(next_state)
            rep_fp = self._rep_fp(next_state, next_fp)
            if rep_fp in self._generated:
                # Could be a cycle (terminal for eventually-checking
                # purposes) or a DAG join (not terminal); like the
                # reference we do not disambiguate, accepting the
                # documented false negative (bfs.rs:353-360).
                is_terminal = False
                continue
            self._generated.add(rep_fp)
            if next_fp not in self._parents:
                self._parents[next_fp] = state_fp
            is_terminal = False
            entry = (next_state, next_fp, ebits, depth + 1)
            if self._lifo:
                self._pending.append(entry)
            else:
                self._pending.appendleft(entry)
        if is_terminal:
            for i in ebits:
                self._discoveries[properties[i].name] = state_fp
        if (
            self._target_state_count is not None
            and self._state_count >= self._target_state_count
        ):
            self._target_reached = True
            return False
        return True

    # --- Checker API ------------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return len(self._generated)

    def max_depth(self) -> int:
        return self._max_depth

    def is_done(self) -> bool:
        return (
            self._exhausted
            or self._target_reached
            or len(self._discoveries) == len(self._properties)
            or not self._pending
        )

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: self._reconstruct_path(fp) for name, fp in self._discoveries.items()
        }

    def _reconstruct_path(self, fp: int) -> Path:
        """Walk the predecessor chain back to an init fingerprint, then
        re-execute the model forward (bfs.rs:430-459, path.rs:20-97)."""
        fingerprints: List[int] = []
        next_fp: Optional[int] = fp
        while next_fp is not None and next_fp in self._parents:
            fingerprints.append(next_fp)
            next_fp = self._parents[next_fp]
        fingerprints.reverse()
        return Path.from_fingerprints(self._model, fingerprints)


class BfsChecker(SearchChecker):
    """Breadth-first search: finds shortest witnesses (checker.rs:146-155)."""

    def __init__(self, builder):
        super().__init__(builder, lifo=False)


class DfsChecker(SearchChecker):
    """Depth-first search: frontier stays small (checker.rs:179-187)."""

    def __init__(self, builder):
        super().__init__(builder, lifo=True)
