"""The ``Checker`` interface: counters, discoveries, assertions, reporting.

Mirrors the reference's ``Checker`` trait (``/root/reference/src/checker.rs:
254-538``).  Checkers here run lazily in-process: ``spawn_*`` builds the
checker with initial counters, ``join()`` (or ``report()``) drives it to
completion.  This makes progress snapshots deterministic — the reference got
the same effect racily from background threads.  The TPU engine drives a
device super-step per ``_run_block`` call.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional

from ..core import Expectation, Model
from ..report import ReportData, ReportDiscovery, Reporter
from .path import Path


class Checker:
    """Uniform checker API (checker.rs:254-538)."""

    # --- engine hooks -----------------------------------------------------

    def model(self) -> Model:
        raise NotImplementedError

    def state_count(self) -> int:
        """Total states generated including repeats (checker.rs:270)."""
        raise NotImplementedError

    def unique_state_count(self) -> int:
        """Unique states generated (checker.rs:274)."""
        raise NotImplementedError

    def max_depth(self) -> int:
        """Maximum depth explored so far (checker.rs:277)."""
        raise NotImplementedError

    def discoveries(self) -> Dict[str, Path]:
        """Map from property name to discovery path (checker.rs:281)."""
        raise NotImplementedError

    def is_done(self) -> bool:
        """All properties discovered or all reachable states visited."""
        raise NotImplementedError

    def _run_block(self, max_count: int = 1500) -> None:
        """Advance the search by a bounded amount of work (engine hook)."""
        raise NotImplementedError

    def metrics(self) -> Dict[str, Any]:
        """A unified telemetry snapshot (stateright_tpu/obs;
        docs/observability.md). The base form carries the counters every
        engine has; the device engines override with the full registry
        (dispatch/growth/flush counters, occupancy and capacity gauges).
        Safe to poll mid-run — the Explorer's ``/.status`` does."""
        out = {
            "engine": type(self).__name__,
            "state_count": self.state_count(),
            "unique_state_count": self.unique_state_count(),
            "max_depth": self.max_depth(),
        }
        if self._service_job_id is not None:
            out["job_id"] = self._service_job_id
        return out

    # --- service hooks (stateright_tpu/service) ---------------------------

    #: Set when this checker serves a ``CheckerService`` job (the Explorer
    #: registers its interactive checker); threads the job identity through
    #: ``metrics()`` so pool-wide and per-checker telemetry join up.
    _service_job_id: Optional[str] = None

    def attach_job(self, job_id: str) -> None:
        self._service_job_id = job_id

    _started = False

    def _ensure_started(self) -> None:
        """Runs at least one block per checker lifetime, matching the
        reference whose worker threads always enter check_block once even if
        every property already has a discovery (bfs.rs:149-159) — this is
        what makes visitors fire for zero-property models."""
        if not self._started:
            self._started = True
            self._run_block()

    def join(self) -> "Checker":
        """Drives checking to completion (checker.rs:287-295)."""
        self._ensure_started()
        while not self.is_done():
            self._run_block()
        return self

    # --- on-demand hooks (no-ops for batch checkers, checker.rs:259-266) --

    def check_fingerprint(self, fingerprint: int) -> None:
        pass

    def run_to_completion(self) -> None:
        pass

    # --- derived API ------------------------------------------------------

    def discovery(self, name: str) -> Optional[Path]:
        return self.discoveries().get(name)

    def discovery_classification(self, name: str) -> str:
        """"example" or "counterexample" (checker.rs:414-424)."""
        prop = self.model().property(name)
        if prop.expectation == Expectation.SOMETIMES:
            return "example"
        return "counterexample"

    def report(self, reporter: Reporter) -> "Checker":
        """Runs to completion, emitting periodic progress (checker.rs:371-412).

        The first progress snapshot is emitted before any work, so output for
        small models is deterministic: ``Checking. states=…`` with initial
        counters, then ``Done. …``, then discoveries sorted by name.
        """
        start = time.monotonic()
        if not self.is_done():
            reporter.report_checking(self._report_data(start, done=False))
        last = time.monotonic()
        self._ensure_started()
        while not self.is_done():
            self._run_block()
            now = time.monotonic()
            if now - last >= reporter.delay() and not self.is_done():
                reporter.report_checking(self._report_data(start, done=False))
                last = now
        reporter.report_checking(self._report_data(start, done=True))
        discoveries = {
            name: ReportDiscovery(path, self.discovery_classification(name))
            for name, path in self.discoveries().items()
        }
        reporter.report_discoveries(discoveries)
        return self

    def join_and_report(self, reporter: Reporter) -> "Checker":
        return self.report(reporter)

    def _report_data(self, start: float, done: bool) -> ReportData:
        return ReportData(
            total_states=self.state_count(),
            unique_states=self.unique_state_count(),
            max_depth=self.max_depth(),
            duration=time.monotonic() - start,
            done=done,
        )

    # --- assertion helpers (checker.rs:426-537) ---------------------------

    def assert_properties(self) -> None:
        for p in self.model().properties():
            if p.expectation == Expectation.SOMETIMES:
                self.assert_any_discovery(p.name)
            else:
                self.assert_no_discovery(p.name)

    def assert_any_discovery(self, name: str) -> Path:
        found = self.discovery(name)
        if found is not None:
            return found
        if not self.is_done():
            raise AssertionError(
                f'Discovery for "{name}" not found, but model checking is incomplete.'
            )
        raise AssertionError(f'Discovery for "{name}" not found.')

    def assert_no_discovery(self, name: str) -> None:
        found = self.discovery(name)
        if found is not None:
            raise AssertionError(
                f'Unexpected "{name}" {self.discovery_classification(name)} '
                f"{found}Last state: {found.last_state()!r}\n"
            )
        if not self.is_done():
            raise AssertionError(
                f'Discovery for "{name}" not found, but model checking is incomplete.'
            )

    def assert_discovery(self, name: str, actions: List[Any]) -> None:
        """Asserts ``actions`` produce a valid discovery for ``name``
        (checker.rs:481-537)."""
        additional_info: List[str] = []
        found = self.assert_any_discovery(name)
        model = self.model()
        for init_state in model.init_states():
            path = Path.from_actions(model, init_state, actions)
            if path is None:
                continue
            prop = model.property(name)
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, path.last_state()):
                    return
            elif prop.expectation == Expectation.EVENTUALLY:
                states = path.into_states()
                is_liveness_satisfied = any(prop.condition(model, s) for s in states)
                terminal_actions: List[Any] = []
                model.actions(states[-1], terminal_actions)
                is_path_terminal = not terminal_actions
                if not is_liveness_satisfied and is_path_terminal:
                    return
                if is_liveness_satisfied:
                    additional_info.append(
                        "incorrect counterexample satisfies eventually property"
                    )
                if not is_path_terminal:
                    additional_info.append("incorrect counterexample is nonterminal")
            else:  # SOMETIMES
                if prop.condition(model, path.last_state()):
                    return
        info = f" ({'; '.join(additional_info)})" if additional_info else ""
        raise AssertionError(
            f'Invalid discovery for "{name}"{info}, but a valid one was found. '
            f"found={found.into_actions()!r}"
        )
