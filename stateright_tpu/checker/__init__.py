from .base import Checker
from .builder import CheckerBuilder
from .path import NondeterministicModelError, Path
from .visitor import CheckerVisitor, PathRecorder, StateRecorder

__all__ = [
    "Checker",
    "CheckerBuilder",
    "CheckerVisitor",
    "NondeterministicModelError",
    "Path",
    "PathRecorder",
    "StateRecorder",
]
