"""Checker visitors: hooks applied to every evaluated state's path.

Mirrors ``/root/reference/src/checker/visitor.rs``.  A visitor may be any
callable taking a :class:`Path`, or one of the recorder classes below.
"""

from __future__ import annotations

from typing import Any, Callable, List, Set

from .path import Path


class CheckerVisitor:
    """Hook applied to every evaluated path (visitor.rs:19-22)."""

    def visit(self, model, path: Path) -> None:
        raise NotImplementedError


class _FnVisitor(CheckerVisitor):
    def __init__(self, fn: Callable[[Path], None]):
        self._fn = fn

    def visit(self, model, path: Path) -> None:
        self._fn(path)


def as_visitor(v) -> CheckerVisitor:
    if isinstance(v, CheckerVisitor):
        return v
    if callable(v):
        return _FnVisitor(v)
    raise TypeError(f"not a visitor: {v!r}")


class PathRecorder(CheckerVisitor):
    """Records the set of paths visited (visitor.rs:47-73).

    Path reconstruction itself validates each path by re-executing the model,
    so recording doubles as a path-validity check (used by the reference's
    symmetry-reduction regression test, dfs.rs:618-622).
    """

    def __init__(self):
        self._paths: Set[Path] = set()

    def visit(self, model, path: Path) -> None:
        self._paths.add(path)

    @staticmethod
    def new_with_accessor():
        recorder = PathRecorder()
        return recorder, lambda: set(recorder._paths)


class StateRecorder(CheckerVisitor):
    """Records states evaluated, in evaluation order (visitor.rs:87-111)."""

    def __init__(self):
        self._states: List[Any] = []

    def visit(self, model, path: Path) -> None:
        self._states.append(path.last_state())

    @staticmethod
    def new_with_accessor():
        recorder = StateRecorder()
        return recorder, lambda: list(recorder._states)
