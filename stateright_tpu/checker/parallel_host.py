"""Parallel host BFS: ``threads(n)`` with real workers.

The reference runs N OS threads over a shared DashMap visited set with a
job-market work-sharing protocol (``/root/reference/src/checker/bfs.rs:89-211``).
Python threads cannot parallelize model callbacks (the interpreter lock), so
this engine uses N *forked worker processes* — and rather than translating
the job market, it reuses this framework's own scale-out design
(``stateright_tpu/parallel/sharded.py``) on the host:

- **fingerprint-sharded ownership**: worker ``k`` owns every state whose
  representative fingerprint hashes to ``k``; it keeps that shard of the
  visited set, the parent map (bfs.rs:29-30), and the frontier;
- **level-synchronous supersteps**: each round, every worker expands its
  local frontier (the Python-heavy ``actions``/``next_state``/``fingerprint``
  callbacks — the hot loop of bfs.rs:332-349), buckets candidates by owner,
  and exchanges buckets over per-worker pipes (the host analogue of the
  device engine's ``all_to_all``; a drain thread receives while the worker
  sends, so full pipe buffers cannot deadlock the exchange);
- **deterministic merges**: owners ingest buckets in sender order, so
  counts, witness election, and the documented eventually-false-negatives
  (bfs.rs:343-360) are reproducible run to run — unlike the reference,
  whose discovery races are documented as benign (bfs.rs:291-306).

Forked workers inherit the model by copy-on-write, so models may hold
lambdas (property conditions) that could never cross a pickle boundary;
only candidate states are pickled, for the exchange.

The sequential engine (``search.py``) remains the semantics oracle; this
engine matches its full-coverage counts exactly. Early-exit points may
differ by up to one level (any parallel checker stops "soon after" a
discovery; the reference's is nondeterministic too). Visitors force the
sequential engine — they observe per-state paths one at a time.
"""

from __future__ import annotations

import multiprocessing as mp
from typing import Any, Dict, List, Optional

from ..core import Expectation, Model
from ..fingerprint import fingerprint
from .base import Checker
from .path import Path

# Owner mix decorrelated from raw fingerprint bits (fingerprints feed
# Python sets downstream); any fixed odd 64-bit multiplier works.
_OWNER_MULT = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


def _owner_of(fp: int, n: int) -> int:
    return (((fp * _OWNER_MULT) & _MASK64) >> 32) % n


def _eval_properties(model, properties, state, fp, ebits, discoveries):
    """Property evaluation at dequeue time (bfs.rs:279-328): returns the
    state's updated eventually-bits, recording ALWAYS/SOMETIMES discoveries
    into ``discoveries`` in place.

    EVENTUALLY conditions must clear ebits even after that property has a
    recorded discovery this level — skipping the clear would hand children
    a stale eventually-bit and invent terminal counterexamples at deeper
    levels.
    """
    for i, prop in enumerate(properties):
        if prop.expectation == Expectation.EVENTUALLY:
            if prop.condition(model, state):
                ebits = ebits - {i}
        elif i in discoveries:
            continue
        elif prop.expectation == Expectation.ALWAYS:
            if not prop.condition(model, state):
                discoveries[i] = fp
        elif prop.condition(model, state):
            discoveries[i] = fp
    return ebits


def _worker_main(rank, n, model, properties, symmetry, target_max_depth,
                 inbox, outboxes, to_main, from_main):
    """Worker loop: owns one shard of visited set / parent map / frontier.

    Protocol (driven by the main process):
      ("seed", bucket)  -> ingest the initial frontier shard; reply count.
      ("expand",)       -> one level: expand, exchange, ingest; reply stats.
      ("parent", fp)    -> reply (present?, parent fp or None).
      ("stop",)         -> exit.
    """
    visited: set = set()
    parents: Dict[int, Optional[int]] = {}
    frontier: List[tuple] = []  # (state, fp, ebits)
    depth = 1

    def rep_fp(state, fp):
        return fp if symmetry is None else fingerprint(symmetry(state))

    def ingest(bucket):
        fresh = 0
        for state, fp, rfp, parent_fp, ebits in bucket:
            if rfp in visited:
                continue
            visited.add(rfp)
            if fp not in parents:
                parents[fp] = parent_fp
            frontier.append((state, fp, ebits))
            fresh += 1
        return fresh

    while True:
        msg = from_main.recv()
        cmd = msg[0]
        if cmd == "stop":
            return
        if cmd == "parent":
            fp = msg[1]
            to_main.send(("parent", fp in parents, parents.get(fp)))
            continue
        if cmd == "seed":
            count = ingest(msg[1])
            to_main.send(("seeded", count))
            continue
        assert cmd == "expand"
        # A model-callback failure must not wedge the level barrier: the
        # failing worker still participates in the exchange (with empty
        # buckets) so its peers' gets complete, and reports the error only
        # after the barrier.
        failure = None
        try:
            generated = 0
            discoveries: Dict[int, int] = {}  # prop index -> witness fp
            buckets: List[List[tuple]] = [[] for _ in range(n)]
            at_depth_target = (
                target_max_depth is not None and depth >= target_max_depth
            )
            for state, fp, ebits in frontier:
                # Depth-target states are counted in max_depth but neither
                # evaluated nor expanded (bfs.rs:267-272 — the early return
                # precedes the property pass).
                if at_depth_target:
                    continue
                ebits = _eval_properties(
                    model, properties, state, fp, ebits, discoveries
                )
                # Expansion (bfs.rs:330-381).
                is_terminal = True
                actions: List[Any] = []
                model.actions(state, actions)
                for action in actions:
                    nxt = model.next_state(state, action)
                    if nxt is None:
                        continue
                    if not model.within_boundary(nxt):
                        continue
                    generated += 1
                    is_terminal = False
                    nfp = fingerprint(nxt)
                    rfp = rep_fp(nxt, nfp)
                    buckets[_owner_of(rfp, n)].append((nxt, nfp, rfp, fp, ebits))
                if is_terminal:
                    # Unmet eventually-bits at a terminal state are
                    # counterexamples (bfs.rs:374-381).
                    for i in ebits:
                        if i not in discoveries:
                            discoveries[i] = fp
        except Exception:
            import traceback

            failure = traceback.format_exc()
            buckets = [[] for _ in range(n)]
        frontier = []
        # ---- exchange. Inboxes are mp.Queues: puts are serialized
        # across producer processes (raw pipe writes from multiple
        # senders could interleave) and buffered by the feeder thread
        # (so N mutually-full pipes cannot deadlock the level). ------
        for k in range(n):
            outboxes[k].put((rank, buckets[k]))
        received = [inbox.get() for _ in range(n)]
        if failure is None:
            try:
                fresh = 0
                for _, bucket in sorted(received):  # deterministic merge
                    fresh += ingest(bucket)
                depth += 1
                to_main.send(
                    ("level", generated, fresh, len(frontier), discoveries)
                )
            except Exception:
                import traceback

                failure = traceback.format_exc()
        if failure is not None:
            to_main.send(("error", failure))
            return


class ParallelBfsChecker(Checker):
    """Level-synchronous multiprocess BFS behind ``threads(n)``."""

    def __init__(self, builder):
        if builder._visitor is not None:
            raise ValueError(
                "threads(n)>1 with a visitor is unsupported: visitors observe "
                "per-state paths sequentially. Drop the visitor or threads()."
            )
        self._model: Model = builder._model
        self._n = max(2, builder._thread_count or 0)
        self._symmetry = builder._symmetry
        self._target_state_count = builder._target_state_count
        self._target_max_depth = builder._target_max_depth
        self._properties = self._model.properties()
        self._prop_names = [p.name for p in self._properties]

        self._state_count = 0
        self._unique_count = 0
        self._max_depth = 0
        self._depth = 1
        self._discoveries: Dict[str, int] = {}
        self._paths: Dict[str, Path] = {}
        self._exhausted = False
        self._target_reached = False
        self._pool_started = False
        self._closed = False

    # --- worker pool -------------------------------------------------------

    def _start(self) -> None:
        self._pool_started = True
        ctx = mp.get_context("fork")
        n = self._n
        inboxes = [ctx.Queue() for _ in range(n)]
        to_main_pipes = [ctx.Pipe(duplex=False) for _ in range(n)]
        from_main_pipes = [ctx.Pipe(duplex=False) for _ in range(n)]
        self._to_main = [r for r, _ in to_main_pipes]
        self._from_main = [w for _, w in from_main_pipes]
        self._workers = []
        import warnings

        for k in range(n):
            p = ctx.Process(
                target=_worker_main,
                args=(
                    k,
                    n,
                    self._model,
                    self._properties,
                    self._symmetry,
                    self._target_max_depth,
                    inboxes[k],
                    inboxes,
                    to_main_pipes[k][1],
                    from_main_pipes[k][0],
                ),
                daemon=True,
            )
            with warnings.catch_warnings():
                # JAX registers an at-fork hook that warns (RuntimeWarning)
                # because its runtime threads live in the parent. The fork
                # is deliberate — it is what lets lambda-bearing models
                # cross into workers without pickling — and the children
                # never touch JAX, so the feared deadlock cannot involve
                # them.
                warnings.filterwarnings(
                    "ignore", message=".*fork.*", category=RuntimeWarning
                )
                warnings.filterwarnings(
                    "ignore", message=".*fork.*", category=DeprecationWarning
                )
                p.start()
            self._workers.append(p)

        # Seed the initial frontier shards (bfs.rs:52-78).
        ebits0 = frozenset(
            i
            for i, p in enumerate(self._properties)
            if p.expectation == Expectation.EVENTUALLY
        )
        init_states = [
            s for s in self._model.init_states() if self._model.within_boundary(s)
        ]
        buckets: List[List[tuple]] = [[] for _ in range(n)]
        for s in init_states:
            fp = fingerprint(s)
            rfp = fp if self._symmetry is None else fingerprint(self._symmetry(s))
            buckets[_owner_of(rfp, n)].append((s, fp, rfp, None, ebits0))
        for k in range(n):
            self._from_main[k].send(("seed", buckets[k]))
        seeded = 0
        for k in range(n):
            tag, count = self._to_main[k].recv()
            assert tag == "seeded"
            seeded += count
        self._state_count = len(init_states)
        self._unique_count = seeded
        if seeded == 0:
            self._exhausted = True

    def close(self) -> None:
        """Stops the worker pool (idempotent). Before the pool starts there
        is nothing to stop — and the checker stays usable (a later join()
        starts and finalizes normally)."""
        if not self._pool_started or self._closed:
            return
        self._closed = True
        for pipe in self._from_main:
            try:
                pipe.send(("stop",))
            except (BrokenPipeError, OSError):  # pragma: no cover
                pass
        for p in self._workers:
            p.join(timeout=5)
            if p.is_alive():  # pragma: no cover
                p.terminate()

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass

    # --- engine ------------------------------------------------------------

    def _run_block(self, max_count: int = 1500) -> None:
        """One BFS level across all workers."""
        if not self._pool_started:
            self._start()
        if self.is_done():
            self._finalize()
            return
        self._max_depth = max(self._max_depth, self._depth)
        at_depth_target = (
            self._target_max_depth is not None
            and self._depth >= self._target_max_depth
        )
        for pipe in self._from_main:
            pipe.send(("expand",))
        generated = fresh = frontier_total = 0
        discovery_cands: Dict[int, List[int]] = {}
        failure = None
        for k in range(self._n):
            msg = self._to_main[k].recv()
            if msg[0] == "error":  # pragma: no cover
                failure = (k, msg[1])
                continue
            _, g, f, ftotal, discs = msg
            generated += g
            fresh += f
            frontier_total += ftotal
            for i, fp in discs.items():
                discovery_cands.setdefault(i, []).append(fp)
        if failure is not None:  # pragma: no cover
            self.close()
            raise RuntimeError(f"worker {failure[0]} failed:\n{failure[1]}")
        self._state_count += generated
        self._unique_count += fresh
        self._depth += 1
        for i, fps in sorted(discovery_cands.items()):
            name = self._prop_names[i]
            if name not in self._discoveries:
                # Deterministic witness election (the reference lets worker
                # threads race here, bfs.rs:291-306): lowest fingerprint.
                self._discoveries[name] = min(fps)
        if (
            self._target_state_count is not None
            and self._state_count >= self._target_state_count
        ):
            self._target_reached = True
        if frontier_total == 0 or at_depth_target:
            self._exhausted = True
        if self.is_done():
            self._finalize()

    def _finalize(self) -> None:
        """Resolve witness paths through the sharded parent maps, then shut
        the pool down; paths are cached for discoveries()."""
        if self._closed or not self._pool_started:
            return
        for name, fp in self._discoveries.items():
            if name not in self._paths:
                self._paths[name] = self._reconstruct_path(fp)
        self.close()

    def _parent_of(self, fp: int) -> Optional[int]:
        """The parent map is keyed by *actual* fingerprint but sharded by
        *representative* fingerprint, which the main process cannot derive;
        chains are short and n is small, so query shards starting with the
        no-symmetry owner."""
        if self._closed:
            raise RuntimeError(
                "worker pool already closed: close() preempted finalize, so "
                "this discovery's witness path was never cached and the "
                "parent-map shards that could rebuild it are gone. Let the "
                "check finish (join()) before closing, or re-run it."
            )
        guess = _owner_of(fp, self._n)
        order = [guess] + [j for j in range(self._n) if j != guess]
        for j in order:
            self._from_main[j].send(("parent", fp))
            tag, present, parent = self._to_main[j].recv()
            assert tag == "parent"
            if present:
                return parent
        raise KeyError(f"fingerprint {fp:#x} not in any parent shard")

    def _reconstruct_path(self, fp: int) -> Path:
        """Walk the (sharded) predecessor chain, then re-execute the model
        (bfs.rs:430-459, path.rs:20-97)."""
        fingerprints: List[int] = [fp]
        cur = fp
        while True:
            parent = self._parent_of(cur)
            if parent is None:
                break
            fingerprints.append(parent)
            cur = parent
        fingerprints.reverse()
        return Path.from_fingerprints(self._model, fingerprints)

    # --- Checker API --------------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    def is_done(self) -> bool:
        if not self._pool_started:
            return False
        return (
            self._exhausted
            or self._target_reached
            or len(self._discoveries) == len(self._properties)
        )

    def discoveries(self) -> Dict[str, Path]:
        out = dict(self._paths)
        for name, fp in self._discoveries.items():
            if name not in out:
                out[name] = self._reconstruct_path(fp)
        return out
