"""Path: a state/action trace witnessing a property discovery.

Mirrors ``/root/reference/src/checker/path.rs``.  Paths are reconstructed from
64-bit fingerprints by re-executing the model forward (the TLC technique cited
at path.rs:439-442), which keeps the search engine free of state storage —
essential for the TPU engine, whose visited set holds only fingerprints in
device HBM.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence, Tuple

from ..fingerprint import fingerprint


class NondeterministicModelError(RuntimeError):
    """Raised when path reconstruction fails: the model's ``init_states``/
    ``actions``/``next_state`` varied between calls (path.rs:36-55, 68-90)."""


class Path:
    """``state --action--> state ... --action--> state``.

    Stored as a list of ``(state, action_or_None)`` pairs where the final
    pair's action is ``None`` (path.rs:16).
    """

    def __init__(self, pairs: List[Tuple[Any, Optional[Any]]]):
        if not pairs:
            raise ValueError("empty path is invalid")
        self._pairs = pairs

    @staticmethod
    def from_fingerprints(model, fingerprints: Sequence[int]) -> "Path":
        """Reconstructs a path by re-executing ``model`` (path.rs:20-97)."""
        fps = list(fingerprints)
        if not fps:
            raise NondeterministicModelError("empty path is invalid")
        init_print = fps[0]
        last_state = None
        for s in model.init_states():
            if fingerprint(s) == init_print:
                last_state = s
                break
        if last_state is None:
            available = [fingerprint(s) for s in model.init_states()]
            raise NondeterministicModelError(
                "Unable to reconstruct a Path from fingerprints: no init state "
                f"has the expected fingerprint ({init_print}). This usually "
                "happens when Model.init_states varies between calls (e.g. the "
                "model reads untracked external state or iterates an unordered "
                f"container). Available init fingerprints: {available}"
            )
        pairs: List[Tuple[Any, Optional[Any]]] = []
        for next_fp in fps[1:]:
            found = None
            for action, state in model.next_steps(last_state):
                if fingerprint(state) == next_fp:
                    found = (action, state)
                    break
            if found is None:
                available = [fingerprint(s) for s in model.next_states(last_state)]
                raise NondeterministicModelError(
                    f"Unable to reconstruct a Path from fingerprints: {1 + len(pairs)} "
                    "previous state(s) were reconstructed, but no subsequent state "
                    f"has the next fingerprint ({next_fp}). This usually happens "
                    "when Model.actions or Model.next_state vary between calls. "
                    f"Available next fingerprints: {available}"
                )
            pairs.append((last_state, found[0]))
            last_state = found[1]
        pairs.append((last_state, None))
        return Path(pairs)

    @staticmethod
    def from_actions(model, init_state, actions: Iterable[Any]) -> Optional["Path"]:
        """Builds a path from an initial state plus actions (path.rs:101-131).

        Returns ``None`` if the input is unreachable via the model.
        """
        if init_state not in model.init_states():
            return None
        pairs: List[Tuple[Any, Optional[Any]]] = []
        prev_state = init_state
        for action in actions:
            found = None
            for a, s in model.next_steps(prev_state):
                if a == action:
                    found = (a, s)
                    break
            if found is None:
                return None
            pairs.append((prev_state, found[0]))
            prev_state = found[1]
        pairs.append((prev_state, None))
        return Path(pairs)

    @staticmethod
    def final_state(model, fingerprints: Sequence[int]) -> Optional[Any]:
        """The final state of a fingerprint path, or None (path.rs:134-165)."""
        fps = list(fingerprints)
        if not fps:
            return None
        matching = None
        for s in model.init_states():
            if fingerprint(s) == fps[0]:
                matching = s
                break
        if matching is None:
            return None
        for next_fp in fps[1:]:
            found = None
            for s in model.next_states(matching):
                if fingerprint(s) == next_fp:
                    found = s
                    break
            if found is None:
                return None
            matching = found
        return matching

    def last_state(self) -> Any:
        return self._pairs[-1][0]

    def into_states(self) -> List[Any]:
        return [s for s, _a in self._pairs]

    def into_actions(self) -> List[Any]:
        return [a for _s, a in self._pairs if a is not None]

    def into_vec(self) -> List[Tuple[Any, Optional[Any]]]:
        return list(self._pairs)

    def encode(self) -> str:
        """Encodes as ``/``-joined fingerprints for URLs (path.rs:189-198)."""
        return "/".join(str(fingerprint(s)) for s, _a in self._pairs)

    def __len__(self) -> int:
        return len(self._pairs) - 1

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self._pairs == other._pairs

    def __hash__(self) -> int:
        # Hash only state fingerprints: consistent with __eq__ (equal pairs
        # imply equal states) and avoids requiring actions to be
        # fingerprintable — the engine never requires that of actions.
        return hash(tuple(fingerprint(s) for s, _a in self._pairs))

    def __repr__(self) -> str:
        return f"Path({self._pairs!r})"

    def __str__(self) -> str:
        # Display format asserted by the reference's reporter tests
        # (checker.rs:684-757): "Path[n]:" then "- {action}" per action.
        lines = [f"Path[{len(self)}]:"]
        for _state, action in self._pairs:
            if action is not None:
                lines.append(f"- {action}")
        return "\n".join(lines) + "\n"
