"""Demand-driven checking ON the device engine: the Explorer's backend for
packed models.

The reference Explorer wraps its real engine (``OnDemandChecker``,
``/root/reference/src/checker/explorer.rs:81-103``); round 2's ``serve()``
wrapped only the host oracle, so browsing a packed model silently ran the
Python engine — fine at 544 states, useless at 1.7M. This checker keeps the
interactive contract (compute nothing until asked) while every expansion,
property evaluation, dedup, and witness reconstruction runs through the
device engine's compiled machinery:

- a **targeted expansion** (the user clicked a state) loads exactly that
  packed row as a one-row frontier and dispatches one compiled super-step:
  children dedup against the device hash set, properties evaluate on
  device, discoveries pin exactly as in batch runs;
- pending (discovered-but-unexpanded) rows live in a host-side pool keyed
  by device fingerprint — the on-demand analogue of the frontier;
- ``run_to_completion()`` reloads the entire pool as the frontier and
  hands over to the inherited **fused multi-level dispatch** — from that
  point this IS the batch engine (counts stay exact; with a mixed-depth
  pool the per-level depth accounting becomes approximate, exactly like
  the reference's run-to-completion from a driven state).

The Explorer passes the clicked object state (it has it in hand) via
``check_state``; host fingerprints never need translating to device ones.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..ops import fphash
from ..xla import XlaChecker


class DeviceOnDemandChecker(XlaChecker):
    """Spawned via ``CheckerBuilder.spawn_on_demand(engine="xla")`` or the
    Explorer's ``serve()`` on a packed model."""

    def __init__(self, builder, **kwargs):
        super().__init__(builder, **kwargs)
        self._waiting = True
        #: device fp64 -> (packed row, ebits, depth) of pending entries.
        self._pool: Dict[int, Tuple[np.ndarray, int, int]] = {}
        # self._depth is 1 for a fresh init frontier and the restored depth
        # after a checkpoint resume — the pool must inherit it either way.
        self._pool_add(
            self._frontier_rows_host(),
            np.asarray(self._frontier_ebits)[: self._frontier_count],
            self._depth,
        )

    def _pool_add(self, rows: np.ndarray, ebits: np.ndarray, depth: int) -> None:
        """File rows as pending entries, batch-fingerprinted (one vectorized
        dedup + hash over the whole batch, like the batch engine's init)."""
        if not len(rows):
            return
        dedup = self._dedup_words_host(np.asarray(rows, dtype=np.uint32))
        hi, lo = fphash.fingerprint_words(dedup, np)
        for i in range(len(rows)):
            key = (int(hi[i]) << 32) | int(lo[i])
            self._pool[key] = (rows[i].copy(), int(ebits[i]), depth)

    # --- control flow (the on-demand contract) -----------------------------

    def check_state(self, state: Any, fp: Optional[int] = None) -> None:
        """Evaluate and expand the pending entry for this object state, if
        any (the device form of ``OnDemandChecker.check_fingerprint``;
        unknown or already-expanded states are ignored). The state itself is
        passed — not just a fingerprint — because pending rows are keyed by
        DEVICE fingerprint, which only the packed codec can compute."""
        self.check_states([state])

    def check_states(self, states) -> None:
        """Batched :meth:`check_state`: all pending entries among ``states``
        expand in one device dispatch per depth group — one tunnel
        round-trip where per-child expansion would pay one per state (the
        Explorer expands every child of a clicked state)."""
        if not self._waiting:
            return
        if self._target_reached or (
            self._P > 0
            and all(n in self._found_names for n in self._prop_names)
        ):
            # _run_block_single would refuse to expand (its entry checks),
            # leaving the input rows in the frontier; don't pop them.
            return
        by_depth: Dict[int, list] = {}
        for state in states:
            entry = self._pool.pop(self._packed_fp64(state), None)
            if entry is not None:
                by_depth.setdefault(entry[2], []).append(entry)
        for depth, entries in sorted(by_depth.items()):
            if self._target_reached or (
                self._P > 0
                and all(n in self._found_names for n in self._prop_names)
            ):
                # An earlier group crossed a target / pinned the last
                # property: _run_block_single would refuse to expand, so
                # put the remaining entries back untouched.
                for row, eb, d in entries:
                    key = fphash.fingerprint_u64(
                        self._dedup_words_host(row[None, :])[0], np
                    )
                    self._pool[key] = (row, eb, d)
                continue
            self._expand_rows(
                np.stack([r for r, _, _ in entries]),
                np.asarray([e for _, e, _ in entries], np.uint32),
                depth,
            )

    def check_fingerprint(self, fingerprint: int) -> None:
        """Host fingerprints cannot address device-keyed pending rows; the
        Explorer uses :meth:`check_state` (it always has the state in hand).
        Kept as an explicit no-op for API compatibility."""

    def run_to_completion(self) -> None:
        """Unblock: the whole pending pool becomes the frontier and the
        inherited fused batch engine takes over (on_demand.rs:193-198)."""
        import jax.numpy as jnp

        if not self._waiting:
            return
        self._waiting = False
        if not self._pool:
            self._frontier_count = 0
            self._exhausted = True
            return
        rows = np.stack([r for r, _, _ in self._pool.values()])
        ebits = np.asarray([e for _, e, _ in self._pool.values()], np.uint32)
        depth = min(d for _, _, d in self._pool.values())
        self._pool.clear()
        need = 1 << max(int(len(rows) - 1).bit_length(), 4)
        if need > self._frontier_capacity:
            self._frontier_capacity = need
        self._store_frontier_rows(rows)
        self._frontier_ebits = jnp.asarray(ebits)
        self._frontier_count = len(rows)
        self._depth = depth
        self._exhausted = False

    # --- engine ------------------------------------------------------------

    def _expand_rows(self, rows: np.ndarray, ebits: np.ndarray, depth: int) -> None:
        """One compiled super-step over exactly these rows; fresh children
        join the pending pool at depth + 1."""
        import jax.numpy as jnp

        self._depth = depth
        self._exhausted = False
        self._store_frontier_rows(rows)
        self._frontier_ebits = jnp.asarray(ebits)
        self._frontier_count = len(rows)
        self._run_block_single()
        # Children are table-fresh by construction, so they cannot collide
        # with an existing pending entry.
        self._pool_add(
            self._frontier_rows_host(),
            np.asarray(self._frontier_ebits)[: self._frontier_count],
            depth + 1,
        )

    def _run_block(self, max_count: int = 1500) -> None:
        if self._waiting:
            return  # computes nothing until asked (on_demand.rs:165-203)
        super()._run_block(max_count)

    def discoveries(self):
        """Explorer polls this on every request; witness paths are stable
        once found (parent chains never change under later insertions), so
        cache by the discovery set instead of re-pulling the device table
        per poll."""
        key = tuple(sorted(self._found_names.items()))
        cached = self.__dict__.get("_disc_cache")
        if cached is not None and cached[0] == key:
            return cached[1]
        out = super().discoveries()
        self.__dict__["_disc_cache"] = (key, out)
        return out

    # --- Checker API adjustments (mirror checker/on_demand.py) -------------

    def metrics(self):
        """The engine registry plus the on-demand surface's own gauges:
        the pending pool (discovered-but-unexpanded states) and whether
        the checker is still waiting (compute-nothing-until-asked).

        As the Explorer's backend this checker is one CLIENT of the
        multi-tenant ``stateright_tpu/service`` pool: ``make_app``
        registers it via ``CheckerService.register_interactive`` (typed
        admission past ``max_sessions``), ``attach_job`` (base Checker)
        threads the pool job id in here as ``job_id``, and the pool's
        breaker decides whether a session gets this engine at all — open
        means the Explorer serves degraded on the host on-demand engine
        instead."""
        out = super().metrics()
        out["pending_pool"] = len(self._pool)
        out["waiting"] = self._waiting
        return out

    def is_done(self) -> bool:
        if self._waiting:
            return (
                not self._pool
                or self._target_reached
                or (
                    self._P > 0
                    and all(n in self._found_names for n in self._prop_names)
                )
            )
        return super().is_done()

    def join(self) -> "DeviceOnDemandChecker":
        if self._waiting and not self.is_done():
            raise RuntimeError(
                "join() on an on-demand checker that was never unblocked; "
                "call run_to_completion() first"
            )
        return super().join()
