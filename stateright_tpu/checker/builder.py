"""CheckerBuilder: fluent checker configuration.

Mirrors ``/root/reference/src/checker.rs:52-248``.  The strategy boundary —
``spawn_bfs`` / ``spawn_dfs`` / ``spawn_on_demand`` / ``serve`` — is preserved
and extended with ``spawn_xla()``, the TPU frontier-expansion engine.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..core import Model
from .base import Checker
from .visitor import as_visitor


class CheckerBuilder:
    """Instantiate via ``model.checker()`` (lib.rs:247)."""

    def __init__(self, model: Model):
        self._model = model
        self._symmetry: Optional[Callable[[Any], Any]] = None
        self._target_state_count: Optional[int] = None
        self._target_max_depth: Optional[int] = None
        self._thread_count: int = 1
        self._visitor = None

    # --- terminal strategies ---------------------------------------------

    def spawn_bfs(self) -> Checker:
        """Breadth-first search; shortest witness paths (checker.rs:155).

        With ``threads(n)`` for n > 1 (and no visitor), a level-synchronous
        multiprocess engine expands the frontier across n forked workers
        with fingerprint-sharded visited sets
        (``stateright_tpu.checker.parallel_host``) — the host analogue of
        the reference's worker pool (bfs.rs:89-211)."""
        if (self._thread_count or 1) > 1 and self._visitor is None:
            from .parallel_host import ParallelBfsChecker

            return ParallelBfsChecker(self)
        from .search import BfsChecker

        return BfsChecker(self)

    def spawn_dfs(self) -> Checker:
        """Depth-first search; smaller frontier (checker.rs:187). With
        ``threads(n)`` for n > 1 (and no visitor — visitors observe
        per-state paths sequentially, so they fall back to the sequential
        engine exactly as ``spawn_bfs`` does), the job-market parallel
        DFS — the reference's default CLI discipline (dfs.rs:42,
        92-215)."""
        if (self._thread_count or 1) > 1 and self._visitor is None:
            from .parallel_dfs import ParallelDfsChecker

            return ParallelDfsChecker(self)
        from .search import DfsChecker

        return DfsChecker(self)

    def spawn_on_demand(self, engine: str = "host", **spawn_kwargs) -> Checker:
        """Demand-driven search: computes nothing until asked
        (checker.rs:171). ``engine="xla"`` runs it on the device engine
        (packed models; ``spawn_kwargs`` are ``spawn_xla`` capacities) —
        targeted expansions dispatch compiled super-steps and
        ``run_to_completion()`` hands over to the fused batch engine.
        The host engine accepts ``block_size`` (default 1): with the
        reference's 1500 a ``check_fingerprint`` pre-computes up to that
        many states of the clicked subtree (on_demand.rs:209-218)."""
        if engine == "xla":
            from .device_on_demand import DeviceOnDemandChecker

            return DeviceOnDemandChecker(self, **spawn_kwargs)
        unknown = set(spawn_kwargs) - {"block_size"}
        if unknown:
            raise TypeError(
                f"spawn kwargs {sorted(unknown)} only apply to engine=\"xla\""
            )
        try:
            from .on_demand import OnDemandChecker
        except ImportError as e:
            raise NotImplementedError(
                "spawn_on_demand() is not available yet in this build"
            ) from e
        return OnDemandChecker(self, **spawn_kwargs)

    def spawn_xla(self, *, mesh=None, **kwargs) -> Checker:
        """TPU/XLA frontier-expansion engine: the whole BFS frontier is
        expanded per device super-step with vmapped packed transitions,
        device-resident hash-set dedup, and fused property evaluation.

        Requires the model to implement the :class:`PackedModel` protocol
        (see ``stateright_tpu.xla`` for the contract).

        Engine-tuning knobs ride through ``kwargs`` to ``XlaChecker``:
        ``dedup=``, ``compaction=``, ``ladder=``, ``shrink_exit=``, and
        ``cand_ladder=`` (the in-program candidate-width ladder: fused
        dispatches branch over up to K=3 sub-width supersteps via
        ``lax.switch``, so narrow levels sort snug candidate buffers with
        zero added host round-trips; ``STPU_CAND_LADDER`` is the env
        form, 1 disables, planes engine only).

        Observability (``stateright_tpu.obs``, docs/observability.md):
        ``trace=`` appends wall-clock spans around every host↔device
        boundary as JSONL (env ``STPU_TRACE``; ``STPU_TRACE_CHROME``
        additionally exports Chrome trace-event JSON for Perfetto), and
        ``heartbeat=`` names a small JSON file rewritten around every
        device dispatch so watchdogs can tell a wedged tunnel from a
        long XLA compile (env ``STPU_HEARTBEAT``). Both off by default;
        neither adds device syncs. ``checker.metrics()`` returns the
        unified counters/gauges snapshot either way. ``phases=True``
        (env ``STPU_PHASES=1``, needs a live tracer) turns on the
        dispatch-phase profiler: each device call splits into
        host_prep/enqueue/device_compute/readback sub-spans plus a
        ``checker.phase_log`` row (``tools/roofline.py --phases``).

        With ``mesh`` (a ``jax.sharding.Mesh`` with one axis, more than one
        device), the frontier and visited set shard by fingerprint ownership
        over the mesh with all-to-all routing per super-step
        (``stateright_tpu.parallel``; the single-chip tuning knobs above
        do not apply there).
        """
        try:
            from ..xla import XlaChecker
        except ImportError as e:
            raise NotImplementedError(
                "spawn_xla() is not available yet in this build"
            ) from e
        if mesh is not None and mesh.devices.size > 1:
            from ..parallel import ShardedXlaChecker

            return ShardedXlaChecker(self, mesh, **kwargs)
        kwargs.pop("route_capacity", None)  # sharded-only tuning knob
        return XlaChecker(self, **kwargs)

    def serve(self, addresses, engine: str = "auto", **spawn_kwargs) -> Checker:
        """Starts the interactive Explorer web service (checker.rs:137).
        Packed models are explored on the DEVICE engine by default
        (``engine="auto"``); pass ``engine="host"`` to force the Python
        oracle."""
        try:
            from .explorer import serve
        except ImportError as e:
            raise NotImplementedError(
                "serve() is not available yet in this build"
            ) from e
        return serve(self, addresses, engine=engine, **spawn_kwargs)

    # --- configuration ----------------------------------------------------

    def symmetry(self) -> "CheckerBuilder":
        """Enables symmetry reduction; states must define
        ``representative()`` (checker.rs:198-203)."""
        return self.symmetry_fn(lambda s: s.representative())

    def symmetry_fn(self, representative: Callable[[Any], Any]) -> "CheckerBuilder":
        self._symmetry = representative
        return self

    def target_state_count(self, count: int) -> "CheckerBuilder":
        """The checker may exceed this count but never stops short of it
        while more states exist (checker.rs:215-222)."""
        self._target_state_count = count if count > 0 else None
        return self

    def target_max_depth(self, depth: int) -> "CheckerBuilder":
        self._target_max_depth = depth if depth > 0 else None
        return self

    def threads(self, thread_count: int) -> "CheckerBuilder":
        """Worker count for the host engines (checker.rs:234). With n > 1,
        ``spawn_bfs`` runs the multiprocess level-synchronous engine
        (``stateright_tpu.checker.parallel_host``) and ``spawn_dfs`` the
        job-market parallel DFS (``stateright_tpu.checker.parallel_dfs``);
        with a visitor both fall back to their sequential engines. The
        massively parallel form in this framework is the XLA engine, which
        uses every core of every chip regardless of this setting."""
        self._thread_count = thread_count
        return self

    def visitor(self, visitor) -> "CheckerBuilder":
        """A function (or CheckerVisitor) applied to every evaluated path
        (checker.rs:242-247)."""
        self._visitor = as_visitor(visitor)
        return self
