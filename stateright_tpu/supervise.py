"""Crash-recovery supervisor: run wedge-prone device work to completion.

The axon TPU tunnel WEDGES — blocks forever rather than failing — so every
long device run needs an outside supervisor. Until this module, that
supervisor existed twice as near-copies (bench.py's heartbeat-aware
watchdog, tools/tpu_watch.sh's ``hb_stale``) and recovery meant
*restarting from level 0*. This is the ONE library form of both halves:

- :func:`heartbeat_verdict` — the protocol table from
  docs/observability.md, as a function: given the worker's heartbeat file
  (``stateright_tpu/obs/heartbeat.py``), decide *alive* (None) or a kill
  reason. Stale in ``phase="idle"`` is host-side work — never a kill; a
  stale ``phase="dispatch"`` beat is a wedged tunnel, with a stretched
  leash when the beat flags an in-flight XLA compile.
- :func:`run_worker` — ONE supervised attempt: spawn the worker in its own
  process group (``start_new_session``), poll the heartbeat, kill the
  whole group on a wedge verdict or the hard timeout (SIGTERM, then
  SIGKILL — which also takes SIGSTOP-frozen processes). The heartbeat file
  is unlinked on the way out: a dead worker's final ``phase="dispatch"``
  beat must not read as a wedge to an outer watcher.
- :func:`supervise` — the retry loop: bounded attempts with exponential
  backoff, each retry RESUMING from the latest *valid* rotation of the
  worker's checkpoint (``stateright_tpu/checkpoint.py``) — a torn newest
  rotation is skipped automatically in favor of the previous one — plus an
  optional final fallback attempt (e.g. a CPU worker, supervised by the
  hard timeout alone: no tunnel, no wedge).

The worker contract: it writes checkpoints (normally via
``spawn_xla(checkpoint_to=...)``), beats ``STPU_HEARTBEAT`` (injected into
its environment here), and accepts a resume path from ``make_argv`` —
how the path rides into the worker (CLI flag, env var) is the caller's
choice. ``bench.py`` and ``tools/soak.py`` are the two in-tree users.

Everything here is stdlib + the obs/checkpoint helpers — importing this
module never imports jax, so a supervisor process stays wedge-proof
itself.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Union

from . import chaos
from . import checkpoint as ck_mod
from .obs import heartbeat as hb_mod
from .obs import trace as trace_mod


def heartbeat_verdict(
    path: str,
    *,
    started_wall: float,
    elapsed_s: float,
    stall_s: float,
    startup_grace_s: float,
    compile_leash: float = 3.0,
) -> Optional[str]:
    """The watchdog's per-poll decision: None = leave the worker alone,
    else the kill reason. Implements the heartbeat-protocol table
    (docs/observability.md): beats older than ``started_wall`` are a
    previous run's; a worker that never beat gets ``startup_grace_s``
    (imports + init inserts can wedge before the first dispatch); stale in
    ``phase="idle"`` is host-side work (the hard timeout governs); stale
    mid-``phase="dispatch"`` past the leash (x ``compile_leash`` when the
    beat flags a fresh XLA compile) is a wedged tunnel."""
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        mtime = None
    if mtime is None or mtime < started_wall:
        if elapsed_s > startup_grace_s:
            return f"no heartbeat within {startup_grace_s:.0f}s startup grace"
        return None
    rec = hb_mod.read(path) or {}
    if rec.get("phase") != "dispatch":
        return None
    age = time.time() - mtime
    allow = stall_s * (compile_leash if rec.get("compile") else 1)
    if age > allow:
        return (
            f"heartbeat stale {age:.0f}s > {allow:.0f}s mid-dispatch "
            f"(compile={bool(rec.get('compile'))}, seq={rec.get('seq', '?')})"
            " — wedged worker"
        )
    return None


@dataclass
class WorkerResult:
    """One supervised attempt's outcome."""

    rc: Optional[int]  #: exit code; None when the watchdog killed it
    killed: Optional[str]  #: kill reason, or None for a natural exit
    seconds: float
    stdout_path: Optional[str]

    @property
    def ok(self) -> bool:
        return self.killed is None and self.rc == 0

    @property
    def wedged(self) -> bool:
        """Whether the watchdog killed this attempt on a *liveness* verdict
        (heartbeat stale mid-dispatch, or no beat within the startup
        grace) — the wedged-tunnel signature — as opposed to the hard
        wall-clock timeout (budget exhaustion, not a device fault). The
        classification multi-job supervisors (``stateright_tpu/service``)
        key their breaker and requeue policy on."""
        return self.killed is not None and not self.killed.startswith(
            "hard timeout"
        )

    @property
    def crashed(self) -> bool:
        """A natural exit by signal (rc < 0): the worker died mid-run —
        SIGKILL from the OOM killer, a segfault — without any watchdog
        verdict. Like a wedge, the remedy is resume-from-checkpoint; unlike
        a wedge, it is not evidence against the device."""
        return self.killed is None and self.rc is not None and self.rc < 0


def backoff_delay(attempt: int, base_s: float) -> float:
    """The retry ladder every supervisor here shares: exponential from
    ``base_s``, where ``attempt`` counts retries from 1 (attempt 0 is the
    first try and never waits)."""
    if attempt < 1 or base_s <= 0:
        return 0.0
    return base_s * (2 ** (attempt - 1))


def _kill_group(proc: subprocess.Popen, grace_s: float = 2.0) -> None:
    """Kill the worker's whole process group: TERM first (a healthy-but-slow
    tree gets to flush), then KILL — which also takes SIGSTOP-frozen
    processes, where TERM would sit pending forever."""
    for sig in (signal.SIGTERM, signal.SIGKILL):
        try:
            os.killpg(proc.pid, sig)
        except ProcessLookupError:
            break
        except OSError:
            proc.kill()
        try:
            proc.wait(timeout=grace_s)
            break
        except subprocess.TimeoutExpired:
            continue
    try:
        proc.wait(timeout=grace_s)
    except subprocess.TimeoutExpired:  # pragma: no cover - unkillable child
        pass


def run_worker(
    argv: Sequence[str],
    *,
    heartbeat: Optional[str] = None,
    timeout_s: float = float("inf"),
    stall_s: float = 1200.0,
    startup_grace_s: float = 900.0,
    compile_leash: float = 3.0,
    env: Optional[dict] = None,
    cwd: Optional[str] = None,
    stdout_path: Optional[str] = None,
    poll_s: float = 5.0,
    log: Optional[Callable[[str], None]] = None,
    on_spawn: Optional[Callable[[subprocess.Popen], None]] = None,
    tracer=None,
    trace_ctx: Optional[tuple] = None,
    trace_attrs: Optional[dict] = None,
) -> WorkerResult:
    """ONE supervised attempt of ``argv``.

    The worker runs in its own process group; with ``heartbeat`` set the
    path is exported as ``STPU_HEARTBEAT`` (the engines beat it around
    every device dispatch) and polled every ``poll_s`` under
    :func:`heartbeat_verdict`; without it only the hard ``timeout_s``
    supervises (the CPU-fallback mode: no tunnel, no wedge). Worker stdout
    goes to ``stdout_path`` (a file, not a pipe — the parent never reads
    concurrently, so a pipe could deadlock a chatty worker, and a file
    survives for post-mortem salvage no matter how the worker dies).

    Distributed tracing (docs/observability.md): with ``tracer`` (a live
    :class:`stateright_tpu.obs.Tracer`) and ``trace_ctx``
    (``(trace_id, parent_span_id)``), the attempt is recorded as ONE
    ``attempt`` span covering spawn→exit — its span id is pre-allocated
    and exported to the worker as ``STPU_TRACE_CTX``, so every span the
    worker's own tracer writes joins the submission's trace with this
    attempt as its parent. ``trace_attrs`` ride on the span (the service
    adds ``job``/``attempt``)."""
    _log = log or (lambda msg: None)
    env = dict(os.environ if env is None else env)
    if heartbeat is not None:
        heartbeat = os.path.abspath(heartbeat)
        os.makedirs(os.path.dirname(heartbeat) or ".", exist_ok=True)
        env["STPU_HEARTBEAT"] = heartbeat
    trace_id = parent_sid = attempt_sid = None
    if trace_ctx is not None:
        trace_id, parent_sid = trace_ctx
    if tracer is not None and getattr(tracer, "enabled", False) and trace_id:
        attempt_sid = tracer.new_span_id()
        env[trace_mod.CTX_ENV] = trace_mod.format_ctx(trace_id, attempt_sid)
    # heartbeat=None leaves an inherited STPU_HEARTBEAT untouched: a
    # worker whose INNER watchdog is off may still beat an OUTER
    # watcher's stage file (tpu_watch.sh + BENCH_HEARTBEAT=0). Callers
    # that must silence beats entirely scrub their env themselves — the
    # CPU paths in bench.py/soak.py and supervise()'s fallback below.
    out_fh = open(stdout_path, "w") if stdout_path else None
    t0 = time.monotonic()
    wall0 = time.time()
    killed = None
    try:
        proc = subprocess.Popen(
            list(argv),
            stdout=out_fh,
            env=env,
            cwd=cwd,
            start_new_session=True,
        )
        if on_spawn is not None:
            # Hands the live Popen to multi-job supervisors (the service's
            # close-with-kill path) — run_worker itself stays the only
            # place that polls or reaps it.
            on_spawn(proc)
        while True:
            try:
                proc.wait(timeout=poll_s)
                break
            except subprocess.TimeoutExpired:
                pass
            elapsed = time.monotonic() - t0
            if elapsed > timeout_s:
                killed = f"hard timeout {timeout_s:.0f}s"
                break
            if chaos.fire("supervise.wedge") is not None:
                # Deterministic fault injection (stateright_tpu/chaos.py):
                # a scripted wedge verdict, classified exactly like a
                # stale mid-dispatch heartbeat (WorkerResult.wedged) so
                # quarantine/breaker paths are drivable without a real
                # SIGSTOP. No-op unless an STPU_CHAOS plan names it.
                killed = "chaos: simulated wedge verdict"
                break
            if heartbeat is not None:
                killed = heartbeat_verdict(
                    heartbeat,
                    started_wall=wall0,
                    elapsed_s=elapsed,
                    stall_s=stall_s,
                    startup_grace_s=startup_grace_s,
                    compile_leash=compile_leash,
                )
                if killed is not None:
                    break
        if killed is not None:
            _log(f"killing worker group (pid {proc.pid}): {killed}")
            _kill_group(proc)
    finally:
        if out_fh is not None:
            out_fh.close()
        if heartbeat is not None:
            # Live supervision state, not an artifact: a dead worker's
            # final phase="dispatch" beat must not linger for an outer
            # watcher to read as a wedge.
            try:
                os.unlink(heartbeat)
            except OSError:
                pass
    if attempt_sid is not None:
        attrs = dict(trace_attrs or {})
        attrs.update(
            pid=proc.pid,
            rc=None if killed else proc.returncode,
            killed=killed,
        )
        tracer.emit(
            "attempt", t0=t0, dur=time.monotonic() - t0, attrs=attrs,
            parent_id=parent_sid, trace_id=trace_id, span_id=attempt_sid,
        )
    return WorkerResult(
        rc=None if killed else proc.returncode,
        killed=killed,
        seconds=time.monotonic() - t0,
        stdout_path=stdout_path,
    )


#: ``make_argv(attempt, resume)`` — the worker command line for this
#: attempt. ``resume`` is the checkpoint path to resume from (the latest
#: valid rotation), or None for a cold start.
MakeArgv = Callable[[int, Optional[str]], Sequence[str]]


@dataclass
class SuperviseResult:
    ok: bool
    attempts: List[WorkerResult] = field(default_factory=list)
    #: The resume path each attempt was handed (None = cold start), index-
    #: aligned with ``attempts``; a fallback attempt appends here too.
    resumed_from: List[Optional[str]] = field(default_factory=list)
    used_fallback: bool = False

    @property
    def final(self) -> Optional[WorkerResult]:
        return self.attempts[-1] if self.attempts else None


def supervise(
    make_argv: MakeArgv,
    *,
    checkpoint: Optional[str] = None,
    retries: int = 2,
    backoff_s: float = 5.0,
    success: Optional[Callable[[WorkerResult], bool]] = None,
    fallback_make_argv: Optional[MakeArgv] = None,
    fallback_timeout_s: Optional[float] = None,
    log: Optional[Callable[[str], None]] = None,
    stdout_path: Union[None, str, Callable[[int], str]] = None,
    **worker_kw,
) -> SuperviseResult:
    """Run a worker to success with bounded retries, resuming each retry
    from the latest valid rotation of ``checkpoint``.

    ``1 + retries`` attempts of ``make_argv(attempt, resume)``; before each
    attempt the resume path is re-resolved via
    :func:`checkpoint.latest_valid_checkpoint`, so progress a previous
    attempt checkpointed is never re-explored and a torn newest rotation
    falls back to the one before it automatically. Retries back off
    exponentially from ``backoff_s``. ``success`` (default: exit code 0)
    judges each attempt. If every attempt fails and ``fallback_make_argv``
    is given, ONE final attempt runs it — heartbeat supervision off, hard
    ``fallback_timeout_s`` only (the CPU-fallback mode) — still handed the
    latest resume path. Remaining keyword arguments go to
    :func:`run_worker`."""
    _log = log or (lambda msg: None)
    judge = success or (lambda r: r.ok)
    result = SuperviseResult(ok=False)

    def attempt_once(attempt: int, builder: MakeArgv, **kw) -> bool:
        resume = (
            ck_mod.latest_valid_checkpoint(checkpoint) if checkpoint else None
        )
        sp = stdout_path(attempt) if callable(stdout_path) else stdout_path
        res = run_worker(
            builder(attempt, resume), stdout_path=sp, log=_log, **kw
        )
        result.attempts.append(res)
        result.resumed_from.append(resume)
        if judge(res):
            result.ok = True
            return True
        _log(
            f"attempt {attempt} failed (rc={res.rc}, killed={res.killed}, "
            f"{res.seconds:.0f}s)"
        )
        return False

    for attempt in range(1 + retries):
        if attempt and backoff_s:
            delay = backoff_delay(attempt, backoff_s)
            _log(f"retry {attempt}/{retries} after {delay:.0f}s backoff")
            time.sleep(delay)
        if attempt_once(attempt, make_argv, **worker_kw):
            return result
    if fallback_make_argv is not None:
        _log("retries exhausted; falling back (heartbeat supervision off)")
        kw = dict(worker_kw)
        kw.pop("heartbeat", None)
        kw.pop("stall_s", None)
        kw.pop("startup_grace_s", None)
        kw.pop("compile_leash", None)
        # The fallback worker (typically CPU: no tunnel, no wedge) must
        # not beat an OUTER watcher's stage file either — on this 1-core
        # box a long CPU dispatch legitimately outlives any stall leash,
        # so an inherited STPU_HEARTBEAT would get the healthy fallback
        # killed as a "wedge".
        fenv = dict(kw.pop("env", None) or os.environ)
        fenv.pop("STPU_HEARTBEAT", None)
        kw["env"] = fenv
        if fallback_timeout_s is not None:
            kw["timeout_s"] = fallback_timeout_s
        result.used_fallback = True
        attempt_once(len(result.attempts), fallback_make_argv, **kw)
    return result


if __name__ == "__main__":  # pragma: no cover - tiny manual harness
    # python -m stateright_tpu.supervise -- CMD ...   (one watched attempt)
    args = sys.argv[1:]
    if args and args[0] == "--":
        args = args[1:]
    res = run_worker(
        args,
        heartbeat=os.environ.get("STPU_HEARTBEAT"),
        timeout_s=float(os.environ.get("SUPERVISE_TIMEOUT_S", "inf")),
        stall_s=float(os.environ.get("SUPERVISE_STALL_S", "1200")),
        log=lambda m: print(f"[supervise] {m}", file=sys.stderr, flush=True),
    )
    print(f"[supervise] rc={res.rc} killed={res.killed}", file=sys.stderr)
    sys.exit(res.rc if res.rc is not None else 125)
