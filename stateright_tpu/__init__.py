"""stateright_tpu: a TPU-native model-checking framework.

Provides the capabilities of the reference `stateright` library — a ``Model``
abstraction for nondeterministic transition systems, always/sometimes/
eventually property checking, an actor framework that can be both model
checked and run over UDP, linearizability/sequential-consistency testers,
symmetry reduction, and an interactive Explorer — with the search engine
re-designed for TPUs: the BFS frontier is expanded with vmapped bit-packed
transition kernels, deduplicated against a device-resident hash set, and
property checks fused into the same pass (``spawn_xla()``), scaling across a
``jax.sharding.Mesh`` by fingerprint-sharded frontier routing.

The flat namespace mirrors the reference's re-export style
(``/root/reference/src/lib.rs:145``): ``from stateright_tpu import *`` gives
``Model``, ``Property``, ``CheckerBuilder`` etc.  JAX is imported lazily —
the core API and CPU oracle engines work without touching an accelerator.
"""

from .core import Expectation, Model, Property
from .fingerprint import fingerprint
from .checker import (
    Checker,
    CheckerBuilder,
    CheckerVisitor,
    NondeterministicModelError,
    Path,
    PathRecorder,
    StateRecorder,
)
from .report import ReportData, ReportDiscovery, Reporter, WriteReporter
from .semantics import (
    ConsistencyTester,
    HistoryError,
    LinearizabilityTester,
    SequentialConsistencyTester,
    SequentialSpec,
)

__version__ = "0.1.0"

__all__ = [
    "Checker",
    "CheckerBuilder",
    "CheckerVisitor",
    "ConsistencyTester",
    "Expectation",
    "HistoryError",
    "LinearizabilityTester",
    "SequentialConsistencyTester",
    "SequentialSpec",
    "Model",
    "NondeterministicModelError",
    "Path",
    "PathRecorder",
    "Property",
    "ReportData",
    "ReportDiscovery",
    "Reporter",
    "StateRecorder",
    "WriteReporter",
    "fingerprint",
]
