"""Crash-safe job journal: the durable half of the CheckerService.

The per-job checkpoint rotations (PR f279271) survive a pool-process
death; the pool state around them — the queue, per-job budgets,
quarantine/backoff, the breaker — did not (ROADMAP item 3b). This module
is the append-only record the service replays on restart: one JSONL line
per typed job event, written with the same durability discipline as
``checkpoint.py``:

- **self-verifying appends** — every record embeds a SHA-256 over its own
  canonical serialization (``sha256`` field, digest computed with the
  field absent). A crash mid-append leaves a torn final line that fails
  JSON parse or digest; :func:`read_journal` reports it as a typed,
  recoverable condition (the record is dropped, everything before it
  replays) — never a wedge, never a bare traceback.
- **keep-K snapshot compaction** — :meth:`Journal.compact` rewrites the
  log as ONE ``snapshot`` record of the service's current state (atomic:
  same-directory temp + ``os.replace``), rotating the previous log to
  ``<path>.1`` … ``<path>.K-1`` like checkpoint rotations, so the live
  log is bounded by the compaction cadence and history stays inspectable.
  Recovery always compacts (the snapshot it just rebuilt), which also
  amputates a torn tail — appends never land after torn bytes.

Record shape (one JSON object per line)::

    {"v": 1, "seq": N, "ts": <unix>, "event": "<type>", ...payload,
     "sha256": <hex over the record without this field>}

Event types and their payloads are the service's
(``service/core.py`` ``_jlog``/``_snapshot_payload``; documented in
docs/service.md "Durability & recovery"): ``submitted`` / ``admitted`` /
``started`` / ``checkpointed`` / ``budget_charged`` / ``quarantined`` /
``completed`` / ``breaker_tripped`` / ``breaker_closed`` / ``snapshot``
/ ``recovered``.

Fault injection (``stateright_tpu/chaos.py``): the writer honors
``journal.torn`` (append only the first ``at`` bytes, then SIGKILL —
a crash mid-append) and ``journal.die`` (append fully, then SIGKILL —
a crash at a deterministic journal position). Both are no-ops unless an
``STPU_CHAOS`` plan names them.

Everything here is stdlib — importing it never imports jax.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .. import chaos

FORMAT_VERSION = 1


class JournalTorn(Exception):
    """A journal whose tail (or a mid-file record) cannot be trusted.
    Raised only by ``read_journal(strict=True)``; the default replay path
    returns the torn reason alongside the clean prefix instead — torn is
    a *recoverable condition* for a restarting service, not an error."""


@dataclass
class JournalReplay:
    """``read_journal``'s result: the verified records in order, plus the
    torn-tail description (None when the file read clean)."""

    path: str
    records: List[Dict[str, Any]] = field(default_factory=list)
    torn: Optional[str] = None


def _digest(record: Dict[str, Any]) -> str:
    """SHA-256 over the record's canonical JSON, ``sha256`` field absent
    — recomputed on read, like checkpoint.py's payload digest."""
    body = {k: v for k, v in record.items() if k != "sha256"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


class Journal:
    """Writer side. All appends happen under the owning service's lock —
    this class adds durability discipline, not thread coordination. The
    file handle opens lazily (service construction stays cheap) and in
    append mode (a restart that chose not to compact keeps history)."""

    def __init__(self, path: str, *, keep: int = 3,
                 compact_every: int = 256):
        if keep < 1:
            raise ValueError(f"journal keep must be >= 1, got {keep}")
        if compact_every < 2:
            raise ValueError(
                f"journal compact_every must be >= 2, got {compact_every}"
            )
        self.path = path
        self.keep = keep
        self.compact_every = compact_every
        self.seq = 0
        #: Appends since the last compaction (compaction is the SERVICE's
        #: call — it owns the snapshot payload; the journal only reports
        #: when one is due).
        self.since_compact = 0
        self._fh = None
        #: A torn-append injection simulates a crash; if the process
        #: somehow survives (tests driving the writer directly), the
        #: writer plays dead — a real crashed writer appends nothing
        #: more, and bytes after a torn tail would corrupt mid-file.
        self._dead = False

    # -- append ------------------------------------------------------------

    def _handle(self):
        if self._fh is None:
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def append(self, event: str, *, ts: float, **payload: Any) -> Optional[dict]:
        """One durable record; returns it (None from a dead writer).
        ``ts`` is wall-clock (recovery charges budgets from these)."""
        if self._dead:
            return None
        self.seq += 1
        record: Dict[str, Any] = {
            "v": FORMAT_VERSION, "seq": self.seq, "ts": ts, "event": event,
        }
        record.update(payload)
        record["sha256"] = _digest(record)
        data = json.dumps(record, sort_keys=True, separators=(",", ":")) + "\n"
        fh = self._handle()
        inj = chaos.fire("journal.torn", size=len(data))
        if inj is not None:
            # Crash mid-append: some prefix of the record reaches disk,
            # then the process dies (stateright_tpu/chaos.py).
            fh.write(data[: max(1, min(int(inj.get("at", 1)), len(data) - 1))])
            fh.flush()
            chaos.kill_self()
            self._dead = True  # pragma: no cover - unreachable after kill
            return None  # pragma: no cover
        fh.write(data)
        fh.flush()
        if chaos.fire("journal.die") is not None:
            # Crash AT a deterministic journal position: the record is
            # durable, nothing after it happens.
            chaos.kill_self()
        self.since_compact += 1
        return record

    @property
    def compaction_due(self) -> bool:
        return self.since_compact >= self.compact_every

    # -- compaction --------------------------------------------------------

    def compact(self, snapshot: Dict[str, Any], *, ts: float) -> dict:
        """Atomically rewrite the log as one ``snapshot`` record (payload
        = the service's full recoverable state), rotating the previous
        log to ``<path>.1``.. like checkpoint rotations. A kill anywhere
        inside leaves either the old log or the new one — never a mix."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self.seq += 1
        record: Dict[str, Any] = {
            "v": FORMAT_VERSION, "seq": self.seq, "ts": ts,
            "event": "snapshot", "state": snapshot,
        }
        record["sha256"] = _digest(record)
        tmp = f"{self.path}.tmp-{os.getpid()}"
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(
                json.dumps(record, sort_keys=True, separators=(",", ":"))
                + "\n"
            )
        if self.keep > 1 and os.path.exists(self.path):
            for i in range(self.keep - 1, 1, -1):
                older = f"{self.path}.{i - 1}"
                if os.path.exists(older):
                    os.replace(older, f"{self.path}.{i}")
            os.replace(self.path, f"{self.path}.1")
        os.replace(tmp, self.path)
        self.since_compact = 0
        return record

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read_journal(path: str, *, strict: bool = False) -> JournalReplay:
    """Replay side: every record that parses AND verifies, in order,
    stopping at the first one that does not (a torn tail from a crash
    mid-append — or, defensively, a tampered mid-file record; nothing
    after an untrusted record can be ordered against it). The torn
    description rides back on the result; ``strict=True`` raises
    :class:`JournalTorn` instead. A missing file stays
    ``FileNotFoundError`` — "no journal yet" and "journal destroyed" are
    different verdicts to a supervisor, exactly like checkpoints."""
    out = JournalReplay(path=path)
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh, 1):
            reason = None
            try:
                record = json.loads(line)
            except json.JSONDecodeError as e:
                reason = f"line {i}: unparseable ({e.msg})"
            else:
                if not isinstance(record, dict):
                    reason = f"line {i}: not a record object"
                elif record.get("sha256") != _digest(record):
                    reason = f"line {i}: record digest mismatch — torn or tampered"
                elif record.get("v") != FORMAT_VERSION:
                    reason = (
                        f"line {i}: unsupported journal format {record.get('v')!r}"
                    )
            if reason is not None:
                out.torn = reason
                if strict:
                    raise JournalTorn(f"{path}: {reason}")
                break
            out.records.append(record)
    return out
