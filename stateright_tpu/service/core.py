"""CheckerService: fault-isolated multi-tenant checking on one device.

ROADMAP item 3's production framing ("millions of users": one chip, many
concurrent interactive sessions and batch jobs) composed from the recovery
primitives PR 3 built for *one* run (``supervise.run_worker`` heartbeat
verdicts, atomic rotating checkpoints) into a pool where faults are
isolated per job and the pool degrades instead of dying:

- **Admission control** — bounded in-flight jobs and a bounded queue;
  beyond either, :meth:`CheckerService.submit` raises the typed
  :class:`AdmissionError` carrying ``retry_after_s`` (the ``Retry-After``
  value an HTTP front end would send) instead of queueing unboundedly.
  Per-job budgets: wall-clock (``max_seconds``, soft-checked in the worker
  at quiescent points, hard-backstopped by the supervisor) and state count
  (``max_states`` via ``target_state_count``), both clamped by pool caps.
- **Per-job fault isolation** — every device job runs
  ``service/worker.py`` in its own process group under
  ``supervise.run_worker`` with its *own* heartbeat, span trace, and
  auto-checkpoint rotation set under the service's run dir. A wedge
  verdict (heartbeat stale mid-dispatch — the tunnel signature) kills
  exactly that job's group, **quarantines** the job for an exponential
  backoff, and requeues it resuming from its latest valid checkpoint
  rotation; sibling jobs never see it. A worker that dies by signal
  (crash) requeues the same way but is not evidence against the device.
- **Graceful degradation** — ``breaker_k`` *consecutive* device wedge
  verdicts (any job) trip a breaker: new and requeued jobs route to the
  host on-demand engine (``checker/on_demand.py``) on the CPU backend with
  ``degraded: true`` in their status — slower, but no tunnel to wedge. A
  background prober (a watchdogged subprocess, so the service process
  itself never touches jax) re-probes the device and closes the breaker.
- **Status surface** — :meth:`metrics` snapshots pool gauges
  (queued/running/quarantined/interactive, breaker state, wedge/requeue
  counters through the obs registry) plus per-job summaries; each job's
  span trace exports as a Perfetto-loadable Chrome trace via
  :meth:`job_trace_chrome` (reusing ``obs.export_chrome``). The Explorer
  is one client: ``make_app``/``serve`` register their interactive checker
  as a pool job and embed the gauges in ``/.status``.

Like the supervisor it builds on, importing this module never imports jax
— the service process stays wedge-proof; only workers and the prober (both
subprocesses) touch a backend.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from .. import supervise as sup
from ..checkpoint import latest_valid_checkpoint
from ..obs import Counters, export_chrome
from . import registry

#: Pre-seeded pool counters (stable ``metrics()`` key set, like the
#: engines' ENGINE_COUNTERS; docs/service.md).
SERVICE_COUNTERS = (
    "submitted",
    "admitted",
    "rejected",
    "jobs_done",
    "jobs_failed",
    "wedge_verdicts",
    "crashes",
    "requeues",
    "breaker_trips",
    "breaker_closes",
    "degraded_jobs",
    "device_probes",
    "lint_checks",
    "lint_rejects",
    "lint_errors",
)

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "worker.py")
#: The admission flight-check entry point (stpu-lint's --admission mode;
#: docs/static-analysis.md). A subprocess, like every other jax touch —
#: the service process stays import-clean of jax even while it VERIFIES
#: jax programs.
_LINT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tools",
    "stpu_lint.py",
)


class AdmissionError(Exception):
    """Typed admission rejection. ``retry_after_s`` is the back-pressure
    hint (an HTTP front end's ``Retry-After``); None when retrying cannot
    help (a budget above the pool cap)."""

    def __init__(self, reason: str, retry_after_s: Optional[float] = None):
        msg = reason
        if retry_after_s is not None:
            msg += f" (retry after ~{retry_after_s:.0f}s)"
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclass
class ServiceConfig:
    """Pool knobs; everything has a production-shaped default and the chaos
    tests shrink the time constants."""

    run_dir: str = os.path.join("runs", "service")
    # -- admission ---------------------------------------------------------
    max_inflight: int = 2  #: concurrently running batch jobs
    max_queue: int = 8  #: queued + quarantined jobs beyond the running set
    max_sessions: int = 4  #: interactive (Explorer) clients
    default_max_seconds: float = 600.0
    max_seconds_cap: float = 3600.0
    max_states_cap: Optional[int] = None
    block_size: int = 1500  #: host-engine block granularity (on_demand.py)
    # -- supervision (supervise.run_worker) --------------------------------
    stall_s: float = 1200.0
    startup_grace_s: float = 900.0
    poll_s: float = 0.5
    requeue_limit: int = 2  #: wedge/crash requeues per job before it fails
    backoff_s: float = 5.0  #: quarantine backoff base (exponential)
    # -- breaker -----------------------------------------------------------
    breaker_k: int = 3  #: consecutive wedge verdicts that trip it
    probe_auto: bool = True  #: background re-probe while open
    probe_interval_s: float = 60.0
    probe_timeout_s: float = 45.0
    #: Device-liveness probe command (rc 0 = device healthy). The default
    #: pays full plugin init in a throwaway subprocess, exactly like
    #: ``backend.ensure_live_backend``'s probe.
    probe_argv: Optional[Sequence[str]] = None
    # -- admission flight-check (stpu-lint --admission) --------------------
    #: Statically lint a spec's kernel surfaces (STPU001/002/003), its
    #: cross-backend lowering diff (STPU008), and its compile plan
    #: (STPU007) before the pool schedules it on the device — the gate
    #: user-submitted specs (STPU_FAMILIES) pass through. Runs as a
    #: subprocess (the service never imports jax) and is double-cached:
    #: the linter's content-hash surface cache makes shipped specs cost
    #: one jax import (~2 s), and a per-service memo makes repeat
    #: submissions of the same spec free.
    admission_lint: bool = True
    lint_timeout_s: float = 240.0
    # -- workers -----------------------------------------------------------
    platform: str = "default"  #: "default" (accelerator) | "cpu" (tests)
    compile_cache: Optional[str] = None  #: default: <cwd>/.jax_cache
    checkpoint_every: Any = 1  #: per-job auto-checkpoint cadence
    checkpoint_keep: int = 3


class Job:
    """One pool entry. Batch jobs own a job dir (checkpoints, heartbeat,
    trace, worker stdout); interactive jobs wrap a live in-process checker.
    All mutation happens under the service lock."""

    def __init__(
        self,
        service: "CheckerService",
        job_id: str,
        spec: str,
        *,
        kind: str = "batch",
        max_seconds: float = 600.0,
        max_states: Optional[int] = None,
        chaos: Optional[Dict[str, Any]] = None,
    ):
        self._service = service
        self.id = job_id
        self.spec = spec
        self.kind = kind  #: "batch" | "interactive"
        self.status = "queued"  #: queued|running|quarantined|done|failed
        self.engine = "xla"  #: engine of the current/last attempt
        self.degraded = False  #: served by the host fallback
        self.max_seconds = max_seconds
        self.max_states = max_states
        self.chaos = chaos or {}
        self.attempts: List[Dict[str, Any]] = []
        self.wedges = 0
        self.requeues = 0
        self.consumed_s = 0.0
        self.requeue_at = 0.0  #: monotonic; quarantine release time
        self.resumed_from: Optional[str] = None  #: last attempt's resume
        self.lint: Optional[Dict[str, Any]] = None  #: admission flight-check
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        self.created_unix_ts = time.time()
        self.checker = None  #: interactive jobs only
        self.dir: Optional[str] = None
        self._proc = None  #: live worker Popen (close-with-kill path)

    # -- paths -------------------------------------------------------------

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    @property
    def checkpoint_path(self) -> str:
        return self._path("ck.npz")

    @property
    def trace_path(self) -> str:
        return self._path("trace.jsonl")

    # -- surface -----------------------------------------------------------

    @property
    def done(self) -> bool:
        return self.status in ("done", "failed")

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Blocks until the job reaches a terminal state; returns whether
        it did within ``timeout``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._service._cond:
            while not self.done:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._service._cond.wait(timeout=remaining)
        return True

    def snapshot(self) -> Dict[str, Any]:
        """The per-job status record (pool ``metrics()["jobs"]`` entry)."""
        out = {
            "id": self.id,
            "kind": self.kind,
            "spec": self.spec,
            "status": self.status,
            "engine": self.engine,
            "degraded": self.degraded,
            "wedges": self.wedges,
            "requeues": self.requeues,
            "attempts": len(self.attempts),
            "resumed_from": self.resumed_from,
            "lint": self.lint,
            "error": self.error,
        }
        if self.result is not None:
            out["result"] = {
                k: self.result.get(k)
                for k in ("generated", "unique", "max_depth", "seconds")
            }
        return out

    def metrics(self) -> Optional[Dict[str, Any]]:
        """The per-job engine snapshot: a finished batch job's recorded
        ``metrics()``, or a live poll of an interactive checker."""
        if self.checker is not None:
            return self.checker.metrics()
        if self.result is not None:
            return self.result.get("metrics")
        return None


class CheckerService:
    """The device's owner: N concurrent checking jobs behind admission
    control, per-job supervision, and a degradation breaker. Construction
    is cheap (no threads, no dirs) — the scheduler thread starts on the
    first :meth:`submit`, the prober when the breaker opens."""

    def __init__(self, config: Optional[ServiceConfig] = None, **overrides):
        if config is not None and overrides:
            raise TypeError(
                "pass either a ServiceConfig or keyword overrides, not both "
                f"(got config and {sorted(overrides)})"
            )
        self._cfg = config or ServiceConfig(**overrides)
        if self._cfg.compile_cache is None:
            self._cfg.compile_cache = os.path.abspath(".jax_cache")
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._jobs: Dict[str, Job] = {}
        self._order: List[str] = []
        self._counters = Counters(SERVICE_COUNTERS)
        self._breaker = "closed"  #: "closed" | "open"
        self._consecutive_wedges = 0
        self._breaker_opened_unix_ts: Optional[float] = None
        self._closed = False
        self._next_id = 0
        #: Per-service admission-lint memo (spec -> verdict): a pool
        #: outlives none of the tree edits that would invalidate it, so
        #: one subprocess per distinct SHIPPED spec per service
        #: lifetime. User-family specs (STPU_FAMILIES) are never
        #: memoized — their source lives outside the tree, and a user
        #: who fixes (or breaks) their model mid-pool must get a fresh
        #: verdict, mirroring the linter's own cache bypass.
        self._lint_memo: Dict[str, Dict[str, Any]] = {}
        #: In-flight lint checks (spec -> Event): concurrent submissions
        #: of the same uncached spec wait for one subprocess instead of
        #: each paying a cold check serially on this 1-core box.
        self._lint_inflight: Dict[str, threading.Event] = {}
        self._scheduler: Optional[threading.Thread] = None
        self._prober: Optional[threading.Thread] = None
        self._session_dir: Optional[str] = None
        self.log = lambda msg: None  #: swap in print for a chatty service

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self) -> "CheckerService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def close(self, kill: bool = True, timeout: float = 10.0) -> None:
        """Stops scheduling and the prober; with ``kill`` (default), kills
        any in-flight worker process groups (their jobs read as failed).
        Every non-terminal job reaches a terminal state here — a waiter
        blocked in ``Job.wait()``/``wait_all()`` must wake to a verdict,
        never hang on a queue that will no longer be scheduled."""
        with self._cond:
            self._closed = True
            procs = [
                j._proc
                for j in self._jobs.values()
                if j._proc is not None and j._proc.poll() is None
            ]
            for j in self._jobs.values():
                # Running batch jobs are settled by their _run_job thread
                # (it re-checks _closed under the lock); interactive jobs
                # just end with the pool.
                if j.status in ("queued", "quarantined"):
                    j.status = "failed"
                    j.error = "service closed"
                    self._counters.inc("jobs_failed")
                elif j.kind == "interactive" and j.status == "running":
                    j.status = "done"
                    self._counters.inc("jobs_done")
            self._cond.notify_all()
        if kill:
            for proc in procs:
                sup._kill_group(proc)
        for t in (self._scheduler, self._prober):
            if t is not None:
                t.join(timeout=timeout)

    def _ensure_session_dir(self) -> str:
        if self._session_dir is None:
            d = os.path.join(
                self._cfg.run_dir, f"svc-{int(time.time())}-{os.getpid()}"
            )
            os.makedirs(d, exist_ok=True)
            self._session_dir = d
        return self._session_dir

    def _ensure_scheduler(self) -> None:
        if self._scheduler is None or not self._scheduler.is_alive():
            self._scheduler = threading.Thread(
                target=self._scheduler_loop, name="stpu-service-scheduler",
                daemon=True,
            )
            self._scheduler.start()

    # -- admission ---------------------------------------------------------

    def _counts(self) -> Dict[str, int]:
        c = {"queued": 0, "running": 0, "quarantined": 0, "interactive": 0,
             "done": 0, "failed": 0}
        for j in self._jobs.values():
            if j.kind == "interactive":
                if j.status == "running":
                    c["interactive"] += 1
                continue
            c[j.status] += 1
        return c

    def _retry_after(self, counts: Dict[str, int]) -> float:
        """The back-pressure estimate: jobs ahead, amortized over the
        in-flight slots at the default budget. An estimate, not a promise
        — but monotone in pool pressure, which is what a client's retry
        loop needs."""
        ahead = counts["queued"] + counts["quarantined"] + counts["running"]
        per_slot = ahead / max(self._cfg.max_inflight, 1)
        return min(
            max(10.0, per_slot * self._cfg.default_max_seconds * 0.5),
            self._cfg.max_seconds_cap,
        )

    def _budget_rejection(
        self, max_seconds: float, max_states: Optional[int]
    ) -> Optional[str]:
        """The ONE budget/caps validator: the rejection reason, or None
        when the budgets are servable. Shared by submit()'s pre-lint
        precheck and its under-lock authoritative rejection so the two
        can never drift (a drifted precheck would admit an unlinted
        job)."""
        if not 0 < max_seconds <= self._cfg.max_seconds_cap:
            return (
                f"max_seconds {max_seconds:.0f} outside the servable "
                f"range (0, {self._cfg.max_seconds_cap:.0f}]"
            )
        if (
            self._cfg.max_states_cap is not None
            and max_states is not None
            and max_states > self._cfg.max_states_cap
        ):
            return (
                f"max_states {max_states} exceeds the pool cap "
                f"{self._cfg.max_states_cap}"
            )
        return None

    def _admission_verdict(self, spec: str) -> Dict[str, Any]:
        """One spec's admission flight-check verdict (memoized per
        service): the relevant kernel-surface subset of stpu-lint run in
        a subprocess (``--admission``, docs/static-analysis.md). The
        verdict dict rides into ``Job.lint`` (and so the job snapshot
        and ``/.pool``). ``ok`` is tri-state: True/False are the
        linter's word; None means the CHECK failed (timeout, crash,
        unparseable output) — the pool fails OPEN on that (the device
        still has per-job fault isolation behind it) but records it as
        ``lint_errors`` so an operator sees a blind gate."""
        family, _ = registry.parse(spec)
        memoizable = family in registry.FAMILIES  # user families: never
        while True:
            with self._lock:
                memo = self._lint_memo.get(spec) if memoizable else None
                if memo is not None:
                    return dict(memo, cached=True)
                waiter = self._lint_inflight.get(spec)
                if waiter is None:
                    self._lint_inflight[spec] = threading.Event()
                    self._counters.inc("lint_checks")
                    break
            # Another thread is checking this spec: wait for its
            # verdict, then loop to read the memo (or run our own check
            # if it wasn't memoizable / errored).
            waiter.wait(timeout=self._cfg.lint_timeout_s + 30.0)
        argv = [sys.executable, _LINT, "--admission", spec, "--json"]
        verdict: Dict[str, Any]
        try:
            try:
                proc = subprocess.run(
                    argv,
                    timeout=self._cfg.lint_timeout_s,
                    capture_output=True,
                    text=True,
                )
                report = json.loads(proc.stdout)
                verdict = {
                    "ok": bool(report["ok"]),
                    "findings": [
                        {k: f[k] for k in ("rule", "surface", "message")}
                        for f in report["findings"]
                    ],
                    "waived": len(report["waived"]),
                    "errors": report["errors"],
                    "cached": False,
                }
            except (
                subprocess.TimeoutExpired,
                OSError,
                json.JSONDecodeError,
                KeyError,
            ) as e:
                verdict = {
                    "ok": None,
                    "findings": [],
                    "waived": 0,
                    "errors": [
                        f"admission lint failed: {type(e).__name__}: {e}"
                    ],
                    "cached": False,
                }
            with self._lock:
                if verdict["ok"] is None:
                    # A TOOLING failure is not a verdict about the spec:
                    # count it, fail open for THIS submission, but do
                    # NOT memoize — the next submission retries the
                    # check, so one transient timeout can't disable the
                    # gate for a spec for the rest of the service's
                    # life.
                    self._counters.inc("lint_errors")
                elif memoizable:
                    self._lint_memo[spec] = verdict
        finally:
            # Always release waiters, even on an unexpected error — a
            # leaked in-flight entry would spin every later submitter of
            # this spec through wait-timeout loops forever.
            with self._lock:
                waiter = self._lint_inflight.pop(spec, None)
            if waiter is not None:
                waiter.set()
        return verdict

    def submit(
        self,
        spec: str,
        *,
        max_seconds: Optional[float] = None,
        max_states: Optional[int] = None,
        chaos: Optional[Dict[str, Any]] = None,
    ) -> Job:
        """Queues one batch checking job; returns its :class:`Job` handle
        or raises :class:`AdmissionError` (queue full → carries
        ``retry_after_s``; an over-cap budget → no retry hint, shrink the
        request; an unwaived flight-check finding → no retry hint, fix
        the spec). Unknown/malformed specs raise ``ValueError`` before
        any admission accounting."""
        registry.parse(spec)  # typed spec validation, pre-admission
        with self._lock:
            # Pre-flight closed check: a closed pool must reject
            # immediately (the old contract), not after a cold lint
            # subprocess. The post-lint re-check under the lock still
            # guards the race.
            if self._closed:
                raise RuntimeError("service is closed")
        max_seconds = (
            self._cfg.default_max_seconds if max_seconds is None else max_seconds
        )
        # Budget validation BEFORE the flight-check (ONE definition —
        # the same validator rejects under the lock below): a request
        # the range checks reject anyway must not pay a cold lint
        # subprocess. Same for a full queue: the precheck is racy (the
        # authoritative check below still holds the lock), but a retry
        # loop against a saturated pool must not keep the 1-core box
        # pinned on lint subprocesses for doomed submissions.
        budget_reason = self._budget_rejection(max_seconds, max_states)
        queue_full = False
        if budget_reason is None and self._cfg.admission_lint:
            with self._lock:
                counts = self._counts()
                queue_full = (
                    counts["queued"] + counts["quarantined"]
                    >= self._cfg.max_queue
                )
        # The flight-check runs OUTSIDE the lock (a cold check is a
        # subprocess); scheduling state is only touched afterwards.
        lint = (
            self._admission_verdict(spec)
            if self._cfg.admission_lint
            and budget_reason is None
            and not queue_full
            else None
        )
        with self._cond:
            if self._closed:
                raise RuntimeError("service is closed")
            self._counters.inc("submitted")
            if lint is not None and lint["ok"] is False:
                # A typed rejection with NO retry hint: retrying the
                # same spec cannot help — the finding is in the model's
                # kernels (or its compile plan), not in pool pressure.
                self._counters.inc("rejected")
                self._counters.inc("lint_rejects")
                rules = sorted({f["rule"] for f in lint["findings"]})
                first = lint["findings"][0]["message"] if lint["findings"] else (
                    "; ".join(lint["errors"]) or "flight-check failed"
                )
                raise AdmissionError(
                    f"admission flight-check failed for {spec!r} "
                    f"({', '.join(rules) or 'trace error'}): {first}"
                )
            if budget_reason is not None:
                self._counters.inc("rejected")
                raise AdmissionError(budget_reason)
            counts = self._counts()
            if (
                counts["queued"] + counts["quarantined"] >= self._cfg.max_queue
                # The precheck saw a full queue and skipped the lint; if
                # it drained in the (subprocess-free, microsecond) gap,
                # still reject as queue-full rather than admit an
                # UNLINTED job — the client's retry gets the real
                # verdict.
                or (queue_full and lint is None and self._cfg.admission_lint)
            ):
                self._counters.inc("rejected")
                raise AdmissionError(
                    f"queue full ({self._cfg.max_queue} waiting jobs)",
                    retry_after_s=self._retry_after(counts),
                )
            self._next_id += 1
            job = Job(
                self,
                f"job-{self._next_id:04d}",
                spec,
                max_seconds=max_seconds,
                max_states=max_states,
                chaos=chaos,
            )
            job.lint = lint
            job.dir = os.path.join(self._ensure_session_dir(), job.id)
            os.makedirs(job.dir, exist_ok=True)
            self._jobs[job.id] = job
            self._order.append(job.id)
            self._counters.inc("admitted")
            self._ensure_scheduler()
            self._cond.notify_all()
        return job

    def check_session_capacity(self) -> None:
        """Raises :class:`AdmissionError` when the interactive-session cap
        is already reached. Callers building EXPENSIVE checkers (the
        Explorer's device backend allocates device-resident buffers) call
        this *before* construction so a rejected tenant never pays — the
        small pre-check-to-register window is benign (register still
        enforces the cap). A rejection here counts as submitted+rejected —
        capacity-rejected sessions must be visible in the pool telemetry,
        and ``submitted == admitted + rejected`` stays reconcilable (a
        passing pre-check counts nothing; registration does)."""
        with self._lock:
            counts = self._counts()
            if counts["interactive"] >= self._cfg.max_sessions:
                self._counters.inc("submitted")
                self._counters.inc("rejected")
                raise AdmissionError(
                    f"interactive sessions full ({self._cfg.max_sessions})",
                    retry_after_s=self._retry_after(counts),
                )

    def register_interactive(self, checker, *, label: Optional[str] = None,
                             degraded: bool = False) -> Job:
        """Admits a live in-process checker (the Explorer's) as a pool job
        of kind ``"interactive"`` — counted, capped (``max_sessions``),
        and visible in the pool gauges like any other tenant."""
        with self._cond:
            if self._closed:
                raise RuntimeError("service is closed")
            self._counters.inc("submitted")
            counts = self._counts()
            if counts["interactive"] >= self._cfg.max_sessions:
                self._counters.inc("rejected")
                raise AdmissionError(
                    f"interactive sessions full ({self._cfg.max_sessions})",
                    retry_after_s=self._retry_after(counts),
                )
            self._next_id += 1
            job = Job(
                self,
                f"job-{self._next_id:04d}",
                label or type(checker.model()).__name__,
                kind="interactive",
            )
            job.status = "running"
            job.engine = "host" if degraded else "xla"
            job.degraded = degraded
            job.checker = checker
            if degraded:
                self._counters.inc("degraded_jobs")
            self._jobs[job.id] = job
            self._order.append(job.id)
            self._counters.inc("admitted")
            self._cond.notify_all()
        checker.attach_job(job.id)
        return job

    def release_interactive(self, job: Job) -> None:
        with self._cond:
            if job.status == "running":
                job.status = "done"
                self._counters.inc("jobs_done")
            self._cond.notify_all()

    # -- scheduling --------------------------------------------------------

    def _scheduler_loop(self) -> None:
        while True:
            to_start: List[Job] = []
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                counts = self._counts()
                slots = self._cfg.max_inflight - counts["running"]
                quarantine_release = None
                if slots > 0:
                    for jid in self._order:
                        job = self._jobs[jid]
                        if job.kind != "batch":
                            continue
                        if job.status == "quarantined" and job.requeue_at > now:
                            quarantine_release = (
                                job.requeue_at
                                if quarantine_release is None
                                else min(quarantine_release, job.requeue_at)
                            )
                            continue
                        if job.status in ("queued", "quarantined"):
                            job.status = "running"
                            to_start.append(job)
                            slots -= 1
                            if slots == 0:
                                break
                if not to_start:
                    # Event-driven idle: submit/requeue/close all notify.
                    # A timed wait is only needed to release a quarantine
                    # backoff (or re-poll a full pool) — an idle pool
                    # sleeps on the condition instead of polling at 5 Hz
                    # on this one-core box.
                    if quarantine_release is not None:
                        self._cond.wait(
                            timeout=max(quarantine_release - now, 0.05)
                        )
                    else:
                        # Idle or full pool: every relevant transition
                        # (submit, requeue, job settlement, close)
                        # notifies, so an untimed wait suffices.
                        self._cond.wait()
            for job in to_start:
                threading.Thread(
                    target=self._run_job, args=(job,),
                    name=f"stpu-service-{job.id}", daemon=True,
                ).start()

    def _worker_env(self, job: Job, device: bool) -> Dict[str, str]:
        env = dict(os.environ)
        # Scrub inherited run-trace/recovery env: per-job artifacts must
        # never alias an outer run's files.
        for key in (
            "STPU_TRACE", "STPU_TRACE_CHROME", "STPU_HEARTBEAT",
            "STPU_CHECKPOINT_TO", "STPU_CHECKPOINT_EVERY",
            "STPU_CHECKPOINT_KEEP",
        ):
            env.pop(key, None)
        if device:
            env["STPU_TRACE"] = job.trace_path
        env["STPU_COMPILE_CACHE"] = self._cfg.compile_cache
        return env

    def _run_job(self, job: Job) -> None:
        """One supervised attempt of ``job``; classification + requeue
        decisions happen under the lock afterwards. Any unexpected
        exception settles the job as failed — a job stuck in "running"
        with no thread behind it would consume a ``max_inflight`` slot
        forever and hang its waiters."""
        try:
            self._run_job_inner(job)
        except Exception as e:  # noqa: BLE001 - the verdict IS the handling
            with self._cond:
                job._proc = None
                job.status = "failed"
                job.error = f"supervisor error: {type(e).__name__}: {e}"
                self._counters.inc("jobs_failed")
                self._cond.notify_all()

    def _run_job_inner(self, job: Job) -> None:
        cfg = self._cfg
        attempt = len(job.attempts)
        device = self._breaker == "closed"
        engine = "xla" if device else "host"
        remaining = job.max_seconds - job.consumed_s
        if remaining <= 0:
            with self._cond:
                job.status = "failed"
                job.error = "wall-clock budget exhausted"
                self._counters.inc("jobs_failed")
                self._cond.notify_all()
            return
        resume = (
            latest_valid_checkpoint(job.checkpoint_path) if device else None
        )
        argv = [
            sys.executable, _WORKER,
            "--spec", job.spec,
            "--engine", engine,
            "--platform", cfg.platform if device else "cpu",
            "--out", job._path("result.json"),
            "--block-size", str(cfg.block_size),
            "--max-seconds", str(remaining),
        ]
        if device:
            argv += [
                "--checkpoint", job.checkpoint_path,
                "--every", str(cfg.checkpoint_every),
                "--keep", str(cfg.checkpoint_keep),
            ]
            if resume:
                argv += ["--resume", resume]
        if job.max_states:
            argv += ["--max-states", str(job.max_states)]
        for flag, key in (
            ("--chaos-die-at-depth", "die_at_depth"),
            ("--chaos-freeze-at-depth", "freeze_at_depth"),
            ("--chaos-marker", "marker"),
        ):
            if job.chaos.get(key) is not None:
                argv += [flag, str(job.chaos[key])]

        def on_spawn(proc):
            # close() snapshots live procs under the lock; a worker that
            # spawns in the close race is killed HERE instead of running
            # unsupervised for its whole budget after the pool is gone.
            with self._cond:
                job._proc = proc
                closed = self._closed
            if closed:
                sup._kill_group(proc)

        with self._cond:
            if self._closed:
                job.status = "failed"
                job.error = "service closed"
                self._counters.inc("jobs_failed")
                self._cond.notify_all()
                return
            job.engine = engine
            job.resumed_from = resume
            if not device:
                job.degraded = True
        self.log(f"{job.id} attempt {attempt} engine={engine} resume={resume}")
        res = sup.run_worker(
            argv,
            heartbeat=job._path("hb.json") if device else None,
            # Verdict ordering contract: the worker's soft budget exit
            # (rc 3) fires first; a wedge that starts ANY time inside the
            # budget draws its heartbeat-staleness verdict (<= stall_s x
            # the 3x compile leash after onset) before the hard timeout,
            # which only backstops a worker that can neither reach a
            # quiescent point nor be diagnosed by heartbeat. Without the
            # stall headroom here, a production-default pool (600s budget,
            # 1200s stall) would misread every wedge as budget exhaustion
            # — no requeue, no breaker evidence.
            timeout_s=remaining * 1.5 + 60.0 + cfg.stall_s * 3.0,
            stall_s=cfg.stall_s,
            startup_grace_s=cfg.startup_grace_s,
            poll_s=cfg.poll_s,
            env=self._worker_env(job, device),
            stdout_path=job._path(f"worker{attempt}.out"),
            log=self.log,
            on_spawn=on_spawn,
        )
        result = None
        if res.ok:
            try:
                with open(job._path("result.json")) as fh:
                    result = json.load(fh)
            except (OSError, json.JSONDecodeError):
                result = None
        with self._cond:
            job._proc = None
            # Wedge time is the DEVICE's fault, not the tenant's demand:
            # charging it would make the requeued attempt start with a
            # drained budget and fail as "budget exhausted" instead of
            # resuming. Crashes still charge — the compute was real and
            # checkpointed.
            if not res.wedged:
                job.consumed_s += res.seconds
            job.attempts.append(
                {
                    "rc": res.rc,
                    "killed": res.killed,
                    "seconds": res.seconds,
                    "engine": engine,
                    "wedged": res.wedged,
                    "resumed_from": resume,
                }
            )
            if self._closed:
                job.status = "failed"
                job.error = "service closed"
                self._counters.inc("jobs_failed")
                self._cond.notify_all()
                return
            if result is not None:
                job.status = "done"
                job.result = result
                if result.get("degraded"):
                    job.degraded = True
                    self._counters.inc("degraded_jobs")
                self._counters.inc("jobs_done")
                if device:
                    self._consecutive_wedges = 0
            elif res.wedged:
                self._counters.inc("wedge_verdicts")
                job.wedges += 1
                self._record_wedge()
                self._requeue_or_fail(job, f"wedge verdict: {res.killed}")
            elif res.crashed:
                self._counters.inc("crashes")
                self._requeue_or_fail(
                    job, f"worker died by signal (rc={res.rc})"
                )
            elif res.killed is not None or res.rc == 3:
                job.status = "failed"
                job.error = "wall-clock budget exhausted"
                self._counters.inc("jobs_failed")
            else:
                job.status = "failed"
                job.error = f"worker exited rc={res.rc}"
                self._counters.inc("jobs_failed")
            self._cond.notify_all()

    def _requeue_or_fail(self, job: Job, reason: str) -> None:
        """Quarantine-and-requeue with exponential backoff, up to the
        requeue limit. Caller holds the lock."""
        if job.requeues < self._cfg.requeue_limit:
            job.requeues += 1
            self._counters.inc("requeues")
            job.status = "quarantined"
            job.requeue_at = time.monotonic() + sup.backoff_delay(
                job.requeues, self._cfg.backoff_s
            )
            self.log(f"{job.id} quarantined ({reason})")
        else:
            job.status = "failed"
            job.error = f"{reason}; requeue limit reached"
            self._counters.inc("jobs_failed")

    # -- breaker -----------------------------------------------------------

    def _record_wedge(self) -> None:
        """Caller holds the lock."""
        self._consecutive_wedges += 1
        if (
            self._breaker == "closed"
            and self._consecutive_wedges >= self._cfg.breaker_k
        ):
            self._breaker = "open"
            self._breaker_opened_unix_ts = time.time()
            self._counters.inc("breaker_trips")
            self.log(
                f"breaker OPEN after {self._consecutive_wedges} consecutive "
                "wedge verdicts; routing jobs to the host engine"
            )
            if self._cfg.probe_auto:
                self._prober = threading.Thread(
                    target=self._probe_loop, name="stpu-service-prober",
                    daemon=True,
                )
                self._prober.start()

    @property
    def degraded(self) -> bool:
        """Whether the breaker is open (new work routes to the host
        engine)."""
        return self._breaker == "open"

    def probe_device_now(self) -> bool:
        """One device-liveness probe (a watchdogged subprocess — the
        service process never touches jax); on success while the breaker
        is open, closes it. The background prober calls this on
        ``probe_interval_s``; tests and operators call it directly."""
        argv = list(
            self._cfg.probe_argv
            or [sys.executable, "-c", "import jax; jax.devices()"]
        )
        with self._lock:  # Counters.inc is not atomic; every mutation locks
            self._counters.inc("device_probes")
        try:
            rc = subprocess.run(
                argv,
                timeout=self._cfg.probe_timeout_s,
                capture_output=True,
            ).returncode
        except (subprocess.TimeoutExpired, OSError):
            rc = None
        ok = rc == 0
        with self._cond:
            if ok and self._breaker == "open":
                self._breaker = "closed"
                self._breaker_opened_unix_ts = None
                self._consecutive_wedges = 0
                self._counters.inc("breaker_closes")
                self.log("breaker CLOSED (device probe healthy)")
                self._cond.notify_all()
        return ok

    def _probe_loop(self) -> None:
        while True:
            deadline = time.monotonic() + self._cfg.probe_interval_s
            with self._cond:
                while not self._closed and time.monotonic() < deadline:
                    if self._breaker == "closed":
                        return
                    self._cond.wait(timeout=min(
                        1.0, deadline - time.monotonic()
                    ))
                if self._closed or self._breaker == "closed":
                    return
            self.probe_device_now()

    # -- status surface ----------------------------------------------------

    def job(self, job_id: str) -> Job:
        return self._jobs[job_id]

    def jobs(self) -> List[Job]:
        with self._lock:
            return [self._jobs[jid] for jid in self._order]

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Blocks until every batch job is terminal."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while any(
                not j.done for j in self._jobs.values() if j.kind == "batch"
            ):
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def gauges(self) -> Dict[str, Any]:
        """The pool-wide snapshot without per-job payloads — what the
        Explorer embeds under ``/.status``'s ``"pool"`` key."""
        with self._lock:
            counts = self._counts()
            return {
                **counts,
                "max_inflight": self._cfg.max_inflight,
                "max_queue": self._cfg.max_queue,
                "max_sessions": self._cfg.max_sessions,
                "breaker": {
                    "state": self._breaker,
                    "consecutive_wedges": self._consecutive_wedges,
                    "k": self._cfg.breaker_k,
                    "opened_unix_ts": self._breaker_opened_unix_ts,
                },
                **self._counters.snapshot(),
            }

    def metrics(self) -> Dict[str, Any]:
        """Pool gauges plus per-job status snapshots (the full service
        status surface; per-job engine metrics via ``Job.metrics()``)."""
        out = self.gauges()
        with self._lock:
            out["jobs"] = {
                jid: self._jobs[jid].snapshot() for jid in self._order
            }
        return out

    def job_trace_chrome(self, job_id: str,
                         out_path: Optional[str] = None) -> Optional[str]:
        """Exports a job's span trace as Perfetto-loadable Chrome trace
        JSON (``obs.export_chrome``); returns the output path, or None when
        the job never produced a trace (host-engine jobs don't)."""
        job = self._jobs[job_id]
        if job.dir is None or not os.path.exists(job.trace_path):
            return None
        dst = out_path or job._path("trace.chrome.json")
        try:
            fresh = os.stat(dst).st_mtime >= os.stat(job.trace_path).st_mtime
        except OSError:
            fresh = False
        if not fresh:
            # Re-export only when the append-only source advanced — a
            # polled trace endpoint must not re-parse the whole JSONL per
            # request.
            export_chrome(job.trace_path, dst)
        return dst
