"""CheckerService: fault-isolated multi-tenant checking on one device.

ROADMAP item 3's production framing ("millions of users": one chip, many
concurrent interactive sessions and batch jobs) composed from the recovery
primitives PR 3 built for *one* run (``supervise.run_worker`` heartbeat
verdicts, atomic rotating checkpoints) into a pool where faults are
isolated per job and the pool degrades instead of dying:

- **Admission control** — bounded in-flight jobs and a bounded queue;
  beyond either, :meth:`CheckerService.submit` raises the typed
  :class:`AdmissionError` carrying ``retry_after_s`` (the ``Retry-After``
  value an HTTP front end would send) instead of queueing unboundedly.
  Per-job budgets: wall-clock (``max_seconds``, soft-checked in the worker
  at quiescent points, hard-backstopped by the supervisor) and state count
  (``max_states`` via ``target_state_count``), both clamped by pool caps.
- **Per-job fault isolation** — every device job runs
  ``service/worker.py`` in its own process group under
  ``supervise.run_worker`` with its *own* heartbeat, span trace, and
  auto-checkpoint rotation set under the service's run dir. A wedge
  verdict (heartbeat stale mid-dispatch — the tunnel signature) kills
  exactly that job's group, **quarantines** the job for an exponential
  backoff, and requeues it resuming from its latest valid checkpoint
  rotation; sibling jobs never see it. A worker that dies by signal
  (crash) requeues the same way but is not evidence against the device.
- **Graceful degradation** — ``breaker_k`` *consecutive* device wedge
  verdicts (any job) trip a breaker: new and requeued jobs route to the
  host on-demand engine (``checker/on_demand.py``) on the CPU backend with
  ``degraded: true`` in their status — slower, but no tunnel to wedge. A
  background prober (a watchdogged subprocess, so the service process
  itself never touches jax) re-probes the device and closes the breaker.
- **Status surface** — :meth:`metrics` snapshots pool gauges
  (queued/running/quarantined/interactive, breaker state, wedge/requeue
  counters through the obs registry) plus per-job summaries; each job's
  span trace exports as a Perfetto-loadable Chrome trace via
  :meth:`job_trace_chrome` (reusing ``obs.export_chrome``). The Explorer
  is one client: ``make_app``/``serve`` register their interactive checker
  as a pool job and embed the gauges in ``/.status``.
- **Durability** (``service/journal.py``; docs/service.md "Durability &
  recovery") — every batch-job transition appends a typed, self-verifying
  record to ``<run_dir>/journal.jsonl``. Constructing a service over a
  run dir that already has a journal REPLAYS it: journal-complete jobs
  restore done/failed without re-running, in-flight and queued jobs
  requeue (wall-clock already spent is charged; each re-adopts its
  latest valid checkpoint rotation through the normal resume path, and
  any orphaned worker the dead incarnation left running is killed by its
  journaled pid first), breaker/quarantine state restores (an open
  breaker re-probes immediately), and ``submit(idempotency_key=...)``
  dedupes client resubmissions across the restart — so a supervisor can
  wrap the service *itself* in ``supervise.supervise()`` exactly like a
  worker: kill -9 at any instant, restart into the same job set.

Like the supervisor it builds on, importing this module never imports jax
— the service process stays wedge-proof; only workers and the prober (both
subprocesses) touch a backend. Fault injection for every recovery path
here is the deterministic chaos layer (``stateright_tpu/chaos.py``,
``STPU_CHAOS`` / ``ServiceConfig(chaos=)``); ``tools/service_chaos.py``
drives seeded kill/restart schedules against one pool and asserts the
exactly-once invariant.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from .. import chaos as chaos_mod
from .. import supervise as sup
from ..checkpoint import latest_valid_checkpoint
from ..obs import (
    NULL_TRACER,
    Counters,
    export_chrome,
    new_trace_id,
    resolve_tracer,
)
from . import registry
from .journal import Journal, read_journal

#: Pre-seeded pool counters (stable ``metrics()`` key set, like the
#: engines' ENGINE_COUNTERS; docs/service.md).
SERVICE_COUNTERS = (
    "submitted",
    "admitted",
    "rejected",
    "jobs_done",
    "jobs_failed",
    "wedge_verdicts",
    "crashes",
    "requeues",
    "breaker_trips",
    "breaker_closes",
    "degraded_jobs",
    "device_probes",
    "lint_checks",
    "lint_rejects",
    "lint_errors",
    "idem_dedups",
    "jobs_recovered",
    "orphans_killed",
    "artifacts_swept",
    "jobs_evacuated",
    "mux_groups",
    "mux_lanes",
    "mux_dispatches_saved",
    "sheds",
    "quota_rejects",
    "aged_picks",
    "warm_compiles",
)

#: Priority classes, highest first (docs/service.md "QoS & overload").
#: ``interactive`` here is a *batch-job* urgency class (latency-sensitive
#: checking requests), distinct from ``Job.kind == "interactive"`` (live
#: Explorer sessions, which bypass the batch queue entirely).
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")

#: Default fair-share weights: an interactive job earns device slots at
#: 4x a best-effort job's rate, batch at 2x. Override per pool with
#: ``ServiceConfig(class_weights=)``.
DEFAULT_CLASS_WEIGHTS = {"interactive": 4.0, "batch": 2.0, "best_effort": 1.0}

#: Default overload-shedding thresholds: the fraction of ``max_queue``
#: occupancy above which a class is shed at admission. Best-effort sheds
#: at half-full, batch at three-quarters, interactive only at the hard
#: queue cap — graceful degradation drops the least-important work first.
DEFAULT_SHED_THRESHOLDS = {
    "interactive": 1.0,
    "batch": 0.75,
    "best_effort": 0.5,
}

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "worker.py")
#: The admission flight-check entry point (stpu-lint's --admission mode;
#: docs/static-analysis.md). A subprocess, like every other jax touch —
#: the service process stays import-clean of jax even while it VERIFIES
#: jax programs.
_LINT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tools",
    "stpu_lint.py",
)
#: Compile-on-admit cache warmer (tools/warm_cache.py): a user family's
#: first admission pre-banks its (bucket, rung) compile-plan shapes into
#: the shared .jax_cache in a background subprocess, so the tenant's
#: first real job never pays cold XLA compiles inside its budget.
_WARM = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tools",
    "warm_cache.py",
)


class AdmissionError(Exception):
    """Typed admission rejection. ``retry_after_s`` is the back-pressure
    hint (an HTTP front end's ``Retry-After``); None when retrying cannot
    help (a budget above the pool cap)."""

    def __init__(self, reason: str, retry_after_s: Optional[float] = None):
        msg = reason
        if retry_after_s is not None:
            msg += f" (retry after ~{retry_after_s:.0f}s)"
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclass
class ServiceConfig:
    """Pool knobs; everything has a production-shaped default and the chaos
    tests shrink the time constants."""

    run_dir: str = os.path.join("runs", "service")
    # -- admission ---------------------------------------------------------
    max_inflight: int = 2  #: concurrently running batch jobs
    max_queue: int = 8  #: queued + quarantined jobs beyond the running set
    max_sessions: int = 4  #: interactive (Explorer) clients
    default_max_seconds: float = 600.0
    max_seconds_cap: float = 3600.0
    max_states_cap: Optional[int] = None
    block_size: int = 1500  #: host-engine block granularity (on_demand.py)
    # -- supervision (supervise.run_worker) --------------------------------
    stall_s: float = 1200.0
    startup_grace_s: float = 900.0
    poll_s: float = 0.5
    requeue_limit: int = 2  #: wedge/crash requeues per job before it fails
    backoff_s: float = 5.0  #: quarantine backoff base (exponential)
    # -- breaker -----------------------------------------------------------
    breaker_k: int = 3  #: consecutive wedge verdicts that trip it
    probe_auto: bool = True  #: background re-probe while open
    probe_interval_s: float = 60.0
    probe_timeout_s: float = 45.0
    #: Device-liveness probe command (rc 0 = device healthy). The default
    #: pays full plugin init in a throwaway subprocess, exactly like
    #: ``backend.ensure_live_backend``'s probe.
    probe_argv: Optional[Sequence[str]] = None
    # -- admission flight-check (stpu-lint --admission) --------------------
    #: Statically lint a spec's kernel surfaces (STPU001/002/003), its
    #: cross-backend lowering diff (STPU008), and its compile plan
    #: (STPU007) before the pool schedules it on the device — the gate
    #: user-submitted specs (STPU_FAMILIES) pass through. Runs as a
    #: subprocess (the service never imports jax) and is double-cached:
    #: the linter's content-hash surface cache makes shipped specs cost
    #: one jax import (~2 s), and a per-service memo makes repeat
    #: submissions of the same spec free.
    admission_lint: bool = True
    lint_timeout_s: float = 240.0
    # -- QoS & overload (docs/service.md "QoS & overload") -----------------
    #: Per-class fair-share weights (class -> weight); keys beyond the
    #: defaults are merged over ``DEFAULT_CLASS_WEIGHTS`` at
    #: construction. A class's share of device slots under contention is
    #: weight / sum(weights of backlogged classes).
    class_weights: Optional[Dict[str, float]] = None
    #: The aging time constant: a queued job's effective priority
    #: ``w_class + waited_s / qos_aging_s`` rises monotonically, and the
    #: job jumps the fair-share queue entirely ("aged") once
    #: ``waited_s >= qos_aging_s * (w_max + 1 - w_class)`` — THE
    #: documented starvation bound (defaults: best_effort 2400 s,
    #: batch 1800 s).
    qos_aging_s: float = 600.0
    #: Per-class shed thresholds (fraction of ``max_queue`` occupancy
    #: above which the class is rejected at admission); merged over
    #: ``DEFAULT_SHED_THRESHOLDS``.
    shed_thresholds: Optional[Dict[str, float]] = None
    #: Per-tenant quotas, enforced at admission (queued) and scheduling
    #: (in-flight): defaults for every tenant, overridable per tenant id
    #: via ``tenant_quotas={"t1": {"max_queued": 2, ...}}``. None = no
    #: limit.
    tenant_max_queued: Optional[int] = None
    tenant_max_inflight: Optional[int] = None
    #: Device-seconds budget per tenant: a submission whose requested
    #: ``max_seconds`` would push the tenant's lifetime charged + asked
    #: wall-clock over this rejects typed (``quota_rejects``).
    tenant_budget_s: Optional[float] = None
    tenant_quotas: Optional[Dict[str, Dict[str, Any]]] = None
    #: Completion-rate window for the measured drain rate behind
    #: ``Retry-After`` hints (docs/service.md "QoS & overload").
    drain_window_s: float = 300.0
    #: Compile-on-admit: warm a user family's (STPU_FAMILIES) compile
    #: plan into .jax_cache via tools/warm_cache.py in a background
    #: subprocess on its first admission (counter ``warm_compiles``).
    warm_user_families: bool = True
    # -- workers -----------------------------------------------------------
    platform: str = "default"  #: "default" (accelerator) | "cpu" (tests)
    compile_cache: Optional[str] = None  #: default: <cwd>/.jax_cache
    checkpoint_every: Any = 1  #: per-job auto-checkpoint cadence
    checkpoint_keep: int = 3
    # -- durability (service/journal.py; docs/service.md) ------------------
    #: Append every batch-job transition to <run_dir>/journal.jsonl and
    #: REPLAY it when constructed over a run dir that already has one —
    #: the queue, budgets, breaker, and checkpoint pointers survive a
    #: service kill -9. Off = the pre-durability in-memory pool.
    journal: bool = True
    journal_compact_every: int = 256  #: appends between snapshot compactions
    journal_keep: int = 3  #: journal rotations retained by compaction
    #: Seconds a journal-complete job's run-dir artifacts (heartbeat,
    #: trace, checkpoint rotations, worker stdout) are retained before
    #: the sweep deletes its job dir (gauge: ``artifacts_swept``); None
    #: disables sweeping.
    artifact_retention_s: Optional[float] = 7 * 24 * 3600.0
    # -- fault injection (stateright_tpu/chaos.py) -------------------------
    #: A chaos spec installed process-wide at construction and exported
    #: to worker environments as STPU_CHAOS — the deterministic fault
    #: layer the chaos/restart drills script (None: inherit env, which
    #: is a no-op when STPU_CHAOS is unset).
    chaos: Optional[str] = None
    # -- fleet membership (service/fleet.py; docs/service.md "Fleet") ------
    #: Device label this pool serves ("dev0"...). Rides every job
    #: snapshot (and so /.pool and the dashboard's per-device rows);
    #: None = the single-device pool's legacy surface.
    device: Optional[str] = None
    #: Device ordinal passed to workers as ``--device`` (worker.py pins
    #: ``jax_default_device`` to ``jax.devices()[ordinal]``); None = the
    #: backend default. On the 8-device virtual CPU mesh this is how a
    #: fleet's pools land on distinct virtual devices.
    device_ordinal: Optional[int] = None
    #: Open-breaker policy. "host" (default, the single-pool contract):
    #: jobs route to the host on-demand engine with ``degraded: true``.
    #: "halt" (fleet pools): queued jobs HOLD while the breaker is open —
    #: the FleetService migrates them to a healthy sibling device instead,
    #: and only jobs force-submitted with ``engine="host"`` run (the
    #: fleet's every-device-open last resort).
    breaker_mode: str = "host"
    #: Optional callable(state) notified (from a fresh thread, never under
    #: the pool lock) when the breaker trips ("open") or closes
    #: ("closed") — the fleet's migration trigger.
    breaker_listener: Optional[Any] = None
    #: TTL for Job.snapshot()'s memoized artifact-mtime ages: a 100-job
    #: /.pool render (or a dashboard polling several endpoints in one
    #: tick) does ONE stat per artifact per tick instead of one per
    #: render.
    snapshot_age_ttl_s: float = 1.0
    # -- batched scheduling (stateright_tpu/xla_mux.py; docs/service.md
    # -- "Batched scheduling") --------------------------------------------
    #: Multiplex up to K queued same-spec batch jobs into ONE
    #: ``worker.py --mux`` invocation (per-lane journal events, budgets,
    #: checkpoints, and metrics preserved; a mux worker fault requeues
    #: its members individually, solo). 1 = off. None = the ``STPU_MUX``
    #: env knob (default 1). Only families in ``registry.MUX_FAMILIES``
    #: group; everything else keeps the solo path.
    mux_k: Optional[int] = None
    # -- distributed tracing (docs/observability.md) -----------------------
    #: Service-side span trace: ``True`` appends the pool's own spans
    #: (``submit``/``attempt``) to ``<run_dir>/trace.jsonl``, a path
    #: appends there; ``None`` defers to the ``STPU_SERVICE_TRACE`` env
    #: knob ("1" = the run-dir default, else a path; unset = off).
    #: Every submission mints (and journals) a ``trace_id`` regardless —
    #: tracing off only skips the span writes, never the propagation.
    trace: Any = None


class Job:
    """One pool entry. Batch jobs own a job dir (checkpoints, heartbeat,
    trace, worker stdout); interactive jobs wrap a live in-process checker.
    All mutation happens under the service lock."""

    def __init__(
        self,
        service: "CheckerService",
        job_id: str,
        spec: str,
        *,
        kind: str = "batch",
        max_seconds: float = 600.0,
        max_states: Optional[int] = None,
        chaos: Optional[Dict[str, Any]] = None,
        idempotency_key: Optional[str] = None,
        tenant: str = "default",
        priority: str = "batch",
        deadline_s: Optional[float] = None,
        symmetry: Optional[str] = None,
    ):
        self._service = service
        self.id = job_id
        self.spec = spec
        self.kind = kind  #: "batch" | "interactive"
        self.idempotency_key = idempotency_key
        #: QoS identity (docs/service.md "QoS & overload"): the
        #: submitting tenant, the priority class (PRIORITY_CLASSES), and
        #: an optional soft deadline — EDF orders same-class picks by
        #: ``created_unix_ts + deadline_s``. All three ride the journal's
        #: ``submitted`` record so a restart replays scheduler state.
        self.tenant = tenant
        self.priority = priority
        self.deadline_s = deadline_s
        #: Per-job symmetry-reduction mode (docs/symmetry.md): None
        #: inherits the pool's environment (STPU_SYMMETRY), "on"/"off"/
        #: "auto" override it for this job's workers. Journaled on
        #: ``submitted`` so replay and migration keep the mode — a
        #: resumed attempt under a different mode would fail the
        #: checkpoint's symmetry-identity check (checkpoint.py).
        self.symmetry = symmetry
        #: queued|running|quarantined|done|failed|migrated — "migrated" is
        #: terminal FOR THIS POOL: the fleet evacuated the job to a
        #: sibling device (service/fleet.py), which owns it from then on.
        self.status = "queued"
        self.engine = "xla"  #: engine of the current/last attempt
        self.engine_force: Optional[str] = None  #: "host" = fleet last resort
        #: A sibling pool's checkpoint rotation to resume from when this
        #: job has no checkpoint of its own yet (migration seed).
        self.seed_checkpoint: Optional[str] = None
        self.degraded = False  #: served by the host fallback
        self.max_seconds = max_seconds
        self.max_states = max_states
        self.chaos = chaos or {}
        self.attempts: List[Dict[str, Any]] = []
        self.wedges = 0
        self.requeues = 0
        self.consumed_s = 0.0
        self.requeue_at = 0.0  #: monotonic; quarantine release time
        self.resumed_from: Optional[str] = None  #: last attempt's resume
        self.lint: Optional[Dict[str, Any]] = None  #: admission flight-check
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        self.created_unix_ts = time.time()
        self.completed_unix_ts: Optional[float] = None
        self.recovered = False  #: restored from a journal replay
        #: The submission's distributed-trace id (docs/observability.md
        #: "Distributed tracing") — minted at submit, journaled, carried
        #: across requeues/restarts/migrations so every attempt's spans
        #: stitch into one trace.
        self.trace_id: Optional[str] = None
        #: The root (submit) span's id — the attempt spans' parent.
        #: None on replayed jobs (their attempts re-root at the trace).
        self._root_sid: Optional[str] = None
        self.swept = False  #: run-dir artifacts removed by the retention sweep
        self.checker = None  #: interactive jobs only
        self.dir: Optional[str] = None
        #: Live/last mux-group membership ({"group", "lanes", "lane"}):
        #: rides snapshot() so /.pool and the dashboard attribute a
        #: member's rates to its lane, never to the whole batch.
        self.mux: Optional[Dict[str, Any]] = None
        #: The group heartbeat path while a mux attempt runs — the
        #: snapshot() liveness readout for members (one heartbeat serves
        #: the whole batch; cleared at settlement so a later solo attempt
        #: reads its own hb.json again).
        self._mux_hb: Optional[str] = None
        #: A failed mux attempt pins its unfinished members solo: the
        #: requeued attempt must not regroup into the same faulty batch.
        self._mux_solo = False
        self._proc = None  #: live worker Popen (close-with-kill path)
        self._attempt_t0: Optional[float] = None  #: monotonic; live attempt
        #: path -> (age, read_at_monotonic): the snapshot() mtime memo
        #: (snapshot_age_ttl_s).
        self._age_cache: Dict[str, Any] = {}

    # -- paths -------------------------------------------------------------

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    @property
    def checkpoint_path(self) -> str:
        return self._path("ck.npz")

    @property
    def trace_path(self) -> str:
        return self._path("trace.jsonl")

    @property
    def metrics_path(self) -> str:
        return self._path("metrics.jsonl")

    # -- surface -----------------------------------------------------------

    @property
    def done(self) -> bool:
        return self.status in ("done", "failed", "migrated")

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Blocks until the job reaches a terminal state; returns whether
        it did within ``timeout``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._service._cond:
            while not self.done:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._service._cond.wait(timeout=remaining)
        return True

    def _cached_age(self, path: str) -> Optional[float]:
        """``_mtime_age`` behind a ``snapshot_age_ttl_s`` memo: a 100-job
        ``/.pool`` render (or several dashboard endpoints polled in one
        tick) stats each artifact once per tick, not once per render."""
        ttl = self._service._cfg.snapshot_age_ttl_s
        now = time.monotonic()
        hit = self._age_cache.get(path)
        if hit is not None and now - hit[1] < ttl:
            age = hit[0]
            # The cached value drifts within the TTL; advance it so a
            # frozen heartbeat still reads as aging between stats.
            return None if age is None else round(age + (now - hit[1]), 3)
        age = _mtime_age(path)
        self._age_cache[path] = (age, now)
        return age

    def snapshot(self) -> Dict[str, Any]:
        """The per-job status record (pool ``metrics()["jobs"]`` entry)."""
        out = {
            "id": self.id,
            "kind": self.kind,
            "spec": self.spec,
            "status": self.status,
            "engine": self.engine,
            "degraded": self.degraded,
            "tenant": self.tenant,
            "priority": self.priority,
            "deadline_s": self.deadline_s,
            "symmetry": self.symmetry,
            # The device this pool serves (fleet pools; None on the
            # single-device pool) — the dashboard's per-device grouping.
            "device": self._service._cfg.device,
            "wedges": self.wedges,
            "requeues": self.requeues,
            "attempts": len(self.attempts),
            "resumed_from": self.resumed_from,
            "lint": self.lint,
            "error": self.error,
            "recovered": self.recovered,
            "trace_id": self.trace_id,
            # Liveness/recovery ages, host-side from file mtimes (the
            # dashboard's per-job staleness + checkpoint-age readouts;
            # docs/observability.md "Dashboard"): None when the artifact
            # does not exist (host-engine jobs, swept dirs, heartbeat off).
            # Memoized per poll tick (snapshot_age_ttl_s).
            # A mux member's liveness is the GROUP heartbeat (one worker
            # beats for the whole batch) while its attempt runs.
            "heartbeat_age_s": (
                self._cached_age(self._mux_hb or self._path("hb.json"))
                if self.dir
                else None
            ),
            "checkpoint_age_s": (
                self._cached_age(self.checkpoint_path) if self.dir else None
            ),
        }
        if self.mux is not None:
            out["mux"] = self.mux
        if self.result is not None:
            out["result"] = {
                k: self.result.get(k)
                for k in ("generated", "unique", "max_depth", "seconds")
            }
        return out

    def persist(self) -> Dict[str, Any]:
        """The journal-snapshot form: everything a restarted service needs
        to re-adopt this job (``service/journal.py``; paths relative to
        the service run dir so a relocated run dir still replays).
        Caller holds the service lock."""
        run_dir = self._service._cfg.run_dir
        return {
            "spec": self.spec,
            "status": self.status,
            "max_seconds": self.max_seconds,
            "max_states": self.max_states,
            "chaos": self.chaos or None,
            "idempotency_key": self.idempotency_key,
            "dir": (
                os.path.relpath(self.dir, run_dir)
                if self.dir is not None
                else None
            ),
            "engine": self.engine,
            "engine_force": self.engine_force,
            "seed_checkpoint": self.seed_checkpoint,
            "degraded": self.degraded,
            "consumed_s": self.consumed_s,
            "requeues": self.requeues,
            "wedges": self.wedges,
            "error": self.error,
            "result": (
                {
                    k: self.result.get(k)
                    for k in (
                        "generated", "unique", "max_depth", "seconds",
                        "degraded",
                    )
                }
                if self.result is not None
                else None
            ),
            "created_unix_ts": self.created_unix_ts,
            "completed_unix_ts": self.completed_unix_ts,
            "trace_id": self.trace_id,
            "tenant": self.tenant,
            "priority": self.priority,
            "deadline_s": self.deadline_s,
            "symmetry": self.symmetry,
        }

    def metrics(self) -> Optional[Dict[str, Any]]:
        """The per-job engine snapshot: a finished batch job's recorded
        ``metrics()``, or a live poll of an interactive checker."""
        if self.checker is not None:
            return self.checker.metrics()
        if self.result is not None:
            return self.result.get("metrics")
        return None


def _mtime_age(path: str) -> Optional[float]:
    """Seconds since ``path`` was last written, or None when absent."""
    try:
        return round(max(0.0, time.time() - os.stat(path).st_mtime), 3)
    except OSError:
        return None


#: Pool-counter increments implied by each replayed journal event —
#: recovery restores counters from the last snapshot verbatim, then
#: re-applies these for the events after it. Best-effort telemetry
#: (rejections and lint checks are not journaled), never an invariant.
_COUNTER_EFFECTS = {
    "submitted": ("submitted", "admitted"),
    "breaker_tripped": ("breaker_trips",),
    "breaker_closed": ("breaker_closes",),
}


def _replay_state(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a journal's records into the recoverable pool state: the last
    ``snapshot`` (if any) as the base, every later event applied on top.
    Pure — the unit the torn-tail tests pin without a service."""
    state: Dict[str, Any] = {
        "next_id": 0,
        "breaker": "closed",
        "consecutive_wedges": 0,
        "breaker_opened_unix_ts": None,
        "counters": {},
        "idem": {},
        "jobs": {},
        "order": [],
        "last_ts": 0.0,
        # Fair-share scheduler state (docs/service.md "QoS & overload"):
        # per-class served counts — the stride scheduler's pass values
        # derive as served/weight, so a restart resumes the SAME
        # inter-class rotation instead of resetting every class's credit.
        "qos_served": {},
    }

    def counters_inc(name: str, n: int = 1) -> None:
        state["counters"][name] = state["counters"].get(name, 0) + n

    for rec in records:
        state["last_ts"] = max(state["last_ts"], float(rec.get("ts", 0.0)))
        ev = rec["event"]
        for name in _COUNTER_EFFECTS.get(ev, ()):
            counters_inc(name)
        if ev == "snapshot":
            s = rec["state"]
            state["next_id"] = s.get("next_id", state["next_id"])
            state["breaker"] = s.get("breaker", "closed")
            state["consecutive_wedges"] = s.get("consecutive_wedges", 0)
            state["breaker_opened_unix_ts"] = s.get("breaker_opened_unix_ts")
            state["counters"] = dict(s.get("counters", {}))
            state["idem"] = dict(s.get("idem", {}))
            state["jobs"] = {j: dict(v) for j, v in s.get("jobs", {}).items()}
            state["order"] = [
                j for j in s.get("order", list(state["jobs"]))
                if j in state["jobs"]
            ]
            state["qos_served"] = dict(s.get("qos_served", {}))
            continue
        if ev == "recovered":
            continue
        if ev == "breaker_tripped":
            state["breaker"] = "open"
            state["breaker_opened_unix_ts"] = rec["ts"]
            state["consecutive_wedges"] = rec.get(
                "consecutive", state["consecutive_wedges"]
            )
            continue
        if ev == "breaker_closed":
            state["breaker"] = "closed"
            state["breaker_opened_unix_ts"] = None
            state["consecutive_wedges"] = 0
            continue
        jid = rec.get("job")
        if jid is None:
            continue
        if ev == "submitted":
            job = {
                "spec": rec["spec"],
                "status": "queued",
                "max_seconds": rec.get("max_seconds", 600.0),
                "max_states": rec.get("max_states"),
                "chaos": rec.get("chaos"),
                "idempotency_key": rec.get("idempotency_key"),
                "dir": rec.get("dir"),
                "engine": "xla",
                "engine_force": rec.get("engine_force"),
                "seed_checkpoint": rec.get("seed_checkpoint"),
                "degraded": False,
                # A migrated-in job arrives with wall-clock already spent
                # on its previous device (spent_s rides the journal so a
                # restart keeps charging it).
                "consumed_s": float(rec.get("spent_s") or 0.0),
                "requeues": 0,
                "wedges": 0,
                "error": None,
                "result": None,
                "created_unix_ts": rec["ts"],
                "completed_unix_ts": None,
                "trace_id": rec.get("trace_id"),
                # QoS identity; .get defaults keep pre-QoS journals
                # replaying (every old job reads as a default-tenant
                # batch-class submission, exactly its old behavior).
                "tenant": rec.get("tenant", "default"),
                "priority": rec.get("priority", "batch"),
                "deadline_s": rec.get("deadline_s"),
                "symmetry": rec.get("symmetry"),
            }
            state["jobs"][jid] = job
            state["order"].append(jid)
            if job["idempotency_key"]:
                state["idem"][job["idempotency_key"]] = jid
            try:
                state["next_id"] = max(
                    state["next_id"], int(jid.rsplit("-", 1)[-1])
                )
            except ValueError:
                pass
            continue
        job = state["jobs"].get(jid)
        if job is None:  # an event for a job the torn prefix never admitted
            continue
        if ev == "started":
            if job["status"] == "migrated":
                # The spawn/evacuate race can journal `started` after
                # `evacuated` (the worker spawned in the window between
                # the scheduler's pick and the evacuation sweep): the
                # pool-terminal verdict wins — replay must not resurrect
                # the evacuated job here, the sibling's journal owns it.
                continue
            job["status"] = "running"
            job["started_ts"] = rec["ts"]
            job["pid"] = rec.get("pid")
            # Each start is one fair-share pick: re-derive the stride
            # scheduler's per-class served counts from the events after
            # the last snapshot (the snapshot carries the base).
            cls = job.get("priority", "batch")
            state["qos_served"][cls] = state["qos_served"].get(cls, 0) + 1
            job["engine"] = rec.get("engine", job["engine"])
            job["degraded"] = job["degraded"] or job["engine"] == "host"
            # Older journals only carried the trace id on `submitted`;
            # either event restores it (migration resubmits stamp both).
            job["trace_id"] = rec.get("trace_id", job.get("trace_id"))
        elif ev == "budget_charged":
            job["consumed_s"] = rec.get("consumed_s", job["consumed_s"])
            job["pid"] = None  # the attempt was reaped; no orphan to kill
        elif ev == "quarantined":
            job["status"] = "quarantined"
            job["requeues"] = rec.get("requeues", job["requeues"])
            job["wedges"] = rec.get("wedges", job["wedges"])
            job["pid"] = None
            counters_inc("requeues")
            counters_inc(
                "wedge_verdicts" if rec.get("wedged") else "crashes"
            )
        elif ev == "completed":
            job["status"] = rec["status"]
            job["error"] = rec.get("error")
            job["result"] = rec.get("result", job.get("result"))
            job["completed_unix_ts"] = rec["ts"]
            job["pid"] = None
            counters_inc(
                "jobs_done" if rec["status"] == "done" else "jobs_failed"
            )
        elif ev == "evacuated":
            # The fleet moved this job to a sibling device: terminal for
            # THIS pool — a restart must never requeue it here (the
            # sibling's journal carries the live copy). The event carries
            # the killed attempt's charge: a crash between `evacuated`
            # and the fleet's `migrated` must not refund the budget the
            # straggler repair resubmits with.
            job["status"] = "migrated"
            job["consumed_s"] = float(
                rec.get("consumed_s", job["consumed_s"])
            )
            job["error"] = rec.get("reason")
            job["completed_unix_ts"] = rec["ts"]
            job["pid"] = None
            counters_inc("jobs_evacuated")
        elif ev == "checkpointed":
            job["checkpointed"] = True
    return state


class CheckerService:
    """The device's owner: N concurrent checking jobs behind admission
    control, per-job supervision, and a degradation breaker. Construction
    is cheap (no threads, no dirs) — the scheduler thread starts on the
    first :meth:`submit`, the prober when the breaker opens — UNLESS the
    run dir already holds a job journal, in which case construction
    replays it (docs/service.md "Durability & recovery") and restarts
    whatever the replay says is still due: the scheduler for requeued
    jobs, the prober for a restored-open breaker."""

    def __init__(self, config: Optional[ServiceConfig] = None, **overrides):
        if config is not None and overrides:
            raise TypeError(
                "pass either a ServiceConfig or keyword overrides, not both "
                f"(got config and {sorted(overrides)})"
            )
        self._cfg = config or ServiceConfig(**overrides)
        if self._cfg.compile_cache is None:
            self._cfg.compile_cache = os.path.abspath(".jax_cache")
        # QoS knob normalization (docs/service.md "QoS & overload"):
        # partial dicts merge over the defaults so a pool can reweight
        # one class without restating the rest.
        self._class_weights = dict(
            DEFAULT_CLASS_WEIGHTS, **(self._cfg.class_weights or {})
        )
        self._shed_thresholds = dict(
            DEFAULT_SHED_THRESHOLDS, **(self._cfg.shed_thresholds or {})
        )
        self._w_max = max(self._class_weights.values())
        #: Stride fair-share state: per-class picks served (journaled in
        #: the compaction snapshot, re-derived from `started` events on
        #: replay) and a live-only pass floor that forfeits the credit a
        #: class accrued while it had nothing queued (an idle class must
        #: not bank an unbounded burst against its siblings).
        self._qos_served: Dict[str, int] = {}
        self._qos_floor: Dict[str, float] = {}
        #: Completion timeline for the measured drain rate behind
        #: Retry-After: (unix_ts, priority) per settled batch job,
        #: trimmed to drain_window_s; seeded at replay from restored
        #: jobs' completed_unix_ts.
        self._drain: deque = deque()
        #: Compile-on-admit memo (family -> True): one background
        #: warm_cache subprocess per user family per service lifetime.
        self._warm_started: Dict[str, bool] = {}
        if self._cfg.mux_k is None:
            try:
                self._cfg.mux_k = max(1, int(os.environ.get("STPU_MUX", "1")))
            except ValueError:
                self._cfg.mux_k = 1
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._jobs: Dict[str, Job] = {}
        self._order: List[str] = []
        self._counters = Counters(SERVICE_COUNTERS)
        self._breaker = "closed"  #: "closed" | "open"
        self._consecutive_wedges = 0
        self._breaker_opened_unix_ts: Optional[float] = None
        self._closed = False
        self._next_id = 0
        #: Per-service admission-lint memo (spec -> verdict): a pool
        #: outlives none of the tree edits that would invalidate it, so
        #: one subprocess per distinct SHIPPED spec per service
        #: lifetime. User-family specs (STPU_FAMILIES) are never
        #: memoized — their source lives outside the tree, and a user
        #: who fixes (or breaks) their model mid-pool must get a fresh
        #: verdict, mirroring the linter's own cache bypass.
        self._lint_memo: Dict[str, Dict[str, Any]] = {}
        #: In-flight lint checks (spec -> Event): concurrent submissions
        #: of the same uncached spec wait for one subprocess instead of
        #: each paying a cold check serially on this 1-core box.
        self._lint_inflight: Dict[str, threading.Event] = {}
        self._scheduler: Optional[threading.Thread] = None
        self._prober: Optional[threading.Thread] = None
        self._session_dir: Optional[str] = None
        self.log = lambda msg: None  #: swap in print for a chatty service
        #: idempotency key -> job id (``submit(idempotency_key=...)``
        #: dedupe; survives restarts through the journal).
        self._idem: Dict[str, str] = {}
        self._journal: Optional[Journal] = None
        self._recovery: Optional[Dict[str, Any]] = None
        # Distributed tracing (docs/observability.md "Distributed
        # tracing"): the pool's own span file. NULL_TRACER when off —
        # trace ids still mint/journal/propagate either way.
        trace_cfg = self._cfg.trace
        if trace_cfg is None:
            raw = os.environ.get("STPU_SERVICE_TRACE") or None
            trace_cfg = True if raw == "1" else raw
        if trace_cfg is True:
            trace_cfg = os.path.join(self._cfg.run_dir, "trace.jsonl")
        self._tracer = (
            resolve_tracer(trace_cfg) if trace_cfg else NULL_TRACER
        )
        if self._cfg.chaos:
            # The deterministic fault layer: installed process-wide for
            # the service-side seams (journal writer, run_worker polls)
            # and exported to worker envs in _worker_env.
            chaos_mod.install(self._cfg.chaos)
        if self._cfg.journal:
            self._journal = Journal(
                os.path.join(self._cfg.run_dir, "journal.jsonl"),
                keep=self._cfg.journal_keep,
                compact_every=self._cfg.journal_compact_every,
            )
            if os.path.exists(self._journal.path):
                self._recover()

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self) -> "CheckerService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def close(self, kill: bool = True, timeout: float = 10.0) -> None:
        """Stops scheduling and the prober; with ``kill`` (default), kills
        any in-flight worker process groups (their jobs read as failed).
        Every non-terminal job reaches a terminal state here — a waiter
        blocked in ``Job.wait()``/``wait_all()`` must wake to a verdict,
        never hang on a queue that will no longer be scheduled."""
        with self._cond:
            self._closed = True
            procs = [
                j._proc
                for j in self._jobs.values()
                if j._proc is not None and j._proc.poll() is None
            ]
            for j in self._jobs.values():
                # Running batch jobs are settled by their _run_job thread
                # (it re-checks _closed under the lock); interactive jobs
                # just end with the pool. These close-time settlements
                # are for in-memory WAITERS only and are never journaled
                # as completed: with durability on, unfinished work stays
                # queued/running in the journal, and the next incarnation
                # over this run dir requeues it.
                if j.status in ("queued", "quarantined"):
                    j.status = "failed"
                    j.error = "service closed"
                    self._counters.inc("jobs_failed")
                elif j.kind == "interactive" and j.status == "running":
                    j.status = "done"
                    self._counters.inc("jobs_done")
            self._cond.notify_all()
        if kill:
            for proc in procs:
                sup._kill_group(proc)
        for t in (self._scheduler, self._prober):
            if t is not None:
                t.join(timeout=timeout)
        if self._journal is not None:
            self._journal.close()

    def _ensure_session_dir(self) -> str:
        if self._session_dir is None:
            d = os.path.join(
                self._cfg.run_dir, f"svc-{int(time.time())}-{os.getpid()}"
            )
            os.makedirs(d, exist_ok=True)
            self._session_dir = d
        return self._session_dir

    def _ensure_scheduler(self) -> None:
        if self._scheduler is None or not self._scheduler.is_alive():
            self._scheduler = threading.Thread(
                target=self._scheduler_loop, name="stpu-service-scheduler",
                daemon=True,
            )
            self._scheduler.start()

    def _start_prober(self, immediate: bool = False) -> None:
        """The background breaker prober; with ``immediate`` (a restart
        that recovered an OPEN breaker) the first probe fires now instead
        of after ``probe_interval_s`` — a restarted pool must not send
        its first job at a possibly-wedged device just because the
        incarnation that observed the wedges died."""
        target = self._probe_loop
        if immediate:
            def target() -> None:  # noqa: F811 - deliberate shadowing
                self.probe_device_now()
                self._probe_loop()
        self._prober = threading.Thread(
            target=target, name="stpu-service-prober", daemon=True,
        )
        self._prober.start()

    # -- durability (service/journal.py) -----------------------------------

    def _jlog(self, event: str, **payload: Any) -> None:
        """Append one journal record (caller holds the lock; no-op with
        journaling off). Compaction rides here: past the cadence the log
        is rewritten as one snapshot of the current state."""
        j = self._journal
        if j is None:
            return
        j.append(event, ts=time.time(), **payload)
        if j.compaction_due:
            j.compact(self._snapshot_payload(), ts=time.time())

    def _snapshot_payload(self) -> Dict[str, Any]:
        """The full recoverable pool state (caller holds the lock):
        the journal compaction's snapshot record, and the base a replay
        folds later events onto. Interactive jobs are deliberately
        absent — a live session cannot survive its process."""
        return {
            "next_id": self._next_id,
            "breaker": self._breaker,
            "consecutive_wedges": self._consecutive_wedges,
            "breaker_opened_unix_ts": self._breaker_opened_unix_ts,
            "counters": self._counters.snapshot(),
            "idem": dict(self._idem),
            "qos_served": dict(self._qos_served),
            "order": [
                jid for jid in self._order
                if self._jobs[jid].kind == "batch"
            ],
            "jobs": {
                jid: self._jobs[jid].persist()
                for jid in self._order
                if self._jobs[jid].kind == "batch"
            },
        }

    def _recover(self) -> None:
        """Replay ``<run_dir>/journal.jsonl`` into a live pool: the
        restart-recovery half of the durability contract (docs/service.md
        "Durability & recovery"). A torn tail is recovered-from, not
        fatal: the torn record is dropped, everything before it replays,
        and the recompaction below amputates the torn bytes so appends
        never land after them."""
        replay = read_journal(self._journal.path)
        state = _replay_state(replay.records)
        now = time.time()
        run_dir = self._cfg.run_dir
        readopted = 0
        requeued = 0
        expired: List[Job] = []
        orphans: List[tuple] = []
        with self._cond:
            self._next_id = max(self._next_id, state["next_id"])
            self._breaker = state["breaker"]
            self._consecutive_wedges = state["consecutive_wedges"]
            self._breaker_opened_unix_ts = state["breaker_opened_unix_ts"]
            self._idem.update(state["idem"])
            for cls, served in state["qos_served"].items():
                self._qos_served[cls] = self._qos_served.get(cls, 0) + served
            for name, value in state["counters"].items():
                # jobs_recovered/orphans_killed are per-INCARNATION (they
                # mirror the recovery provenance dict); restoring them
                # from a previous incarnation's snapshot would double-
                # count across a restart loop. Everything else is
                # lifetime-cumulative.
                if value and name not in ("jobs_recovered", "orphans_killed"):
                    self._counters.inc(name, value)
            for jid in state["order"]:
                rec = state["jobs"][jid]
                job = Job(
                    self,
                    jid,
                    rec["spec"],
                    max_seconds=rec["max_seconds"],
                    max_states=rec.get("max_states"),
                    chaos=rec.get("chaos"),
                    idempotency_key=rec.get("idempotency_key"),
                    tenant=rec.get("tenant", "default"),
                    priority=rec.get("priority", "batch"),
                    deadline_s=rec.get("deadline_s"),
                    symmetry=rec.get("symmetry"),
                )
                job.recovered = True
                job.created_unix_ts = rec.get("created_unix_ts", now)
                job.dir = (
                    os.path.join(run_dir, rec["dir"])
                    if rec.get("dir")
                    else None
                )
                job.engine = rec.get("engine", "xla")
                job.engine_force = rec.get("engine_force")
                job.seed_checkpoint = rec.get("seed_checkpoint")
                job.degraded = bool(rec.get("degraded"))
                job.consumed_s = float(rec.get("consumed_s", 0.0))
                job.requeues = int(rec.get("requeues", 0))
                job.wedges = int(rec.get("wedges", 0))
                job.error = rec.get("error")
                # Trace continuity across restarts: the requeued attempt
                # keeps journaling/propagating the submission's trace id
                # (its spans re-root at the trace — the old root span
                # lives in the previous incarnation's file).
                job.trace_id = rec.get("trace_id")
                status = rec["status"]
                if status in ("done", "failed", "migrated"):
                    # Journal-complete: restore the terminal verdict,
                    # never re-run. The full result (discovery paths
                    # included) reloads from the job dir when the sweep
                    # has not reclaimed it; the journaled summary is the
                    # fallback.
                    job.status = status
                    job.completed_unix_ts = rec.get("completed_unix_ts")
                    job.result = rec.get("result")
                    if (
                        status in ("done", "failed")
                        and job.completed_unix_ts is not None
                        and now - job.completed_unix_ts
                        <= self._cfg.drain_window_s
                    ):
                        # Seed the measured drain rate: completions the
                        # dead incarnation settled inside the window
                        # still count toward Retry-After accuracy.
                        self._drain.append(
                            (job.completed_unix_ts, job.priority)
                        )
                    result_path = (
                        os.path.join(job.dir, "result.json")
                        if job.dir is not None
                        else None
                    )
                    if result_path is not None and os.path.exists(result_path):
                        try:
                            with open(result_path) as fh:
                                job.result = json.load(fh)
                        except (OSError, json.JSONDecodeError):
                            pass
                else:
                    # Queued / quarantined / in-flight: requeue. An
                    # in-flight job charges the wall-clock it had already
                    # spent when the pool died (the journal's last
                    # timestamp bounds "the pool was still alive here")
                    # and its worker — orphaned by the pool's death, both
                    # run in their own sessions — is killed by journaled
                    # pid before the scheduler can double-run the job.
                    if status == "running":
                        started = rec.get("started_ts")
                        if started is not None:
                            job.consumed_s += max(
                                0.0, state["last_ts"] - started
                            )
                        if rec.get("pid"):
                            orphans.append((int(rec["pid"]), job))
                    if job.max_seconds - job.consumed_s <= 0:
                        job.status = "failed"
                        job.error = (
                            "wall-clock budget exhausted "
                            "(spent before the restart)"
                        )
                        job.completed_unix_ts = now
                        self._counters.inc("jobs_failed")
                        expired.append(job)
                    else:
                        job.status = "queued"
                        requeued += 1
                        # Existence, not validity: _run_job_inner's
                        # latest_valid_checkpoint does the (decompress +
                        # digest) verification at spawn time; this is
                        # provenance, cheap under the lock.
                        if job.dir is not None and (
                            os.path.exists(job.checkpoint_path)
                            or os.path.exists(job.checkpoint_path + ".1")
                        ):
                            readopted += 1
                self._jobs[jid] = job
                self._order.append(jid)
                self._counters.inc("jobs_recovered")
            # The replay walks submission order; completions may have
            # settled in any order — the drain window trims from the
            # left, so keep it time-sorted.
            self._drain = deque(sorted(self._drain))
        killed = 0
        for pid, job in orphans:
            if self._kill_orphan(pid, job):
                killed += 1
        self._recovery = {
            "records_replayed": len(replay.records),
            "torn": replay.torn,
            "jobs_recovered": len(state["order"]),
            "jobs_requeued": requeued,
            "jobs_readopted": readopted,
            "jobs_expired": len(expired),
            "orphans_killed": killed,
        }
        with self._cond:
            if killed:
                self._counters.inc("orphans_killed", killed)
            # Recompact: the journal becomes [snapshot, recovered, ...] —
            # bounded growth across restart loops, and a torn tail can
            # never be appended after.
            self._journal.seq = (
                replay.records[-1]["seq"] if replay.records else 0
            )
            # The snapshot already carries the expired jobs settled as
            # failed (status, error, completed_unix_ts, counters) —
            # appending separate `completed` events here would replay ON
            # TOP of it at the next restart and double-count
            # jobs_failed.
            self._journal.compact(self._snapshot_payload(), ts=time.time())
            self._jlog("recovered", **self._recovery)
            self._sweep_artifacts(now)
            runnable = any(
                j.kind == "batch" and not j.done
                for j in self._jobs.values()
            )
            self._cond.notify_all()
        if runnable:
            self._ensure_scheduler()
        if self._breaker == "open" and self._cfg.probe_auto:
            self._start_prober(immediate=True)

    def _kill_orphan(self, pid: int, job: Job) -> bool:
        """Best-effort kill of a worker the dead incarnation left running
        (journaled pid; workers lead their own sessions, so the pool's
        death never took them down). Guarded against pid reuse: only a
        process whose command line still looks like our worker body is
        touched."""
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as fh:
                cmdline = fh.read().replace(b"\0", b" ").decode(
                    errors="replace"
                )
        except OSError:
            return False  # already gone
        if "worker.py" not in cmdline and "service.worker" not in cmdline:
            return False  # pid reused by something that is not ours
        self.log(f"killing orphaned worker pid {pid} ({job.id})")
        # Straight to SIGKILL: the orphan's incarnation is gone, nothing
        # coordinates a graceful stop, and a SIGSTOP-frozen worker would
        # sit on TERM forever (the same reasoning as _kill_group's last
        # resort). run_worker spawns workers as session leaders, so the
        # pid doubles as the pgid; fall back to the single process if
        # the group is already gone.
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            return False
        except OSError:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                return False
        return True

    def _sweep_artifacts(self, now: Optional[float] = None) -> None:
        """Reclaim journal-complete jobs' run-dir artifacts (heartbeat,
        trace, checkpoint rotations, worker stdout) past the retention —
        a long-lived service must not grow ``runs/service/`` without
        bound. Caller holds the lock; gauge: ``artifacts_swept``."""
        retention = self._cfg.artifact_retention_s
        if retention is None:
            return
        now = time.time() if now is None else now
        for job in self._jobs.values():
            if (
                job.kind != "batch"
                or not job.done
                or job.swept
                or job.dir is None
                or job.completed_unix_ts is None
                or now - job.completed_unix_ts < retention
            ):
                continue
            if os.path.isdir(job.dir):
                shutil.rmtree(job.dir, ignore_errors=True)
            job.swept = True
            self._counters.inc("artifacts_swept")
            try:
                # A previous incarnation's session dir, once empty, goes
                # too (rmdir refuses non-empty dirs — live siblings keep
                # theirs).
                os.rmdir(os.path.dirname(job.dir))
            except OSError:
                pass

    # -- admission ---------------------------------------------------------

    def _counts(self) -> Dict[str, int]:
        c = {"queued": 0, "running": 0, "quarantined": 0, "interactive": 0,
             "done": 0, "failed": 0, "migrated": 0}
        for j in self._jobs.values():
            if j.kind == "interactive":
                if j.status == "running":
                    c["interactive"] += 1
                continue
            c[j.status] += 1
        return c

    def _record_drain(self, priority: str) -> None:
        """One settled batch job on the completion timeline (caller holds
        the lock) — the measured drain rate behind ``Retry-After``."""
        now = time.time()
        self._drain.append((now, priority))
        cutoff = now - self._cfg.drain_window_s
        while self._drain and self._drain[0][0] < cutoff:
            self._drain.popleft()

    def _drain_rate(self, priority: Optional[str] = None) -> Optional[float]:
        """Measured completions/second over ``drain_window_s`` (caller
        holds the lock), optionally for one class; None below two
        completions — one settlement is an anecdote, not a rate."""
        now = time.time()
        cutoff = now - self._cfg.drain_window_s
        while self._drain and self._drain[0][0] < cutoff:
            self._drain.popleft()
        ts = [
            t for t, cls in self._drain
            if priority is None or cls == priority
        ]
        if len(ts) < 2:
            return None
        span = max(now - ts[0], 1e-3)
        return len(ts) / span

    def _jobs_ahead(self, priority: Optional[str]) -> int:
        """How many batch jobs the scheduler would serve before (or
        alongside) a NEW submission of ``priority`` — same-or-higher
        class weight among the non-terminal set. Caller holds the
        lock."""
        w = (
            self._class_weights.get(priority, 1.0)
            if priority is not None
            else 0.0
        )
        ahead = 0
        for j in self._jobs.values():
            if j.kind != "batch" or j.done:
                continue
            if (
                priority is None
                or self._class_weights.get(j.priority, 1.0) >= w
            ):
                ahead += 1
        return ahead

    def _retry_after(
        self, counts: Dict[str, int], priority: Optional[str] = None
    ) -> float:
        """The back-pressure estimate an HTTP front end would send as
        ``Retry-After``: jobs ahead of (same-or-higher class than) the
        rejected submission over the MEASURED drain rate — the per-class
        completion timeline when that class has recent settlements, the
        pool-wide rate otherwise. Falls back to the static jobs-ahead /
        slots * default-budget guess only when the window holds fewer
        than two completions (a cold pool has no rate to measure). An
        estimate, not a promise — but monotone in pool pressure, which
        is what a client's retry loop needs."""
        ahead = self._jobs_ahead(priority)
        rate = self._drain_rate(priority) or self._drain_rate()
        if rate is not None:
            # +1: the retrier's own job must drain too.
            return min(
                max(5.0, (ahead + 1) / rate), self._cfg.max_seconds_cap
            )
        per_slot = ahead / max(self._cfg.max_inflight, 1)
        return min(
            max(10.0, per_slot * self._cfg.default_max_seconds * 0.5),
            self._cfg.max_seconds_cap,
        )

    def _tenant_quota(self, tenant: str) -> Dict[str, Any]:
        """The effective quota for one tenant: per-tenant overrides
        merged over the pool-wide defaults; None values = unlimited."""
        quota = {
            "max_queued": self._cfg.tenant_max_queued,
            "max_inflight": self._cfg.tenant_max_inflight,
            "budget_s": self._cfg.tenant_budget_s,
        }
        quota.update((self._cfg.tenant_quotas or {}).get(tenant, {}))
        return quota

    def _tenant_usage(self, tenant: str) -> Dict[str, float]:
        """One tenant's live pool usage (caller holds the lock), derived
        by scanning the job table — no separate books to drift or
        replay: restored jobs ARE the quota state."""
        queued = inflight = 0
        spent = 0.0
        for j in self._jobs.values():
            if j.kind != "batch" or j.tenant != tenant:
                continue
            spent += j.consumed_s
            if j.status in ("queued", "quarantined"):
                queued += 1
            elif j.status == "running":
                inflight += 1
        return {"queued": queued, "inflight": inflight, "spent_s": spent}

    def _quota_rejection(
        self, tenant: str, max_seconds: float
    ) -> Optional[str]:
        """The per-tenant admission verdict (caller holds the lock):
        the rejection reason, or None when the tenant is inside its
        quota. In-flight quota is enforced at SCHEDULING time (the
        fair-share pick skips a saturated tenant), not here — a queued
        job costs nothing until a slot serves it."""
        quota = self._tenant_quota(tenant)
        usage = self._tenant_usage(tenant)
        if (
            quota["max_queued"] is not None
            and usage["queued"] >= quota["max_queued"]
        ):
            return (
                f"tenant {tenant!r} queued quota reached "
                f"({quota['max_queued']})"
            )
        if (
            quota["budget_s"] is not None
            and usage["spent_s"] + max_seconds > quota["budget_s"]
        ):
            return (
                f"tenant {tenant!r} device-seconds budget exceeded "
                f"({usage['spent_s']:.0f}s spent + {max_seconds:.0f}s "
                f"asked > {quota['budget_s']:.0f}s)"
            )
        return None

    def _shed_occupancy_limit(self, priority: str) -> int:
        """The queue occupancy at which ``priority`` sheds: its
        threshold fraction of ``max_queue``, floored at one so a
        threshold never rejects an empty pool."""
        frac = self._shed_thresholds.get(priority, 1.0)
        return max(1, int(round(self._cfg.max_queue * frac)))

    def _budget_rejection(
        self, max_seconds: float, max_states: Optional[int]
    ) -> Optional[str]:
        """The ONE budget/caps validator: the rejection reason, or None
        when the budgets are servable. Shared by submit()'s pre-lint
        precheck and its under-lock authoritative rejection so the two
        can never drift (a drifted precheck would admit an unlinted
        job)."""
        if not 0 < max_seconds <= self._cfg.max_seconds_cap:
            return (
                f"max_seconds {max_seconds:.0f} outside the servable "
                f"range (0, {self._cfg.max_seconds_cap:.0f}]"
            )
        if (
            self._cfg.max_states_cap is not None
            and max_states is not None
            and max_states > self._cfg.max_states_cap
        ):
            return (
                f"max_states {max_states} exceeds the pool cap "
                f"{self._cfg.max_states_cap}"
            )
        return None

    def _admission_verdict(self, spec: str) -> Dict[str, Any]:
        """One spec's admission flight-check verdict (memoized per
        service): the relevant kernel-surface subset of stpu-lint run in
        a subprocess (``--admission``, docs/static-analysis.md). The
        verdict dict rides into ``Job.lint`` (and so the job snapshot
        and ``/.pool``). ``ok`` is tri-state: True/False are the
        linter's word; None means the CHECK failed (timeout, crash,
        unparseable output) — the pool fails OPEN on that (the device
        still has per-job fault isolation behind it) but records it as
        ``lint_errors`` so an operator sees a blind gate."""
        family, _ = registry.parse(spec)
        memoizable = family in registry.FAMILIES  # user families: never
        while True:
            with self._lock:
                memo = self._lint_memo.get(spec) if memoizable else None
                if memo is not None:
                    return dict(memo, cached=True)
                waiter = self._lint_inflight.get(spec)
                if waiter is None:
                    self._lint_inflight[spec] = threading.Event()
                    self._counters.inc("lint_checks")
                    break
            # Another thread is checking this spec: wait for its
            # verdict, then loop to read the memo (or run our own check
            # if it wasn't memoizable / errored).
            waiter.wait(timeout=self._cfg.lint_timeout_s + 30.0)
        argv = [sys.executable, _LINT, "--admission", spec, "--json"]
        verdict: Dict[str, Any]
        try:
            try:
                if chaos_mod.fire("lint.timeout") is not None:
                    # Deterministic fault injection: the admission-lint
                    # subprocess "timing out" — the fail-open tooling-
                    # error path, without waiting out a real timeout.
                    raise subprocess.TimeoutExpired(
                        argv, self._cfg.lint_timeout_s,
                        output="chaos: simulated admission-lint timeout",
                    )
                proc = subprocess.run(
                    argv,
                    timeout=self._cfg.lint_timeout_s,
                    capture_output=True,
                    text=True,
                )
                report = json.loads(proc.stdout)
                verdict = {
                    "ok": bool(report["ok"]),
                    "findings": [
                        {k: f[k] for k in ("rule", "surface", "message")}
                        for f in report["findings"]
                    ],
                    "waived": len(report["waived"]),
                    "errors": report["errors"],
                    "cached": False,
                }
            except (
                subprocess.TimeoutExpired,
                OSError,
                json.JSONDecodeError,
                KeyError,
            ) as e:
                verdict = {
                    "ok": None,
                    "findings": [],
                    "waived": 0,
                    "errors": [
                        f"admission lint failed: {type(e).__name__}: {e}"
                    ],
                    "cached": False,
                }
            with self._lock:
                if verdict["ok"] is None:
                    # A TOOLING failure is not a verdict about the spec:
                    # count it, fail open for THIS submission, but do
                    # NOT memoize — the next submission retries the
                    # check, so one transient timeout can't disable the
                    # gate for a spec for the rest of the service's
                    # life.
                    self._counters.inc("lint_errors")
                elif memoizable:
                    self._lint_memo[spec] = verdict
        finally:
            # Always release waiters, even on an unexpected error — a
            # leaked in-flight entry would spin every later submitter of
            # this spec through wait-timeout loops forever.
            with self._lock:
                waiter = self._lint_inflight.pop(spec, None)
            if waiter is not None:
                waiter.set()
        return verdict

    def _spawn_warm(self, family: str, spec: str) -> None:
        """Fire-and-forget compile-on-admit warmer: one background
        ``tools/warm_cache.py --specs <spec>`` subprocess per user
        family per service lifetime, banking the family's STPU007
        compile-plan shapes into the pool's shared compile cache. Best
        effort by design — a warm failure costs the tenant only the
        cold compile its first job would have paid anyway."""
        out_dir = os.path.join(self._cfg.run_dir, "warm")
        argv = [
            sys.executable, _WARM,
            "--specs", spec,
            "--platform", self._cfg.platform,
            "--cache-dir", self._cfg.compile_cache,
            "--out-dir", out_dir,
        ]
        try:
            os.makedirs(out_dir, exist_ok=True)
            with open(os.path.join(out_dir, f"{family}.log"), "ab") as fh:
                subprocess.Popen(
                    argv,
                    stdout=fh,
                    stderr=subprocess.STDOUT,
                    start_new_session=True,
                )
        except OSError as e:
            self.log(f"compile-on-admit warm failed to spawn: {e}")

    def submit(
        self,
        spec: str,
        *,
        max_seconds: Optional[float] = None,
        max_states: Optional[int] = None,
        chaos: Optional[Dict[str, Any]] = None,
        idempotency_key: Optional[str] = None,
        engine: str = "auto",
        spent_s: float = 0.0,
        resume_from: Optional[str] = None,
        trace_id: Optional[str] = None,
        tenant: str = "default",
        priority: str = "batch",
        deadline_s: Optional[float] = None,
        symmetry: Optional[str] = None,
    ) -> Job:
        """Queues one batch checking job; returns its :class:`Job` handle
        or raises :class:`AdmissionError` (queue full → carries
        ``retry_after_s``; an over-cap budget → no retry hint, shrink the
        request; an unwaived flight-check finding → no retry hint, fix
        the spec). Unknown/malformed specs raise ``ValueError`` before
        any admission accounting.

        ``idempotency_key`` dedupes client resubmissions — across
        restarts too (the key rides the journal): a key the pool already
        knows returns the EXISTING job (terminal or not; a client that
        wants a genuine re-run picks a new key) with no admission
        accounting beyond the ``idem_dedups`` counter. This is what lets
        a supervisor restart loop blindly resubmit its whole schedule
        after a service crash and converge to exactly-once.

        The fleet-migration knobs (service/fleet.py; docs/service.md
        "Fleet"): ``engine="host"`` forces the host on-demand engine for
        this job regardless of breaker state (the every-device-open last
        resort — it is the only work a ``breaker_mode="halt"`` pool runs
        while open); ``spent_s`` seeds the wall-clock already charged on
        a previous device; ``resume_from`` seeds a sibling pool's
        checkpoint rotation, adopted until this job writes rotations of
        its own.

        ``trace_id`` joins an existing distributed trace (the fleet
        passes its minted id; migration passes the victim's) instead of
        minting a fresh one — docs/observability.md "Distributed
        tracing".

        The QoS identity (docs/service.md "QoS & overload"): ``tenant``
        names the submitter (quota accounting), ``priority`` picks the
        class (:data:`PRIORITY_CLASSES` — weighted fair-share slots,
        overload shedding order), ``deadline_s`` is a soft deadline from
        submission that EDF-orders same-class picks. Under overload a
        lower class sheds FIRST (typed, class-naming
        :class:`AdmissionError` whose ``retry_after_s`` comes from the
        measured per-class drain rate); a tenant over its queued/budget
        quota rejects typed (``quota_rejects``)."""
        if engine not in ("auto", "host"):
            raise ValueError(f"engine must be 'auto' or 'host', got {engine!r}")
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"priority must be one of {PRIORITY_CLASSES}, got {priority!r}"
            )
        if not tenant or not isinstance(tenant, str):
            raise ValueError(f"tenant must be a non-empty string, got {tenant!r}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s!r}")
        if symmetry is not None and symmetry not in ("auto", "on", "off"):
            raise ValueError(
                f"symmetry must be None/'auto'/'on'/'off', got {symmetry!r}"
            )
        family, _ = registry.parse(spec)  # typed spec validation, pre-admission
        _t0 = time.monotonic()
        with self._lock:
            # Pre-flight closed check: a closed pool must reject
            # immediately (the old contract), not after a cold lint
            # subprocess. The post-lint re-check under the lock still
            # guards the race.
            if self._closed:
                raise RuntimeError("service is closed")
            if idempotency_key is not None:
                known = self._jobs.get(self._idem.get(idempotency_key, ""))
                if known is not None:
                    self._counters.inc("idem_dedups")
                    return known
        max_seconds = (
            self._cfg.default_max_seconds if max_seconds is None else max_seconds
        )
        # Budget validation BEFORE the flight-check (ONE definition —
        # the same validator rejects under the lock below): a request
        # the range checks reject anyway must not pay a cold lint
        # subprocess. Same for a full queue: the precheck is racy (the
        # authoritative check below still holds the lock), but a retry
        # loop against a saturated pool must not keep the 1-core box
        # pinned on lint subprocesses for doomed submissions.
        budget_reason = self._budget_rejection(max_seconds, max_states)
        queue_full = False
        if budget_reason is None and self._cfg.admission_lint:
            with self._lock:
                counts = self._counts()
                # The class's SHED limit, not the hard cap: a
                # best-effort submission a half-full pool is about to
                # shed must not pay a cold lint subprocess either.
                queue_full = (
                    counts["queued"] + counts["quarantined"]
                    >= self._shed_occupancy_limit(priority)
                    or self._quota_rejection(tenant, max_seconds) is not None
                )
        # The flight-check runs OUTSIDE the lock (a cold check is a
        # subprocess); scheduling state is only touched afterwards.
        lint = (
            self._admission_verdict(spec)
            if self._cfg.admission_lint
            and budget_reason is None
            and not queue_full
            else None
        )
        with self._cond:
            if self._closed:
                raise RuntimeError("service is closed")
            self._counters.inc("submitted")
            if lint is not None and lint["ok"] is False:
                # A typed rejection with NO retry hint: retrying the
                # same spec cannot help — the finding is in the model's
                # kernels (or its compile plan), not in pool pressure.
                self._counters.inc("rejected")
                self._counters.inc("lint_rejects")
                rules = sorted({f["rule"] for f in lint["findings"]})
                first = lint["findings"][0]["message"] if lint["findings"] else (
                    "; ".join(lint["errors"]) or "flight-check failed"
                )
                raise AdmissionError(
                    f"admission flight-check failed for {spec!r} "
                    f"({', '.join(rules) or 'trace error'}): {first}"
                )
            if budget_reason is not None:
                self._counters.inc("rejected")
                raise AdmissionError(budget_reason)
            quota_reason = self._quota_rejection(tenant, max_seconds)
            if quota_reason is not None:
                self._counters.inc("rejected")
                self._counters.inc("quota_rejects")
                raise AdmissionError(
                    quota_reason,
                    # A queued-quota rejection clears as the tenant's
                    # own jobs drain; a budget quota never does.
                    retry_after_s=(
                        self._retry_after(self._counts(), priority)
                        if "quota reached" in quota_reason
                        else None
                    ),
                )
            counts = self._counts()
            occupancy = counts["queued"] + counts["quarantined"]
            shed_limit = self._shed_occupancy_limit(priority)
            if (
                occupancy >= shed_limit
                # The precheck saw a full/shedding/over-quota pool and
                # skipped the lint; if it drained in the (subprocess-
                # free, microsecond) gap, still reject rather than admit
                # an UNLINTED job — the client's retry gets the real
                # verdict.
                or (queue_full and lint is None and self._cfg.admission_lint)
            ):
                self._counters.inc("rejected")
                hint = self._retry_after(counts, priority)
                if shed_limit < self._cfg.max_queue:
                    # Adaptive overload shedding: this class's threshold
                    # tripped BEFORE the hard cap — the pool is
                    # degrading gracefully, lowest class first.
                    self._counters.inc("sheds")
                    raise AdmissionError(
                        f"overloaded: shedding {priority} submissions "
                        f"({occupancy} waiting >= {shed_limit} "
                        f"= {self._shed_thresholds.get(priority, 1.0):.0%}"
                        f" of {self._cfg.max_queue})",
                        retry_after_s=hint,
                    )
                raise AdmissionError(
                    f"queue full ({self._cfg.max_queue} waiting jobs)",
                    retry_after_s=hint,
                )
            if idempotency_key is not None:
                # Re-check under the final lock: a concurrent submit of
                # the same key between the precheck and here must not
                # admit the job twice.
                known = self._jobs.get(self._idem.get(idempotency_key, ""))
                if known is not None:
                    self._counters.inc("idem_dedups")
                    return known
            self._next_id += 1
            job = Job(
                self,
                f"job-{self._next_id:04d}",
                spec,
                max_seconds=max_seconds,
                max_states=max_states,
                chaos=chaos,
                idempotency_key=idempotency_key,
                tenant=tenant,
                priority=priority,
                deadline_s=deadline_s,
                symmetry=symmetry,
            )
            job.lint = lint
            job.engine_force = "host" if engine == "host" else None
            job.consumed_s = max(0.0, float(spent_s))
            job.seed_checkpoint = resume_from
            # Trace ids mint UNCONDITIONALLY (journaled, surfaced in
            # /.pool) — only span WRITES are gated on the tracer.
            job.trace_id = trace_id or new_trace_id()
            job.dir = os.path.join(self._ensure_session_dir(), job.id)
            os.makedirs(job.dir, exist_ok=True)
            if job.chaos.get("marker") is True:
                # The "arm exactly-once" sentinel for caller-supplied
                # chaos dicts (the fleet's device.flaky): resolved to a
                # per-job marker path now that the job dir exists.
                job.chaos["marker"] = os.path.join(job.dir, "chaos.marker")
            # Pool-level chaos plan -> job-level worker sabotage: the
            # N-th submitted job (the plan's @n trigger counts submits)
            # gets the matching worker flag. `once` (default) arms the
            # exactly-once marker so the requeued attempt runs clean.
            for point, key in (
                ("worker.die", "die_at_depth"),
                ("worker.freeze", "freeze_at_depth"),
            ):
                inj = chaos_mod.fire(point)
                if inj is not None:
                    job.chaos.setdefault(key, int(inj.get("depth", 3)))
                    if inj.get("once", 1):
                        job.chaos.setdefault(
                            "marker", os.path.join(job.dir, "chaos.marker")
                        )
            if idempotency_key is not None:
                self._idem[idempotency_key] = job.id
            self._jobs[job.id] = job
            self._order.append(job.id)
            self._counters.inc("admitted")
            self._jlog(
                "submitted",
                job=job.id,
                spec=spec,
                max_seconds=max_seconds,
                max_states=max_states,
                chaos=job.chaos or None,
                idempotency_key=idempotency_key,
                dir=os.path.relpath(job.dir, self._cfg.run_dir),
                engine_force=job.engine_force,
                spent_s=job.consumed_s or None,
                seed_checkpoint=job.seed_checkpoint,
                trace_id=job.trace_id,
                tenant=tenant,
                priority=priority,
                deadline_s=deadline_s,
                symmetry=symmetry,
            )
            self._jlog(
                "admitted",
                job=job.id,
                lint_ok=None if lint is None else lint["ok"],
            )
            # Compile-on-admit (docs/service.md "QoS & overload"): a
            # user family's (STPU_FAMILIES) first admission pre-banks
            # its compile-plan shapes into .jax_cache in a background
            # warm_cache subprocess — the new tenant's first real job
            # never pays cold XLA compiles inside its wall-clock budget.
            warm_family = None
            if (
                self._cfg.warm_user_families
                and family not in registry.FAMILIES
                and not self._warm_started.get(family)
            ):
                self._warm_started[family] = True
                self._counters.inc("warm_compiles")
                warm_family = family
            self._ensure_scheduler()
            self._cond.notify_all()
        if warm_family is not None:
            self._spawn_warm(warm_family, spec)
        if self._tracer.enabled:
            # Root span of the submission's trace — the attempt spans'
            # parent. Emitted outside the lock (one appended JSONL
            # line); the id is what run_worker exports downstream.
            job._root_sid = self._tracer.emit(
                "submit",
                t0=_t0,
                dur=time.monotonic() - _t0,
                attrs={"job": job.id, "spec": spec},
                trace_id=job.trace_id,
            )
        return job

    def check_session_capacity(self) -> None:
        """Raises :class:`AdmissionError` when the interactive-session cap
        is already reached. Callers building EXPENSIVE checkers (the
        Explorer's device backend allocates device-resident buffers) call
        this *before* construction so a rejected tenant never pays — the
        small pre-check-to-register window is benign (register still
        enforces the cap). A rejection here counts as submitted+rejected —
        capacity-rejected sessions must be visible in the pool telemetry,
        and ``submitted == admitted + rejected`` stays reconcilable (a
        passing pre-check counts nothing; registration does)."""
        with self._lock:
            counts = self._counts()
            if counts["interactive"] >= self._cfg.max_sessions:
                self._counters.inc("submitted")
                self._counters.inc("rejected")
                raise AdmissionError(
                    f"interactive sessions full ({self._cfg.max_sessions})",
                    retry_after_s=self._retry_after(counts),
                )

    def register_interactive(self, checker, *, label: Optional[str] = None,
                             degraded: bool = False) -> Job:
        """Admits a live in-process checker (the Explorer's) as a pool job
        of kind ``"interactive"`` — counted, capped (``max_sessions``),
        and visible in the pool gauges like any other tenant."""
        with self._cond:
            if self._closed:
                raise RuntimeError("service is closed")
            self._counters.inc("submitted")
            counts = self._counts()
            if counts["interactive"] >= self._cfg.max_sessions:
                self._counters.inc("rejected")
                raise AdmissionError(
                    f"interactive sessions full ({self._cfg.max_sessions})",
                    retry_after_s=self._retry_after(counts),
                )
            self._next_id += 1
            job = Job(
                self,
                f"job-{self._next_id:04d}",
                label or type(checker.model()).__name__,
                kind="interactive",
            )
            job.status = "running"
            job.engine = "host" if degraded else "xla"
            job.degraded = degraded
            job.checker = checker
            if degraded:
                self._counters.inc("degraded_jobs")
            self._jobs[job.id] = job
            self._order.append(job.id)
            self._counters.inc("admitted")
            self._cond.notify_all()
        checker.attach_job(job.id)
        return job

    def release_interactive(self, job: Job) -> None:
        with self._cond:
            if job.status == "running":
                job.status = "done"
                self._counters.inc("jobs_done")
            self._cond.notify_all()

    # -- scheduling --------------------------------------------------------

    def _scheduler_loop(self) -> None:
        while True:
            to_start: List[Job] = []
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                counts = self._counts()
                slots = self._cfg.max_inflight - counts["running"]
                quarantine_release = None
                # Halt mode (fleet pools): while the breaker is open,
                # queued jobs HOLD for the fleet to migrate them — only
                # forced-host work (the all-devices-open last resort)
                # runs. The breaker close notifies, re-waking this loop.
                halted = (
                    self._cfg.breaker_mode == "halt"
                    and self._breaker == "open"
                )
                if slots > 0:
                    eligible: List[Job] = []
                    for jid in self._order:
                        job = self._jobs[jid]
                        if job.kind != "batch":
                            continue
                        if halted and job.engine_force != "host":
                            continue
                        if job.status == "quarantined" and job.requeue_at > now:
                            quarantine_release = (
                                job.requeue_at
                                if quarantine_release is None
                                else min(quarantine_release, job.requeue_at)
                            )
                            continue
                        if job.status in ("queued", "quarantined"):
                            eligible.append(job)
                    # The QoS pick (docs/service.md "QoS & overload")
                    # replaces the old FIFO scan: weighted fair share
                    # across classes, EDF within a class, aging as the
                    # starvation backstop, tenant in-flight quotas.
                    for job in self._qos_pick(eligible, slots):
                        job.status = "running"
                        to_start.append(job)
                if not to_start:
                    # Event-driven idle: submit/requeue/close all notify.
                    # A timed wait is only needed to release a quarantine
                    # backoff (or re-poll a full pool) — an idle pool
                    # sleeps on the condition instead of polling at 5 Hz
                    # on this one-core box.
                    if quarantine_release is not None:
                        self._cond.wait(
                            timeout=max(quarantine_release - now, 0.05)
                        )
                    else:
                        # Idle or full pool: every relevant transition
                        # (submit, requeue, job settlement, close)
                        # notifies, so an untimed wait suffices.
                        self._cond.wait()
                groups = self._mux_partition(to_start)
            for unit in groups:
                if len(unit) == 1:
                    threading.Thread(
                        target=self._run_job, args=(unit[0],),
                        name=f"stpu-service-{unit[0].id}", daemon=True,
                    ).start()
                else:
                    threading.Thread(
                        target=self._run_mux_group, args=(unit,),
                        name=f"stpu-service-mux-{unit[0].id}", daemon=True,
                    ).start()

    def _edf_deadline(self, job: Job) -> float:
        """EDF sort key: the absolute soft deadline (submission time +
        ``deadline_s``); no deadline sorts last within the class."""
        if job.deadline_s is None:
            return float("inf")
        return job.created_unix_ts + job.deadline_s

    def _aged(self, job: Job, now_unix: float) -> bool:
        """The starvation backstop (docs/service.md "QoS & overload"): a
        queued job's effective priority ``w_class + waited_s /
        qos_aging_s`` rises monotonically; once it clears ``w_max + 1``
        — i.e. ``waited_s >= qos_aging_s * (w_max + 1 - w_class)`` —
        the job jumps the fair-share rotation entirely. That product is
        THE documented worst-case wait before any admitted job is
        scheduled ahead of every un-aged sibling (defaults: best_effort
        2400 s, batch 1800 s, interactive 600 s)."""
        w = self._class_weights.get(job.priority, 1.0)
        bound = self._cfg.qos_aging_s * (self._w_max + 1.0 - w)
        return now_unix - job.created_unix_ts >= bound

    def _qos_pick(self, eligible: List[Job], slots: int) -> List[Job]:
        """The scheduling-round pick (caller holds the lock): up to
        ``slots`` jobs from ``eligible`` (submission-ordered runnable
        batch jobs), chosen by

        1. **tenant in-flight quota** — a tenant at its ``max_inflight``
           is skipped this round (its jobs stay queued, costing nothing);
        2. **aging** — any job past its aged bound (:meth:`_aged`) is
           picked first, oldest first (counter ``aged_picks``): EDF
           churn or a heavier sibling class can never starve an
           admitted job beyond the documented bound;
        3. **weighted fair share** — stride scheduling across classes:
           the class with the lowest pass (``served / weight``) among
           those with runnable jobs wins the slot, so under sustained
           contention class c receives ``w_c / Σ w`` of the slots. A
           class with nothing runnable forfeits the credit it would
           accrue while idle (its pass floor ratchets to the active
           minimum) — returning traffic resumes at fair share instead
           of bursting on banked credit;
        4. **EDF within the class** — earliest absolute deadline first,
           deadline-less jobs last, FIFO as the tiebreak."""
        picks: List[Job] = []
        if not eligible or slots <= 0:
            return picks
        inflight: Dict[str, int] = {}
        for j in self._jobs.values():
            if j.kind == "batch" and j.status == "running":
                inflight[j.tenant] = inflight.get(j.tenant, 0) + 1
        fifo = {id(job): i for i, job in enumerate(eligible)}
        now_unix = time.time()
        remaining = list(eligible)
        while len(picks) < slots and remaining:
            candidates = []
            for job in remaining:
                cap = self._tenant_quota(job.tenant)["max_inflight"]
                if cap is not None and inflight.get(job.tenant, 0) >= cap:
                    continue
                candidates.append(job)
            if not candidates:
                break
            aged = [j for j in candidates if self._aged(j, now_unix)]
            if aged:
                job = min(
                    aged,
                    key=lambda j: (j.created_unix_ts, fifo[id(j)]),
                )
                self._counters.inc("aged_picks")
            else:
                by_class: Dict[str, List[Job]] = {}
                for j in candidates:
                    by_class.setdefault(j.priority, []).append(j)

                def eff_pass(cls: str) -> float:
                    w = self._class_weights.get(cls, 1.0)
                    return max(
                        self._qos_served.get(cls, 0) / w,
                        self._qos_floor.get(cls, 0.0),
                    )

                min_active = min(eff_pass(c) for c in by_class)
                for cls in self._class_weights:
                    if cls not in by_class:
                        self._qos_floor[cls] = max(
                            self._qos_floor.get(cls, 0.0), min_active
                        )
                cls = min(
                    by_class,
                    key=lambda c: (
                        eff_pass(c), -self._class_weights.get(c, 1.0)
                    ),
                )
                job = min(
                    by_class[cls],
                    key=lambda j: (
                        self._edf_deadline(j),
                        j.created_unix_ts,
                        fifo[id(j)],
                    ),
                )
            self._qos_served[job.priority] = (
                self._qos_served.get(job.priority, 0) + 1
            )
            inflight[job.tenant] = inflight.get(job.tenant, 0) + 1
            picks.append(job)
            remaining.remove(job)
        return picks

    def _mux_partition(self, to_start: List[Job]) -> List[List[Job]]:
        """Partition a scheduling round's picks into mux groups (same
        spec, up to ``mux_k`` lanes) and solo singletons. Caller holds
        the lock (the eligibility checks read breaker state).

        Grouping rules (docs/service.md "Batched scheduling"): the
        batching is opt-in (``mux_k > 1``), device-path only (an open
        breaker's host fallback stays solo), spec families must be
        statically mux-eligible (``registry.MUX_FAMILIES`` — shipped
        families only; the worker still verifies at resolve time and
        falls back to sequential drive on a typed ``MuxError``), and a
        member whose previous mux attempt faulted retries solo
        (``_mux_solo``). Migration seeds (``seed_checkpoint``) stay solo
        too: a migrated-in job's adopted rotation can arrive at grown
        capacities the fresh sibling lanes don't share. Groups form
        WITHIN a priority class and symmetry mode ((spec, priority,
        symmetry) key — lanes must agree on the canonicalization tag,
        xla_mux._check_lanes): the group budget
        is the tightest member's, and batching across classes would let
        a best-effort lane ride — and clip — an interactive dispatch's
        budget (docs/service.md "QoS & overload")."""
        if self._cfg.mux_k <= 1 or self._breaker != "closed":
            return [[job] for job in to_start]

        def eligible(job: Job) -> bool:
            if job.engine_force is not None or job.seed_checkpoint:
                return False
            if job._mux_solo:
                return False
            try:
                family = registry.parse(job.spec)[0]
            except ValueError:  # pragma: no cover - admission validated
                return False
            return family in registry.MUX_FAMILIES

        groups: List[List[Job]] = []
        by_spec: Dict[Any, List[Job]] = {}
        for job in to_start:
            if eligible(job):
                by_spec.setdefault(
                    (job.spec, job.priority, job.symmetry), []
                ).append(job)
            else:
                groups.append([job])
        for members in by_spec.values():
            for at in range(0, len(members), self._cfg.mux_k):
                groups.append(members[at:at + self._cfg.mux_k])
        return groups

    def _worker_env(self, job: Job, device: bool) -> Dict[str, str]:
        env = dict(os.environ)
        # Scrub inherited run-trace/recovery env: per-job artifacts must
        # never alias an outer run's files.
        for key in (
            "STPU_TRACE", "STPU_TRACE_CHROME", "STPU_TRACE_CTX",
            "STPU_HEARTBEAT",
            "STPU_CHECKPOINT_TO", "STPU_CHECKPOINT_EVERY",
            "STPU_CHECKPOINT_KEEP", "STPU_METRICS_TO",
            "STPU_METRICS_EVERY", "STPU_METRICS_KEEP",
        ):
            env.pop(key, None)
        if device:
            env["STPU_TRACE"] = job.trace_path
        if job.symmetry is not None:
            # The per-job mode beats the pool's inherited STPU_SYMMETRY
            # (None inherits — symmetry is a plain env knob otherwise).
            env["STPU_SYMMETRY"] = job.symmetry
        env["STPU_COMPILE_CACHE"] = self._cfg.compile_cache
        if self._cfg.chaos:
            # The config's chaos plan rides into every worker (each
            # process replays its own deterministic schedule); a plain
            # env STPU_CHAOS inherits anyway, like any other knob.
            env["STPU_CHAOS"] = self._cfg.chaos
        return env

    def _run_job(self, job: Job) -> None:
        """One supervised attempt of ``job``; classification + requeue
        decisions happen under the lock afterwards. Any unexpected
        exception settles the job as failed — a job stuck in "running"
        with no thread behind it would consume a ``max_inflight`` slot
        forever and hang its waiters."""
        try:
            self._run_job_inner(job)
        except Exception as e:  # noqa: BLE001 - the verdict IS the handling
            with self._cond:
                job._proc = None
                if job.status == "migrated":  # the fleet owns it now
                    self._cond.notify_all()
                    return
                job.status = "failed"
                job.error = f"supervisor error: {type(e).__name__}: {e}"
                job.completed_unix_ts = time.time()
                self._counters.inc("jobs_failed")
                self._record_drain(job.priority)
                self._jlog(
                    "completed", job=job.id, status="failed",
                    error=job.error, result=None,
                )
                self._cond.notify_all()

    def _run_job_inner(self, job: Job) -> None:
        cfg = self._cfg
        with self._cond:
            if job.status == "migrated":
                # Evacuated between the scheduler's pick and this
                # attempt: the sibling pool owns the job now — spawning
                # a worker here would run the condemned device anyway
                # (and settle/charge a job this pool no longer owns).
                self._cond.notify_all()
                return
        attempt = len(job.attempts)
        device = self._breaker == "closed" and job.engine_force != "host"
        if (
            not device
            and job.engine_force != "host"
            and cfg.breaker_mode == "halt"
        ):
            # Halt-mode race guard: the breaker tripped between the
            # scheduler's pick and here. Re-queue for the fleet to
            # migrate instead of silently degrading to the host engine.
            with self._cond:
                if job.status == "running":
                    job.status = "queued"
                self._cond.notify_all()
            return
        engine = "xla" if device else "host"
        remaining = job.max_seconds - job.consumed_s
        if remaining <= 0:
            with self._cond:
                job.status = "failed"
                job.error = "wall-clock budget exhausted"
                job.completed_unix_ts = time.time()
                self._counters.inc("jobs_failed")
                self._record_drain(job.priority)
                self._jlog(
                    "completed", job=job.id, status="failed",
                    error=job.error, result=None,
                )
                self._cond.notify_all()
            return
        resume = (
            latest_valid_checkpoint(job.checkpoint_path) if device else None
        )
        if resume is None and device and job.seed_checkpoint:
            # Migration seed: no rotation of our own yet — adopt (and
            # re-verify) the sibling pool's rotation the fleet handed us.
            resume = latest_valid_checkpoint(job.seed_checkpoint)
        argv = [
            sys.executable, _WORKER,
            "--spec", job.spec,
            "--engine", engine,
            "--platform", cfg.platform if device else "cpu",
            "--out", job._path("result.json"),
            "--block-size", str(cfg.block_size),
            "--max-seconds", str(remaining),
        ]
        if device:
            argv += [
                "--checkpoint", job.checkpoint_path,
                "--every", str(cfg.checkpoint_every),
                "--keep", str(cfg.checkpoint_keep),
                "--metrics", job.metrics_path,
            ]
            if cfg.device_ordinal is not None:
                argv += ["--device", str(cfg.device_ordinal)]
            if resume:
                argv += ["--resume", resume]
        if job.max_states:
            argv += ["--max-states", str(job.max_states)]
        for flag, key in (
            ("--chaos-die-at-depth", "die_at_depth"),
            ("--chaos-freeze-at-depth", "freeze_at_depth"),
            ("--chaos-marker", "marker"),
        ):
            if job.chaos.get(key) is not None:
                argv += [flag, str(job.chaos[key])]

        def on_spawn(proc):
            # close() snapshots live procs under the lock; a worker that
            # spawns in the close race is killed HERE instead of running
            # unsupervised for its whole budget after the pool is gone.
            # The journaled pid is the restart-recovery orphan handle: a
            # pool killed -9 here leaves this worker running (its own
            # session), and the next incarnation kills it by this record
            # before re-scheduling the job.
            with self._cond:
                job._proc = proc
                closed = self._closed
                migrated = job.status == "migrated"
                if not migrated:
                    # An evacuated job must not append `started` after
                    # its `evacuated` record: replay would read the
                    # journal-ordering race as a live attempt.
                    self._jlog(
                        "started", job=job.id, attempt=attempt,
                        engine=engine, resumed_from=resume, pid=proc.pid,
                        trace_id=job.trace_id,
                    )
            if closed or migrated:
                sup._kill_group(proc)

        with self._cond:
            if self._closed:
                job.status = "failed"
                job.error = "service closed"
                self._counters.inc("jobs_failed")
                self._cond.notify_all()
                return
            if job.status == "migrated":
                # Evacuate raced us between the top-of-attempt check and
                # here: the sibling owns the job — don't spawn.
                self._cond.notify_all()
                return
            job.engine = engine
            job.resumed_from = resume
            job._attempt_t0 = time.monotonic()
            if not device:
                job.degraded = True
        self.log(f"{job.id} attempt {attempt} engine={engine} resume={resume}")
        res = sup.run_worker(
            argv,
            heartbeat=job._path("hb.json") if device else None,
            # Verdict ordering contract: the worker's soft budget exit
            # (rc 3) fires first; a wedge that starts ANY time inside the
            # budget draws its heartbeat-staleness verdict (<= stall_s x
            # the 3x compile leash after onset) before the hard timeout,
            # which only backstops a worker that can neither reach a
            # quiescent point nor be diagnosed by heartbeat. Without the
            # stall headroom here, a production-default pool (600s budget,
            # 1200s stall) would misread every wedge as budget exhaustion
            # — no requeue, no breaker evidence.
            timeout_s=remaining * 1.5 + 60.0 + cfg.stall_s * 3.0,
            stall_s=cfg.stall_s,
            startup_grace_s=cfg.startup_grace_s,
            poll_s=cfg.poll_s,
            env=self._worker_env(job, device),
            stdout_path=job._path(f"worker{attempt}.out"),
            log=self.log,
            on_spawn=on_spawn,
            tracer=self._tracer,
            trace_ctx=(job.trace_id, job._root_sid) if job.trace_id else None,
            trace_attrs={"job": job.id, "attempt": attempt, "engine": engine},
        )
        result = None
        if res.ok:
            try:
                with open(job._path("result.json")) as fh:
                    result = json.load(fh)
            except (OSError, json.JSONDecodeError):
                result = None
        with self._cond:
            job._proc = None
            job._attempt_t0 = None
            if job.status == "migrated":
                # The fleet evacuated this job while its worker ran (and
                # killed the worker group): the sibling pool owns it now —
                # no settlement, no budget charge (evacuate() already
                # captured the live attempt's wall-clock), no requeue.
                self._cond.notify_all()
                return
            # Wedge time is the DEVICE's fault, not the tenant's demand:
            # charging it would make the requeued attempt start with a
            # drained budget and fail as "budget exhausted" instead of
            # resuming. Crashes still charge — the compute was real and
            # checkpointed.
            if not res.wedged:
                job.consumed_s += res.seconds
            job.attempts.append(
                {
                    "rc": res.rc,
                    "killed": res.killed,
                    "seconds": res.seconds,
                    "engine": engine,
                    "wedged": res.wedged,
                    "resumed_from": resume,
                }
            )
            self._jlog(
                "budget_charged", job=job.id, seconds=res.seconds,
                consumed_s=job.consumed_s, charged=not res.wedged,
            )
            if self._closed:
                # Settles the in-memory waiters only — deliberately NOT
                # journaled as completed: a durable pool's unfinished
                # work stays queued in the journal for the next
                # incarnation (docs/service.md "Durability & recovery").
                job.status = "failed"
                job.error = "service closed"
                self._counters.inc("jobs_failed")
                self._cond.notify_all()
                return
            if result is not None:
                job.status = "done"
                job.result = result
                job.completed_unix_ts = time.time()
                if result.get("degraded"):
                    job.degraded = True
                    self._counters.inc("degraded_jobs")
                self._counters.inc("jobs_done")
                self._record_drain(job.priority)
                if device:
                    self._consecutive_wedges = 0
                self._jlog(
                    "completed", job=job.id, status="done", error=None,
                    result=job.persist()["result"],
                )
                self._sweep_artifacts()
            elif res.wedged:
                self._counters.inc("wedge_verdicts")
                job.wedges += 1
                self._record_wedge()
                self._requeue_or_fail(
                    job, f"wedge verdict: {res.killed}", wedged=True
                )
            elif res.crashed:
                self._counters.inc("crashes")
                self._requeue_or_fail(
                    job, f"worker died by signal (rc={res.rc})", wedged=False
                )
            elif res.killed is not None or res.rc == 3:
                job.status = "failed"
                job.error = "wall-clock budget exhausted"
                job.completed_unix_ts = time.time()
                self._counters.inc("jobs_failed")
                self._record_drain(job.priority)
                self._jlog(
                    "completed", job=job.id, status="failed",
                    error=job.error, result=None,
                )
            else:
                job.status = "failed"
                job.error = f"worker exited rc={res.rc}"
                job.completed_unix_ts = time.time()
                self._counters.inc("jobs_failed")
                self._record_drain(job.priority)
                self._jlog(
                    "completed", job=job.id, status="failed",
                    error=job.error, result=None,
                )
            self._cond.notify_all()

    def _run_mux_group(self, jobs: List[Job]) -> None:
        """One supervised multiplexed attempt of ``jobs`` (same spec,
        one ``worker.py --mux`` process; docs/service.md "Batched
        scheduling"). Mirrors :meth:`_run_job`'s crash contract: any
        unexpected supervisor exception settles every still-owned member
        as failed rather than leaking ``max_inflight`` slots."""
        try:
            self._run_mux_group_inner(jobs)
        except Exception as e:  # noqa: BLE001 - the verdict IS the handling
            with self._cond:
                for job in jobs:
                    job._proc = None
                    job._mux_hb = None
                    if job.status != "running":
                        continue
                    job.status = "failed"
                    job.error = f"supervisor error: {type(e).__name__}: {e}"
                    job.completed_unix_ts = time.time()
                    self._counters.inc("jobs_failed")
                    self._record_drain(job.priority)
                    self._jlog(
                        "completed", job=job.id, status="failed",
                        error=job.error, result=None,
                    )
                self._cond.notify_all()

    def _run_mux_group_inner(self, jobs: List[Job]) -> None:
        cfg = self._cfg
        lead = jobs[0]
        spec = lead.spec
        attempts = {job.id: len(job.attempts) for job in jobs}
        gid = f"mux-{lead.id}-a{attempts[lead.id]}"

        def requeue_solo(members: List[Job]) -> None:
            # Back to the queue WITHOUT burning a requeue: these members
            # did nothing wrong — the batch (breaker race, a sibling's
            # exhausted budget) did. The journal needs no extra event: a
            # `started` with no terminal already replays as requeue.
            for job in members:
                if job.status == "running":
                    job.status = "queued"
                    job._mux_solo = True

        with self._cond:
            jobs = [j for j in jobs if j.status == "running"]
            if not jobs:
                self._cond.notify_all()
                return
        device = self._breaker == "closed"
        if not device:
            # The breaker tripped between the scheduler's pick and here:
            # batching is a device-path optimization — hand the members
            # back for the solo path's host-fallback/halt semantics.
            with self._cond:
                requeue_solo(jobs)
                self._cond.notify_all()
            return
        live: List[Job] = []
        with self._cond:
            for job in jobs:
                if job.status != "running":
                    continue
                if job.max_seconds - job.consumed_s <= 0:
                    job.status = "failed"
                    job.error = "wall-clock budget exhausted"
                    job.completed_unix_ts = time.time()
                    self._counters.inc("jobs_failed")
                    self._record_drain(job.priority)
                    self._jlog(
                        "completed", job=job.id, status="failed",
                        error=job.error, result=None,
                    )
                    continue
                live.append(job)
            self._cond.notify_all()
        jobs = live
        if not jobs:
            return
        # The group budget is the tightest member's remaining wall-clock:
        # the batch never overruns ANY member. A sibling with budget left
        # when the soft exit fires re-queues uncharged-requeue (below).
        remaining = min(job.max_seconds - job.consumed_s for job in jobs)
        resumes = {
            job.id: latest_valid_checkpoint(job.checkpoint_path)
            for job in jobs
        }
        manifest = {
            "group": gid,
            "spec": spec,
            "lanes": [
                {
                    "job": job.id,
                    "out": job._path("result.json"),
                    "checkpoint": job.checkpoint_path,
                    "metrics": job.metrics_path,
                    "resume": resumes[job.id],
                    "max_states": job.max_states,
                    "trace_id": job.trace_id,
                    "chaos": {
                        key: job.chaos.get(key)
                        for key in ("die_at_depth", "freeze_at_depth", "marker")
                    },
                }
                for job in jobs
            ],
        }
        manifest_path = lead._path(f"mux-manifest-a{attempts[lead.id]}.json")
        tmp = manifest_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(manifest, fh)
        os.replace(tmp, manifest_path)
        hb_path = lead._path("mux-hb.json")
        argv = [
            sys.executable, _WORKER,
            "--mux", manifest_path,
            "--spec", spec,
            "--engine", "xla",
            "--platform", cfg.platform,
            "--out", lead._path("mux-result.json"),
            "--every", str(cfg.checkpoint_every),
            "--keep", str(cfg.checkpoint_keep),
            "--max-seconds", str(remaining),
        ]
        if cfg.device_ordinal is not None:
            argv += ["--device", str(cfg.device_ordinal)]

        def on_spawn(proc):
            # Same close/evacuate race contract as the solo path — every
            # member carries the (shared) proc handle so close() and
            # evacuate() kill the batch through any member, and every
            # member journals its own `started` (the mux provenance keys
            # ride along; replay ignores unknown keys).
            with self._cond:
                closed = self._closed
                migrated = False
                for job in jobs:
                    job._proc = proc
                    if job.status == "migrated":
                        migrated = True
                        continue
                    self._jlog(
                        "started", job=job.id, attempt=attempts[job.id],
                        engine="xla", resumed_from=resumes[job.id],
                        pid=proc.pid, mux_group=gid, mux_lanes=len(jobs),
                        trace_id=job.trace_id,
                    )
            if closed or migrated:
                sup._kill_group(proc)

        with self._cond:
            if self._closed:
                for job in jobs:
                    if job.status != "running":
                        continue
                    job.status = "failed"
                    job.error = "service closed"
                    self._counters.inc("jobs_failed")
                self._cond.notify_all()
                return
            if any(job.status == "migrated" for job in jobs):
                # Evacuate raced the spawn: the whole pool is condemned
                # (evacuate sweeps every non-terminal batch job) — don't
                # start a worker on the dead device.
                self._cond.notify_all()
                return
            self._counters.inc("mux_groups")
            self._counters.inc("mux_lanes", len(jobs))
            now = time.monotonic()
            for i, job in enumerate(jobs):
                job.engine = "xla"
                job.resumed_from = resumes[job.id]
                job._attempt_t0 = now
                job._mux_hb = hb_path
                job.mux = {"group": gid, "lanes": len(jobs), "lane": i}
        self.log(
            f"{gid} lanes={[j.id for j in jobs]} attempt engine=xla"
        )
        res = sup.run_worker(
            argv,
            heartbeat=hb_path,
            # Same verdict-ordering contract as the solo path: soft
            # budget exit first, heartbeat wedge verdict second, hard
            # timeout as the backstop.
            timeout_s=remaining * 1.5 + 60.0 + cfg.stall_s * 3.0,
            stall_s=cfg.stall_s,
            startup_grace_s=cfg.startup_grace_s,
            poll_s=cfg.poll_s,
            env=self._worker_env(lead, True),
            stdout_path=lead._path(f"mux-worker{attempts[lead.id]}.out"),
            log=self.log,
            on_spawn=on_spawn,
            tracer=self._tracer,
            trace_ctx=(
                (lead.trace_id, lead._root_sid) if lead.trace_id else None
            ),
            trace_attrs={
                "job": lead.id, "group": gid,
                "lanes": len(jobs), "engine": "xla",
            },
        )
        summary = None
        try:
            with open(lead._path("mux-result.json")) as fh:
                summary = json.load(fh)
        except (OSError, json.JSONDecodeError):
            summary = None
        results: Dict[str, Any] = {}
        for job in jobs:
            # Per-lane results are written the moment a lane finishes —
            # read them even when the worker died: finished members
            # settle done across a mid-batch crash (a stale file cannot
            # exist: a member with a result would have settled done).
            try:
                with open(job._path("result.json")) as fh:
                    results[job.id] = json.load(fh)
            except (OSError, json.JSONDecodeError):
                pass
        with self._cond:
            for job in jobs:
                job._proc = None
                job._attempt_t0 = None
                job._mux_hb = None
            live = [j for j in jobs if j.status != "migrated"]
            if not live:
                # Evacuated mid-attempt (the fleet killed the worker):
                # the siblings own every member now.
                self._cond.notify_all()
                return
            if summary is not None:
                self._counters.inc(
                    "mux_dispatches_saved",
                    int(summary.get("dispatches_saved") or 0),
                )
            for job in live:
                # Budget: a finished lane's charge is ITS lane wall-clock
                # (the worker stamps per-lane seconds); an unfinished
                # member rode the whole attempt. Wedge time stays
                # uncharged, exactly the solo contract.
                seconds = (
                    results[job.id].get("seconds", res.seconds)
                    if job.id in results
                    else res.seconds
                )
                if not res.wedged:
                    job.consumed_s += float(seconds)
                job.attempts.append(
                    {
                        "rc": res.rc,
                        "killed": res.killed,
                        "seconds": seconds,
                        "engine": "xla",
                        "wedged": res.wedged,
                        "resumed_from": resumes[job.id],
                        "mux_group": gid,
                    }
                )
                self._jlog(
                    "budget_charged", job=job.id, seconds=seconds,
                    consumed_s=job.consumed_s, charged=not res.wedged,
                )
            if self._closed:
                for job in live:
                    job.status = "failed"
                    job.error = "service closed"
                    self._counters.inc("jobs_failed")
                self._cond.notify_all()
                return
            finished = [j for j in live if j.id in results]
            unfinished = [j for j in live if j.id not in results]
            for job in finished:
                job.status = "done"
                job.result = results[job.id]
                job.completed_unix_ts = time.time()
                self._counters.inc("jobs_done")
                self._record_drain(job.priority)
                self._jlog(
                    "completed", job=job.id, status="done", error=None,
                    result=job.persist()["result"],
                )
            if finished:
                self._consecutive_wedges = 0
                self._sweep_artifacts()
            if unfinished:
                for job in unfinished:
                    job._mux_solo = True
                if res.wedged:
                    # ONE device incident (one worker, one wedge) for the
                    # breaker's evidence; each member still records the
                    # wedged attempt it rode.
                    self._counters.inc("wedge_verdicts")
                    self._record_wedge()
                    for job in unfinished:
                        job.wedges += 1
                        self._requeue_or_fail(
                            job, f"mux wedge verdict: {res.killed}",
                            wedged=True,
                        )
                elif res.crashed:
                    self._counters.inc("crashes")
                    for job in unfinished:
                        self._requeue_or_fail(
                            job,
                            f"mux worker died by signal (rc={res.rc})",
                            wedged=False,
                        )
                elif res.killed is not None or res.rc == 3:
                    # The GROUP budget (the tightest member) expired.
                    # Members whose own budget is spent fail; siblings
                    # with wall-clock left retry solo, no requeue burned.
                    for job in unfinished:
                        if job.max_seconds - job.consumed_s <= 0:
                            job.status = "failed"
                            job.error = "wall-clock budget exhausted"
                            job.completed_unix_ts = time.time()
                            self._counters.inc("jobs_failed")
                            self._record_drain(job.priority)
                            self._jlog(
                                "completed", job=job.id, status="failed",
                                error=job.error, result=None,
                            )
                        else:
                            requeue_solo([job])
                else:
                    for job in unfinished:
                        job.status = "failed"
                        job.error = f"mux worker exited rc={res.rc}"
                        job.completed_unix_ts = time.time()
                        self._counters.inc("jobs_failed")
                        self._record_drain(job.priority)
                        self._jlog(
                            "completed", job=job.id, status="failed",
                            error=job.error, result=None,
                        )
            self._cond.notify_all()

    def _requeue_or_fail(
        self, job: Job, reason: str, *, wedged: bool = False
    ) -> None:
        """Quarantine-and-requeue with exponential backoff, up to the
        requeue limit. Caller holds the lock.

        Halt-mode override: a WEDGE at the requeue limit while the
        breaker is open does not fail the job — the device is the
        condemned party, not the tenant, and the fleet is about to
        migrate the pool's jobs to healthy silicon. The job holds
        quarantined (no extra requeue charged) for evacuation; crashes
        and every verdict on a closed breaker keep the single-pool
        contract."""
        hold = (
            wedged
            and self._cfg.breaker_mode == "halt"
            and self._breaker == "open"
            and job.requeues >= self._cfg.requeue_limit
        )
        if job.requeues < self._cfg.requeue_limit or hold:
            if not hold:
                job.requeues += 1
                self._counters.inc("requeues")
            job.status = "quarantined"
            delay = sup.backoff_delay(job.requeues, self._cfg.backoff_s)
            job.requeue_at = time.monotonic() + delay
            if job.dir is not None and (
                os.path.exists(job.checkpoint_path)
                or os.path.exists(job.checkpoint_path + ".1")
            ):
                # The re-adoptable resume pointer (provenance — the next
                # attempt, this incarnation's or a restarted one's,
                # re-resolves latest_valid_checkpoint itself).
                self._jlog(
                    "checkpointed", job=job.id,
                    path=os.path.relpath(
                        job.checkpoint_path, self._cfg.run_dir
                    ),
                )
            self._jlog(
                "quarantined", job=job.id, reason=reason, wedged=wedged,
                requeues=job.requeues, wedges=job.wedges,
                release_in_s=delay,
            )
            self.log(f"{job.id} quarantined ({reason})")
        else:
            job.status = "failed"
            job.error = f"{reason}; requeue limit reached"
            job.completed_unix_ts = time.time()
            self._counters.inc("jobs_failed")
            self._record_drain(job.priority)
            self._jlog(
                "completed", job=job.id, status="failed",
                error=job.error, result=None,
            )

    # -- fleet migration (service/fleet.py) --------------------------------

    def evacuate(self, *, reason: str = "device lost") -> List[Job]:
        """Reclassify every non-terminal batch job as ``migrated`` —
        terminal for THIS pool, journaled as ``evacuated`` so a pool
        restart never requeues it here — and kill any live worker process
        group. Returns the evacuated jobs; each carries everything a
        healthy sibling pool needs to resume it (spec, budgets,
        ``consumed_s`` updated with the live attempt's wall-clock,
        requeue history, and checkpoint rotations still on disk in its
        job dir). The FleetService is the only intended caller: it
        resubmits each to a sibling with ``spent_s=``/``resume_from=``."""
        procs = []
        out: List[Job] = []
        now = time.monotonic()
        with self._cond:
            for jid in self._order:
                job = self._jobs[jid]
                if job.kind != "batch" or job.done:
                    continue
                if job.engine_force == "host":
                    # Forced-host work is device-independent: killing it
                    # would discard progress no checkpoint can restore
                    # (host attempts don't checkpoint) for zero safety
                    # gain — the dead device was never involved.
                    continue
                if job.status == "running" and job._attempt_t0 is not None:
                    # The live attempt's spend: run_worker has not
                    # returned (we are about to kill it), so charge the
                    # elapsed wall-clock here — the sibling must not get
                    # a budget refund out of the migration.
                    job.consumed_s += max(0.0, now - job._attempt_t0)
                    job._attempt_t0 = None
                if job._proc is not None and job._proc.poll() is None:
                    procs.append(job._proc)
                job.status = "migrated"
                job.error = reason
                job.completed_unix_ts = time.time()
                self._counters.inc("jobs_evacuated")
                self._jlog(
                    "evacuated", job=job.id, reason=reason,
                    consumed_s=job.consumed_s,
                )
                out.append(job)
            self._cond.notify_all()
        for proc in procs:
            sup._kill_group(proc)
        return out

    # -- breaker -----------------------------------------------------------

    def _notify_breaker_listener(self, state: str) -> None:
        """Fire the fleet's breaker listener from a fresh thread — the
        trip/close sites hold the pool lock, and the listener (migration
        scheduling) takes fleet locks of its own."""
        listener = self._cfg.breaker_listener
        if listener is not None:
            threading.Thread(
                target=listener, args=(state,),
                name="stpu-breaker-listener", daemon=True,
            ).start()

    def _record_wedge(self) -> None:
        """Caller holds the lock."""
        self._consecutive_wedges += 1
        if (
            self._breaker == "closed"
            and self._consecutive_wedges >= self._cfg.breaker_k
        ):
            self._breaker = "open"
            self._breaker_opened_unix_ts = time.time()
            self._counters.inc("breaker_trips")
            self._jlog(
                "breaker_tripped", consecutive=self._consecutive_wedges
            )
            self.log(
                f"breaker OPEN after {self._consecutive_wedges} consecutive "
                "wedge verdicts; "
                + (
                    "holding queued jobs for fleet migration"
                    if self._cfg.breaker_mode == "halt"
                    else "routing jobs to the host engine"
                )
            )
            self._notify_breaker_listener("open")
            if self._cfg.probe_auto:
                self._start_prober()

    @property
    def degraded(self) -> bool:
        """Whether the breaker is open (new work routes to the host
        engine)."""
        return self._breaker == "open"

    def probe_device_now(self) -> bool:
        """One device-liveness probe (a watchdogged subprocess — the
        service process never touches jax); on success while the breaker
        is open, closes it. The background prober calls this on
        ``probe_interval_s``; tests and operators call it directly."""
        argv = list(
            self._cfg.probe_argv
            or [sys.executable, "-c", "import jax; jax.devices()"]
        )
        with self._lock:  # Counters.inc is not atomic; every mutation locks
            self._counters.inc("device_probes")
        try:
            rc = subprocess.run(
                argv,
                timeout=self._cfg.probe_timeout_s,
                capture_output=True,
            ).returncode
        except (subprocess.TimeoutExpired, OSError):
            rc = None
        ok = rc == 0
        closed_now = False
        with self._cond:
            if ok and self._breaker == "open":
                self._breaker = "closed"
                self._breaker_opened_unix_ts = None
                self._consecutive_wedges = 0
                self._counters.inc("breaker_closes")
                self._jlog("breaker_closed")
                self.log("breaker CLOSED (device probe healthy)")
                closed_now = True
                self._cond.notify_all()
        if closed_now:
            self._notify_breaker_listener("closed")
        return ok

    def _probe_loop(self) -> None:
        while True:
            deadline = time.monotonic() + self._cfg.probe_interval_s
            with self._cond:
                while not self._closed and time.monotonic() < deadline:
                    if self._breaker == "closed":
                        return
                    self._cond.wait(timeout=min(
                        1.0, deadline - time.monotonic()
                    ))
                if self._closed or self._breaker == "closed":
                    return
            self.probe_device_now()

    # -- status surface ----------------------------------------------------

    def job(self, job_id: str) -> Job:
        return self._jobs[job_id]

    def jobs(self) -> List[Job]:
        with self._lock:
            return [self._jobs[jid] for jid in self._order]

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Blocks until every batch job is terminal."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while any(
                not j.done for j in self._jobs.values() if j.kind == "batch"
            ):
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    @property
    def run_dir(self) -> str:
        return self._cfg.run_dir

    def merged_trace_chrome(self, out_path: Optional[str] = None) -> Optional[str]:
        """The whole pool's merged distributed-trace timeline
        (``obs.collect`` over the run dir: service + every job/lane span
        file, flow arrows per trace id) as Perfetto-loadable Chrome trace
        JSON; returns the output path, or None when nothing traced.
        Mtime-cached like :meth:`job_trace_chrome` — the Explorer's
        ``GET /.trace.json`` polls this."""
        from ..obs import collect as collect_mod

        files = collect_mod.trace_files(self._cfg.run_dir)
        if not files:
            return None
        dst = out_path or os.path.join(self._cfg.run_dir, "trace.merged.json")
        try:
            dst_m = os.stat(dst).st_mtime
            fresh = all(os.stat(p).st_mtime <= dst_m for p in files)
        except OSError:
            fresh = False
        if not fresh:
            collect_mod.write(self._cfg.run_dir, dst)
        return dst

    def _qos_gauges(self) -> Dict[str, Any]:
        """The per-class / per-tenant QoS breakdown (caller holds the
        lock): ``gauges()``'s ``"qos"`` dict — the dashboard's class
        tiles and the ``/.metrics`` ``class=``/``tenant=`` labeled
        samples render from it (docs/observability.md)."""
        classes: Dict[str, Dict[str, Any]] = {
            cls: {
                "queued": 0, "running": 0, "quarantined": 0,
                "done": 0, "failed": 0, "migrated": 0,
                "weight": self._class_weights.get(cls, 1.0),
                "served": self._qos_served.get(cls, 0),
                "drain_per_s": self._drain_rate(cls),
            }
            for cls in PRIORITY_CLASSES
        }
        tenants: Dict[str, Dict[str, Any]] = {}
        for j in self._jobs.values():
            if j.kind != "batch":
                continue
            row = classes.get(j.priority)
            if row is not None and j.status in row:
                row[j.status] += 1
            t = tenants.setdefault(
                j.tenant,
                {"queued": 0, "running": 0, "done": 0, "failed": 0,
                 "spent_s": 0.0},
            )
            if j.status in ("queued", "quarantined"):
                t["queued"] += 1
            elif j.status in t:
                t[j.status] += 1
            t["spent_s"] = round(t["spent_s"] + j.consumed_s, 3)
        return {
            "classes": classes,
            "tenants": tenants,
            "aging_s": self._cfg.qos_aging_s,
            "drain_per_s": self._drain_rate(),
        }

    def gauges(self) -> Dict[str, Any]:
        """The pool-wide snapshot without per-job payloads — what the
        Explorer embeds under ``/.status``'s ``"pool"`` key."""
        with self._lock:
            counts = self._counts()
            return {
                **counts,
                "qos": self._qos_gauges(),
                "device": self._cfg.device,
                "max_inflight": self._cfg.max_inflight,
                "max_queue": self._cfg.max_queue,
                "max_sessions": self._cfg.max_sessions,
                "breaker": {
                    "state": self._breaker,
                    "consecutive_wedges": self._consecutive_wedges,
                    "k": self._cfg.breaker_k,
                    "opened_unix_ts": self._breaker_opened_unix_ts,
                },
                # Durability provenance (docs/service.md): the journal's
                # position and — after a restart — what the replay
                # restored; surfaces in the Explorer's /.pool unchanged.
                "journal": (
                    None
                    if self._journal is None
                    else {
                        "path": self._journal.path,
                        "records": self._journal.seq,
                        "since_compact": self._journal.since_compact,
                        "recovery": self._recovery,
                    }
                ),
                **self._counters.snapshot(),
            }

    def metrics(self) -> Dict[str, Any]:
        """Pool gauges plus per-job status snapshots (the full service
        status surface; per-job engine metrics via ``Job.metrics()``)."""
        out = self.gauges()
        with self._lock:
            out["jobs"] = {
                jid: self._jobs[jid].snapshot() for jid in self._order
            }
        return out

    def job_metrics_series(
        self, job_id: str, window: Optional[int] = None
    ) -> Optional[List[Dict[str, Any]]]:
        """A batch job's recorded metrics time-series (the per-job
        ``metrics.jsonl`` the worker samples at quiescent superstep
        boundaries; docs/observability.md "Time series"), newest-``window``
        rows, oldest first. None when the job never produced a series
        (host-engine jobs, swept artifacts) or is interactive (live
        checkers are polled, not recorded — the Explorer samples those
        itself). Raises ``KeyError`` on an unknown job id."""
        from ..obs import read_series

        job = self._jobs[job_id]
        if job.dir is None or not os.path.exists(job.metrics_path):
            return None
        return read_series(job.metrics_path, window=window)

    def job_trace_chrome(self, job_id: str,
                         out_path: Optional[str] = None) -> Optional[str]:
        """Exports a job's span trace as Perfetto-loadable Chrome trace
        JSON (``obs.export_chrome``); returns the output path, or None when
        the job never produced a trace (host-engine jobs don't)."""
        job = self._jobs[job_id]
        if job.dir is None or not os.path.exists(job.trace_path):
            return None
        dst = out_path or job._path("trace.chrome.json")
        try:
            fresh = os.stat(dst).st_mtime >= os.stat(job.trace_path).st_mtime
        except OSError:
            fresh = False
        if not fresh:
            # Re-export only when the append-only source advanced — a
            # polled trace endpoint must not re-parse the whole JSONL per
            # request.
            export_chrome(job.trace_path, dst)
        return dst
