"""Checking-as-a-service: a multi-tenant pool over one device.

:class:`CheckerService` owns the device and serves N concurrent checking
jobs — batch jobs in supervised worker subprocesses (per-job heartbeat,
auto-checkpoint, span trace; a wedge quarantines one job, never the pool)
and interactive Explorer sessions as registered in-process clients —
behind admission control, with a breaker that degrades the pool to the
host engine instead of dying. :class:`FleetService` fronts N such pools
— one per device — with least-loaded routing, per-device breaker state,
and failover migration (``service/fleet.py``). See ``docs/service.md``;
chaos pins in ``tests/test_service.py``.
"""

from .core import (
    SERVICE_COUNTERS,
    AdmissionError,
    CheckerService,
    Job,
    ServiceConfig,
)
from .fleet import FLEET_COUNTERS, FleetConfig, FleetJob, FleetService
from .journal import Journal, JournalTorn, read_journal
from .registry import SHIPPED, resolve

__all__ = [
    "AdmissionError",
    "CheckerService",
    "FLEET_COUNTERS",
    "FleetConfig",
    "FleetJob",
    "FleetService",
    "Job",
    "Journal",
    "JournalTorn",
    "read_journal",
    "SERVICE_COUNTERS",
    "ServiceConfig",
    "SHIPPED",
    "resolve",
]
