"""Checking-as-a-service: a multi-tenant pool over one device.

:class:`CheckerService` owns the device and serves N concurrent checking
jobs — batch jobs in supervised worker subprocesses (per-job heartbeat,
auto-checkpoint, span trace; a wedge quarantines one job, never the pool)
and interactive Explorer sessions as registered in-process clients —
behind admission control, with a breaker that degrades the pool to the
host engine instead of dying. See ``docs/service.md``; chaos pins in
``tests/test_service.py``.
"""

from .core import (
    SERVICE_COUNTERS,
    AdmissionError,
    CheckerService,
    Job,
    ServiceConfig,
)
from .journal import Journal, JournalTorn, read_journal
from .registry import SHIPPED, resolve

__all__ = [
    "AdmissionError",
    "CheckerService",
    "Job",
    "Journal",
    "JournalTorn",
    "read_journal",
    "SERVICE_COUNTERS",
    "ServiceConfig",
    "SHIPPED",
    "resolve",
]
