"""One service job's process body: the unit of fault isolation.

``CheckerService`` never touches the device from its own process — every
device job runs THIS script in its own process group under
``supervise.run_worker`` (heartbeat-polled, killable as a group), so a
wedged tunnel dispatch or a runaway model takes down exactly one job and
the service requeues it from its auto-checkpoint. The script is runnable
both as ``python -m stateright_tpu.service.worker`` and by file path (the
service invokes the latter so the child needs no import-path inheritance).

Engines:

- ``--engine xla`` (default): the single-chip device engine with per-job
  in-loop auto-checkpointing (``--checkpoint``/``--every``/``--keep``),
  resume (``--resume``), and a per-job metrics time-series
  (``--metrics`` → quiescent-boundary samples plus a forced final row;
  docs/observability.md "Time series"). The heartbeat rides in via
  ``STPU_HEARTBEAT`` (injected by ``run_worker``), the span trace via
  ``STPU_TRACE`` — all per-job files under the service's run dir.
- ``--engine host``: the host on-demand engine
  (``stateright_tpu/checker/on_demand.py``) unblocked and driven in
  ``--block-size`` blocks — the breaker's graceful-degradation target. No
  tunnel, no wedge; always pinned to the CPU backend.

Budgets: ``--max-states`` rides through ``target_state_count`` (the
checker may exceed it by one block but never runs past it while more
states exist); ``--max-seconds`` is a soft in-loop wall-clock check that
exits with code 3 at the next quiescent point (the supervisor's hard
timeout still backstops a worker that cannot reach one).

Fault injection (the chaos suite's hooks, mirroring
``tests/chaos_worker.py``): ``--chaos-die-at-depth N`` SIGKILLs the
process at the first quiescent point at or past depth N;
``--chaos-freeze-at-depth N`` rewrites the heartbeat to
``phase="dispatch"`` and SIGSTOPs — the exact signature of a wedged
tunnel. With ``--chaos-marker`` the sabotage trips exactly once (the
requeued attempt runs clean); without it, every attempt trips — the
repeat-wedge shape the breaker tests need.

At completion the counts/discoveries/metrics land in ``--out`` (atomic
write) for the service to parse.

Multiplexed mode (``--mux manifest.json``; docs/service.md "Batched
scheduling"): ONE worker drives K same-spec jobs through the batched
fused engine (``stateright_tpu/xla_mux.py``). The manifest carries one
lane entry per member job — its own ``out``/``checkpoint``/``metrics``/
``resume`` paths, ``max_states``, and chaos flags — and the worker
resolves the spec ONCE, spawns K lane checkers over the shared model,
and steps a :class:`MuxChecker`. Each lane's ``result.json`` is written
the moment that lane finishes (so a crash mid-batch loses only the
unfinished lanes — the service settles finished members done and
requeues the rest), and ``--out`` receives a group summary
(``dispatches``/``dispatches_saved``) the service folds into its mux
counters. A spec that turns out mux-ineligible at resolve time (typed
``MuxError`` — e.g. lanes resuming at diverged capacities) falls back to
driving the lanes sequentially in this same process: same per-lane
results, no batching win, never a failure.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def _enable_compile_cache() -> None:
    """Persistent XLA compile cache (``STPU_COMPILE_CACHE`` names the dir;
    the service and ``tools/warm_cache.py`` set it to the repo's
    ``.jax_cache``): supersteps recompile identically across worker
    processes, so a requeued job — or a fresh service whose cache
    ``tools/warm_cache.py`` pre-seeded — pays seconds, not minutes."""
    cache_dir = os.environ.get("STPU_COMPILE_CACHE")
    if not cache_dir:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # pragma: no cover - cacheless jax builds
        print(f"compile cache unavailable: {e}", file=sys.stderr)


def _lane_armed(chaos: dict) -> bool:
    """Whether a lane's sabotage flags are live (marker = exactly-once)."""
    if (
        chaos.get("die_at_depth") is None
        and chaos.get("freeze_at_depth") is None
    ):
        return False
    marker = chaos.get("marker")
    return marker is None or not os.path.exists(marker)


def _lane_trip(chaos: dict) -> None:
    marker = chaos.get("marker")
    if marker is not None:
        with open(marker, "w") as fh:
            fh.write("tripped\n")


def _job_trace():
    """The worker's end of the distributed-trace seam
    (docs/observability.md "Distributed tracing"): the tracer named by
    ``STPU_TRACE`` inherits the submission context from ``STPU_TRACE_CTX``
    (both exported by the service), and the whole process body runs under
    ONE pre-allocated ``job`` span — engine dispatch spans parent to it
    via ``set_parent``. Returns ``(tracer, job_sid, attempt_sid, t0)``;
    ``job_sid`` is None when tracing or context is off."""
    from stateright_tpu import obs

    tracer = obs.resolve_tracer(None)
    ctx = obs.parse_ctx(os.environ.get(obs.CTX_ENV))
    if not (tracer.enabled and ctx):
        return tracer, None, None, time.monotonic()
    job_sid = tracer.new_span_id()
    tracer.set_parent(job_sid)
    return tracer, job_sid, ctx[1], time.monotonic()


def _end_job_trace(tracer, job_sid, attempt_sid, t0, **attrs) -> None:
    if job_sid is not None:
        tracer.emit(
            "job", t0=t0, dur=time.monotonic() - t0, attrs=attrs,
            parent_id=attempt_sid, span_id=job_sid,
        )


def _mux_main(args, device_label) -> int:
    """The ``--mux`` body: K lanes of one spec through the batched fused
    engine (falling back to sequential solo drive on ``MuxError``)."""
    import jax

    from stateright_tpu.service.registry import resolve
    from stateright_tpu.xla_mux import MuxChecker, MuxError

    tracer, job_sid, attempt_sid, jt0 = _job_trace()
    with open(args.mux) as fh:
        manifest = json.load(fh)
    lanes_cfg = manifest["lanes"]
    model, caps = resolve(args.spec)
    chaos_armed = [_lane_armed(lane.get("chaos") or {}) for lane in lanes_cfg]
    checkers = []
    for i, lane in enumerate(lanes_cfg):
        builder = model.checker()
        if lane.get("max_states"):
            builder = builder.target_state_count(lane["max_states"])
        kw = dict(caps)
        if any(chaos_armed):
            # Same contract as solo chaos runs: one level per dispatch so
            # sabotage depths and checkpoint cadence line up — for EVERY
            # lane, since the batch shares one dispatch cadence.
            kw["levels_per_dispatch"] = 1
        if lane.get("checkpoint"):
            kw.update(
                checkpoint_to=lane["checkpoint"],
                checkpoint_every=args.every,
                checkpoint_keep=args.keep,
            )
        if lane.get("metrics"):
            kw["metrics_to"] = lane["metrics"]
        if lane.get("resume"):
            kw["checkpoint"] = lane["resume"]
        checkers.append(builder.spawn_xla(**kw))
    start_depths = [ln._depth for ln in checkers]
    t0 = time.monotonic()

    def over_budget() -> bool:
        return (
            args.max_seconds is not None
            and time.monotonic() - t0 > args.max_seconds
        )

    try:
        mux = MuxChecker(checkers)
    except MuxError as e:
        # Graceful degradation: same process, same per-lane artifacts,
        # sequential device calls — the batch loses its win, not its jobs.
        print(f"mux ineligible, driving lanes solo: {e}", file=sys.stderr)
        mux = None

    written = [False] * len(checkers)

    def write_lane(i: int) -> None:
        ln = checkers[i]
        lane = lanes_cfg[i]
        metrics = dict(ln.metrics())
        # Lane attribution (docs/observability.md "Lane telemetry"): the
        # lane's own counts/rates, plus the batch context — a member's
        # metrics.json never reports the whole batch's gen/s as its own.
        metrics["mux_lanes"] = len(checkers)
        metrics["mux_dispatches_saved"] = (
            mux._dispatches_saved if mux is not None else 0
        )
        recorder = getattr(ln, "_recorder", None)
        if recorder is not None:
            recorder.sample(metrics, kind="engine")
        result = {
            "spec": args.spec,
            "engine": "xla",
            "platform": jax.default_backend(),
            "device": device_label,
            "device_ordinal": args.device,
            "degraded": False,
            "generated": ln.state_count(),
            "unique": ln.unique_state_count(),
            "max_depth": ln.max_depth(),
            "discoveries": {
                name: [repr(a) for a in path.into_actions()]
                for name, path in sorted(ln.discoveries().items())
            },
            "resumed_from": lane.get("resume"),
            "start_depth": start_depths[i],
            "seconds": time.monotonic() - t0,
            "mux": {
                "group": manifest.get("group"),
                "lanes": len(checkers),
                "lane": i,
            },
            "metrics": metrics,
        }
        tmp = lane["out"] + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(result, fh, default=str)
        os.replace(tmp, lane["out"])
        written[i] = True
        if job_sid is not None and lane.get("trace_id"):
            # Per-lane attribution in the member job's OWN trace: the
            # lane span carries that submission's trace_id (override —
            # the ambient context is the lead member's) parented to this
            # group worker's job span.
            tracer.emit(
                "lane",
                t0=jt0,
                dur=time.monotonic() - jt0,
                attrs={
                    "lane": i, "group": manifest.get("group"),
                    "job": lane.get("job"), "spec": args.spec,
                },
                parent_id=job_sid,
                trace_id=lane["trace_id"],
            )

    def lane_chaos(i: int) -> None:
        if not chaos_armed[i]:
            return
        ln = checkers[i]
        chaos = lanes_cfg[i].get("chaos") or {}
        die = chaos.get("die_at_depth")
        freeze = chaos.get("freeze_at_depth")
        if die is not None and ln._depth >= die:
            _lane_trip(chaos)
            os.kill(os.getpid(), signal.SIGKILL)
        if freeze is not None and ln._depth >= freeze:
            _lane_trip(chaos)
            hb = mux._heartbeat if mux is not None else ln._heartbeat
            if hb is not None:
                hb.beat("dispatch", compile=False)
            os.kill(os.getpid(), signal.SIGSTOP)

    if mux is not None:
        while not mux.is_done():
            mux._run_block()
            # Finished lanes land their results BEFORE any sabotage fires:
            # a chaos kill mid-batch must lose only unfinished lanes.
            for i, ln in enumerate(checkers):
                if not written[i] and ln.is_done():
                    write_lane(i)
            for i in range(len(checkers)):
                lane_chaos(i)
            if over_budget():
                return 3
    else:
        for i, ln in enumerate(checkers):
            while not ln.is_done():
                ln._run_block()
                lane_chaos(i)
                if over_budget():
                    return 3
            write_lane(i)
    for i, ln in enumerate(checkers):
        if not written[i]:
            write_lane(i)
    summary = {
        "group": manifest.get("group"),
        "spec": args.spec,
        "engine": "xla-mux" if mux is not None else "xla",
        "mux": mux is not None,
        "lanes": len(checkers),
        "dispatches": (
            len(mux.dispatch_log)
            if mux is not None
            else sum(len(ln.dispatch_log) for ln in checkers)
        ),
        "dispatches_saved": mux._dispatches_saved if mux is not None else 0,
        "seconds": time.monotonic() - t0,
    }
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(summary, fh, default=str)
    os.replace(tmp, args.out)
    _end_job_trace(
        tracer, job_sid, attempt_sid, jt0,
        spec=args.spec, engine=summary["engine"],
        group=manifest.get("group"), lanes=len(checkers),
    )
    return 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--spec", required=True)  # service/registry.py grammar
    p.add_argument("--engine", default="xla", choices=("xla", "host"))
    p.add_argument("--platform", default="default")  # "default" | "cpu"
    # Fleet device pinning (ServiceConfig.device_ordinal): run this job's
    # engine on jax.devices()[N] — a fleet's per-device pools land their
    # workers on distinct devices of the mesh. Out-of-range ordinals fall
    # back to the backend default (recorded in the result) rather than
    # failing the job: a fleet restarted on a smaller mesh must still
    # drain its journal.
    p.add_argument("--device", type=int, default=None)
    p.add_argument("--out", required=True)
    p.add_argument("--checkpoint", default=None)  # auto-checkpoint base
    p.add_argument("--metrics", default=None)  # metrics time-series base
    p.add_argument("--resume", default=None)
    p.add_argument("--every", default="1")
    p.add_argument("--keep", type=int, default=3)
    p.add_argument("--block-size", type=int, default=1500)
    p.add_argument("--max-states", type=int, default=None)
    p.add_argument("--max-seconds", type=float, default=None)
    p.add_argument("--chaos-die-at-depth", type=int, default=None)
    p.add_argument("--chaos-freeze-at-depth", type=int, default=None)
    p.add_argument("--chaos-marker", default=None)
    # Multiplexed mode: a lane manifest path (docs/service.md "Batched
    # scheduling"). Per-lane out/checkpoint/metrics/resume/chaos ride in
    # the manifest; --out becomes the group summary.
    p.add_argument("--mux", default=None)
    args = p.parse_args()

    import jax

    if args.engine == "host" or args.platform == "cpu":
        # The env var alone cannot select CPU here (the container's
        # sitecustomize pins the accelerator plugin at config level).
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()

    device_label = None
    if args.device is not None and args.engine == "xla":
        devices = jax.devices()
        if 0 <= args.device < len(devices):
            jax.config.update("jax_default_device", devices[args.device])
            device_label = str(devices[args.device])

    if args.mux:
        return _mux_main(args, device_label)

    from stateright_tpu.service.registry import resolve

    tracer, job_sid, attempt_sid, jt0 = _job_trace()
    model, caps = resolve(args.spec)
    builder = model.checker()
    if args.max_states:
        builder = builder.target_state_count(args.max_states)

    t0 = time.monotonic()

    def over_budget() -> bool:
        return (
            args.max_seconds is not None
            and time.monotonic() - t0 > args.max_seconds
        )

    # Chaos arming: a marker file makes sabotage exactly-once (the requeued
    # attempt runs clean); no marker means every attempt trips.
    armed = (args.chaos_die_at_depth is not None
             or args.chaos_freeze_at_depth is not None) and (
        args.chaos_marker is None or not os.path.exists(args.chaos_marker)
    )

    def trip() -> None:
        if args.chaos_marker is not None:
            with open(args.chaos_marker, "w") as fh:
                fh.write("tripped\n")

    chaos_flags = (
        args.chaos_die_at_depth is not None
        or args.chaos_freeze_at_depth is not None
    )
    if args.engine == "xla":
        kw = dict(caps)
        if chaos_flags:
            # Chaos runs force one level per dispatch: fine-grained
            # quiescent points so the sabotage depth and the checkpoint
            # cadence line up deterministically. Production jobs keep the
            # engine's fused multi-level dispatch (the core perf
            # mechanism: one tunnel RTT per up-to-32 levels); checkpoint
            # cadence and budget checks then apply at dispatch-block
            # granularity, as documented.
            kw["levels_per_dispatch"] = 1
        if args.checkpoint:
            kw.update(
                checkpoint_to=args.checkpoint,
                checkpoint_every=args.every,
                checkpoint_keep=args.keep,
            )
        if args.metrics:
            # Per-job metrics time-series (docs/observability.md "Time
            # series"): sampled at quiescent boundaries into the job dir;
            # a requeued attempt appends to the same rotating series.
            kw["metrics_to"] = args.metrics
        if args.resume:
            kw["checkpoint"] = args.resume
        checker = builder.spawn_xla(**kw)
        step = checker._run_block
    else:
        checker = builder.spawn_on_demand(block_size=1)
        checker.run_to_completion()
        step = lambda: checker._run_block(max(args.block_size, 1))  # noqa: E731

    start_depth = checker._depth if args.engine == "xla" else 0

    while not checker.is_done():
        step()
        if args.engine == "xla":
            depth = checker._depth
            if armed and args.chaos_die_at_depth is not None and (
                depth >= args.chaos_die_at_depth
            ):
                trip()
                os.kill(os.getpid(), signal.SIGKILL)
            if armed and args.chaos_freeze_at_depth is not None and (
                depth >= args.chaos_freeze_at_depth
            ):
                trip()
                # A wedged tunnel's signature: the engine entered a device
                # dispatch and never came back.
                if checker._heartbeat is not None:
                    checker._heartbeat.beat("dispatch", compile=False)
                os.kill(os.getpid(), signal.SIGSTOP)
        if over_budget():
            return 3  # soft budget exit at a quiescent point

    metrics = checker.metrics()
    recorder = getattr(checker, "_recorder", None)
    if recorder is not None:
        # Final forced row: the series ends with the completed run's
        # exact totals regardless of cadence (dashboards and the
        # OpenMetrics tail read the last row as "current").
        recorder.sample(metrics, kind="engine")
    result = {
        "spec": args.spec,
        "engine": args.engine,
        "platform": jax.default_backend(),
        "device": device_label,
        "device_ordinal": args.device,
        "degraded": args.engine == "host",
        "generated": checker.state_count(),
        "unique": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "discoveries": {
            name: [repr(a) for a in path.into_actions()]
            for name, path in sorted(checker.discoveries().items())
        },
        "resumed_from": args.resume,
        "start_depth": start_depth,
        "seconds": time.monotonic() - t0,
        "metrics": metrics,
    }
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(result, fh, default=str)
    os.replace(tmp, args.out)
    _end_job_trace(
        tracer, job_sid, attempt_sid, jt0,
        spec=args.spec, engine=args.engine, resumed_from=args.resume,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
