"""Model registry: the specs a :class:`CheckerService` job can name.

Service jobs run in supervised subprocesses (fault isolation — a wedged
tunnel or a runaway model takes down one worker's process group, never the
pool), so a job's model must be constructible from a plain string the
worker re-resolves on its side of the boundary. Spec grammar::

    <family>[:<arg>[,<arg>...]]

e.g. ``2pc:4``, ``paxos:2,3``, ``abd-ordered:2``, ``scr:3,1``. Omitted
args take the family default. :func:`resolve` returns the packed model
plus the engine capacities the shipped configurations are tuned at (the
same anchors bench.py's matrix pins) — callers may override capacities,
but identical capacities replay identical (shape, bucket) schedules and so
hit the persistent XLA compile cache (``tools/warm_cache.py`` pre-seeds it
for exactly the :data:`SHIPPED` list below).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple


def _two_phase(args: List[int]):
    from ..models.two_phase_commit import PackedTwoPhaseSys

    rm = args[0] if args else 3
    return PackedTwoPhaseSys(rm), dict(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )


def _paxos(args: List[int]):
    from ..models.paxos import PackedPaxos

    c = args[0] if len(args) > 0 else 2
    s = args[1] if len(args) > 1 else 3
    return PackedPaxos(c, s), dict(
        frontier_capacity=1 << 12, table_capacity=1 << 16
    )


def _abd(args: List[int]):
    from ..models.linearizable_register import PackedAbd

    c = args[0] if args else 2
    return PackedAbd(c, 2), dict(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )


def _abd_ordered(args: List[int]):
    from ..models.linearizable_register import PackedAbdOrdered

    c = args[0] if args else 2
    return PackedAbdOrdered(c, 2), dict(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )


def _scr(args: List[int]):
    from ..models.single_copy_register import PackedSingleCopyRegister

    c = args[0] if len(args) > 0 else 3
    s = args[1] if len(args) > 1 else 1
    return PackedSingleCopyRegister(c, s), dict(
        frontier_capacity=1 << 11, table_capacity=1 << 14
    )


def _increment(args: List[int]):
    from ..models.increment import PackedIncrement

    t = args[0] if args else 3
    return PackedIncrement(t), dict(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )


def _increment_lock(args: List[int]):
    from ..models.increment_lock import PackedIncrementLock

    t = args[0] if args else 3
    return PackedIncrementLock(t), dict(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )


#: family name -> model factory taking the parsed integer args.
FAMILIES: Dict[str, Callable[[List[int]], Tuple[Any, Dict[str, int]]]] = {
    "2pc": _two_phase,
    "paxos": _paxos,
    "abd": _abd,
    "abd-ordered": _abd_ordered,
    "scr": _scr,
    "increment": _increment,
    "increment-lock": _increment_lock,
}


#: Families whose jobs the batching scheduler may multiplex into one
#: ``worker.py --mux`` invocation (docs/service.md "Batched scheduling").
#: ``MuxChecker`` requires lanes with no host-verified properties — every
#: shipped family resolves hv-free at its shipped configurations EXCEPT
#: ``scr``, whose model conditionally promotes properties to host
#: verification by pattern census, so the scheduler excludes it statically
#: rather than paying a resolve-and-fall-back in the worker. User families
#: (STPU_FAMILIES) are never multiplexed: the service cannot see their
#: model structure without importing user code.
MUX_FAMILIES = frozenset(FAMILIES) - {"scr"}


#: Families whose packed models ship a declarative ``symmetry_spec``
#: (stateright_tpu/sym; docs/symmetry.md) — the set ``tools/warm_cache.py
#: --sym`` pre-banks symmetry-variant programs for, statically (like
#: MUX_FAMILIES: no model import in the jax-free parent). Drift against
#: the models' actual capability is a test failure
#: (tests/test_symmetry.py).
SYM_FAMILIES = frozenset({"2pc", "increment", "increment-lock"})


def _extra_family_targets() -> Dict[str, Tuple[str, str]]:
    """The ``STPU_FAMILIES="name=module:attr,..."`` mapping, parsed but
    NOT imported — :func:`parse` validates spec names against this
    without executing any user code, so the (jax-free, wedge-proof)
    service process can admission-validate a user spec while the import
    itself happens only in the subprocesses that resolve it (the
    admission-lint run, the job workers). A malformed entry raises
    ``ValueError`` — a caller bug, same contract as an unknown spec."""
    import os

    raw = os.environ.get("STPU_FAMILIES", "").strip()
    if not raw:
        return {}
    out: Dict[str, Tuple[str, str]] = {}
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, eq, target = entry.partition("=")
        mod_name, colon, attr = target.partition(":")
        if not (eq and colon and name.strip() and mod_name and attr):
            raise ValueError(
                f"malformed STPU_FAMILIES entry {entry!r} "
                '(expected "name=module:attr")'
            )
        out[name.strip()] = (mod_name, attr)
    return out


def _load_extra_family(name: str) -> Callable[[List[int]], Tuple[Any, Dict[str, int]]]:
    """Import ONE user family's factory (same ``(args) -> (model,
    capacities)`` contract as the shipped ones). Only the requested
    entry is imported — one broken STPU_FAMILIES entry must not take
    down the healthy ones — and only :func:`resolve` reaches this:
    importing a user module executes its top-level code, which must
    never happen in the service pool process (it may import jax and
    wedge on backend bring-up; see service/core.py). Kept OUT of
    :data:`FAMILIES` on purpose: shipped families are the tree's
    (content-hash-cacheable by the lint); user families are the
    caller's, re-resolved lazily on every call so the env var works
    across the process boundaries the service creates."""
    import importlib

    mod_name, attr = _extra_family_targets()[name]
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, attr)
    except (ImportError, AttributeError) as e:
        raise ValueError(
            f"STPU_FAMILIES entry {name}={mod_name}:{attr} "
            f"failed to load: {e}"
        ) from e

#: The seven shipped packed-model configurations — the shapes
#: ``tools/warm_cache.py`` pre-seeds the persistent XLA compile cache with
#: so a fresh service's first request pays seconds, not minutes
#: (VERDICT item 6: paxos warm <= 29 s).
SHIPPED = (
    "2pc:3",
    "2pc:4",
    "abd:2",
    "abd-ordered:2",
    "paxos:2,3",
    "scr:3,1",
    "increment-lock:3",
)


def parse(spec: str) -> Tuple[str, List[int]]:
    """``"paxos:2,3"`` -> ``("paxos", [2, 3])``; raises ``ValueError`` on
    an unknown family or malformed args (typed: admission control converts
    nothing — a bad spec is a caller bug, not a capacity problem)."""
    name, _, rest = spec.strip().partition(":")
    if name not in FAMILIES and name not in _extra_family_targets():
        raise ValueError(
            f"unknown model spec {spec!r}; families: {sorted(FAMILIES)}"
        )
    try:
        args = [int(a) for a in rest.split(",") if a.strip()] if rest else []
    except ValueError:
        raise ValueError(f"malformed spec args in {spec!r}") from None
    return name, args


def resolve(spec: str) -> Tuple[Any, Dict[str, int]]:
    """Spec string -> ``(packed model, default spawn capacities)``."""
    name, args = parse(spec)
    factory = FAMILIES.get(name) or _load_extra_family(name)
    return factory(args)
