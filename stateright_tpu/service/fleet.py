"""FleetService: multi-device pools with routing, failover, and replay.

ROADMAP item 3(a)'s fleet tier: one :class:`~.core.CheckerService` per
device (on this box, the 8-device virtual CPU mesh; on chip, one pool per
enumerated device) fronted by ONE object with the same
``submit``/``job``/``wait_all``/``gauges`` surface a single pool serves —
the reference's spawn-worker fan-out (``src/checker/bfs.rs``), reproduced
across devices instead of threads:

- **Device-aware routing** — whole jobs place on the least-loaded
  *healthy* device (breaker closed, not lost). Idempotency keys are
  fleet-scoped: a key the fleet knows returns the existing
  :class:`FleetJob` (affinity is stable because the routing decision is
  journaled, not re-drawn). Per-device **breaker state is per pool** —
  one wedged device quarantines only its own jobs, and the sibling
  devices never see it.
- **Failover migration** — when a device's breaker trips
  (``breaker_listener`` wakes the fleet monitor immediately) or the
  device is lost outright (``device.lost`` chaos, or an operator's
  :meth:`FleetService.device_lost`), the pool's non-terminal jobs are
  **evacuated** (``CheckerService.evacuate``: journaled terminal-for-
  that-pool ``migrated`` status, worker groups killed) and resubmitted to
  a healthy sibling with ``spent_s=`` (wall-clock stays charged) and
  ``resume_from=`` (the victim's latest valid checkpoint rotation seeds
  the new attempt). Fleet pools run ``breaker_mode="halt"``: an open
  breaker *holds* queued jobs for migration instead of silently degrading
  them — **host-engine degradation is the last resort**, taken only when
  every device is open/lost (``engine="host"`` forced submission to the
  least-loaded alive pool).
- **Durable routing** — the fleet journals its placement decisions
  (``routed`` / ``migrated`` events riding the same sha256-per-record
  ``service/journal.py`` schema as the pools' own journals, at
  ``<run_dir>/fleet.jsonl``). Constructing a fleet over a run dir that
  already has journals REPLAYS everything: each pool restores its own
  job set (requeue/orphan-kill/budget semantics unchanged from the
  single-pool contract), then the fleet journal re-attaches every
  FleetJob to its routed pool job, adopts any pool-restored idempotency
  keys a torn fleet tail lost, and re-routes stragglers evacuated but
  never resubmitted before the crash — kill -9 the whole fleet at any
  instant, restart into the same job set on the same devices.
- **Fleet-scale chaos** (``stateright_tpu/chaos.py``) — ``device.lost``
  (@n counts successful placements; params ``device`` = target index,
  default the device just routed to, ``after_s`` = delay so the loss
  lands mid-job) kills one device's pool mid-schedule;
  ``device.flaky@p=F`` gives the routed job a one-shot heartbeat-freeze
  (the wedged-tunnel signature) on its device. ``tools/service_chaos.py
  --fleet N`` drives seeded schedules through both and asserts
  exactly-once, bit-identical completion across migrations.

Like every other service-tier module, importing this never imports jax —
pools, workers, and probers keep their own process boundaries.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .. import chaos as chaos_mod
from ..checkpoint import latest_valid_checkpoint
from ..obs import NULL_TRACER, Counters, new_trace_id, resolve_tracer
from .core import AdmissionError, CheckerService, Job, ServiceConfig
from .journal import Journal, read_journal

#: Fleet-level counters (the pools keep SERVICE_COUNTERS of their own).
FLEET_COUNTERS = (
    "submitted",
    "admitted",
    "rejected",
    "routed",
    "migrations",
    "devices_lost",
    "device_flakes",
    "host_last_resort",
    "idem_dedups",
    "jobs_recovered",
    "pools_quiesced",
    "pools_woken",
)


@dataclass
class FleetConfig:
    """Fleet knobs. Per-pool knobs ride in ``pool`` (a template
    ServiceConfig; its ``run_dir``/``device``/``device_ordinal``/
    ``breaker_mode``/``breaker_listener`` are overwritten per device)."""

    run_dir: str = os.path.join("runs", "fleet")
    devices: int = 2  #: pools to front (one per device ordinal 0..N-1)
    #: Monitor cadence: the sweep that notices open breakers / lost
    #: devices and migrates their jobs (a breaker trip also wakes it
    #: immediately through the listener).
    monitor_interval_s: float = 1.0
    #: Pin worker processes to their pool's device ordinal (worker.py
    #: ``--device``). Off by default on platform="cpu" pools unless the
    #: virtual mesh is known to be up — the tests enable it explicitly.
    pin_devices: bool = False
    # -- durability (fleet.jsonl; same Journal discipline as the pools) ----
    journal: bool = True
    journal_compact_every: int = 256
    journal_keep: int = 3
    # -- fault injection ---------------------------------------------------
    chaos: Optional[str] = None
    #: Template for the per-device pools (None = ServiceConfig defaults).
    pool: Optional[ServiceConfig] = None
    #: Interactive sessions cap, fleet-wide (None = sum of pool caps).
    max_sessions: Optional[int] = None
    # -- elastic pools (docs/service.md "QoS & overload") ------------------
    #: Idle pools quiesce (drop out of routing; their workers are already
    #: reaped — a pool only quiesces at zero load) and wake under queue
    #: pressure. Quiesce/wake decisions are journaled (``quiesced`` /
    #: ``woken`` fleet events) so a restart resumes the same active set.
    elastic: bool = False
    #: A pool must sit at zero load this long before the monitor
    #: quiesces it.
    idle_quiesce_s: float = 30.0
    #: Never quiesce below this many active (non-lost, non-quiesced)
    #: pools.
    min_active: int = 1
    #: Distributed tracing (docs/observability.md "Distributed tracing"):
    #: True → fleet route/migrate spans to ``<run_dir>/trace.jsonl`` (and
    #: each pool, unless its template says otherwise, traces to its own
    #: run dir); a path appends there; None → ``STPU_SERVICE_TRACE`` env.
    #: Trace ids mint and journal regardless — only span writes gate.
    trace: Any = None


class FleetJob:
    """One fleet entry: a stable fleet-scoped identity over the (possibly
    migrating) pool job currently serving it. The surface mirrors
    :class:`~.core.Job` where it matters (``status``/``result``/``error``/
    ``wait``/``snapshot``/``metrics``/``done``)."""

    def __init__(self, fleet: "FleetService", fleet_id: str,
                 idempotency_key: Optional[str] = None):
        self._fleet = fleet
        self.id = fleet_id
        self.idempotency_key = idempotency_key
        self.device: Optional[int] = None  #: current device index
        self.pool_job: Optional[Job] = None  #: current pool job
        self.migrations: List[Dict[str, Any]] = []
        self.recovered = False  #: restored by a fleet-journal replay
        #: Set when the reserving submit was rejected fleet-wide: the
        #: handle is terminal-failed (a concurrent same-key submit may
        #: have deduped onto it before the rejection landed).
        self._rejected: Optional[str] = None
        #: Journaled spec kept for the repair pass when a restart cannot
        #: re-attach the routed pool job (torn/lost pool journal, or a
        #: smaller fleet): enough to re-route the work from scratch.
        self._orphan_spec: Optional[str] = None
        #: QoS identity (docs/service.md "QoS & overload") — journaled on
        #: ``routed`` so migrations and orphan re-routes keep the class.
        self.tenant: str = "default"
        self.priority: str = "batch"
        self.deadline_s: Optional[float] = None
        #: Per-job symmetry mode (docs/symmetry.md) — journaled on
        #: ``routed`` so migrations and orphan re-routes keep it.
        self.symmetry: Optional[str] = None
        self.created_unix_ts = time.time()
        #: Fleet-minted distributed-trace id — stable across migrations
        #: (every hop's pool job carries the same one).
        self.trace_id: Optional[str] = None

    # -- delegation --------------------------------------------------------

    def _current(self):
        with self._fleet._lock:
            return self.device, self.pool_job

    @property
    def status(self) -> str:
        if self._rejected is not None:
            return "failed"
        job = self._current()[1]
        if job is None:
            return "queued"
        # "migrated" is a pool-internal verdict: from the fleet's view the
        # job is between devices (the monitor is re-routing it).
        return "migrating" if job.status == "migrated" else job.status

    @property
    def done(self) -> bool:
        if self._rejected is not None:
            return True
        job = self._current()[1]
        return job is not None and job.status in ("done", "failed")

    @property
    def result(self):
        job = self._current()[1]
        return None if job is None else job.result

    @property
    def error(self):
        if self._rejected is not None:
            return self._rejected
        job = self._current()[1]
        return None if job is None else job.error

    @property
    def requeues(self) -> int:
        job = self._current()[1]
        base = sum(m.get("requeues", 0) for m in self.migrations)
        return base + (0 if job is None else job.requeues)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Blocks until the job is terminal FOR THE FLEET (done/failed on
        whatever device it ends up on — migrations are waited through)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._rejected is not None:
                return True
            job = self._current()[1]
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                return self.done
            if job is None:
                # Routed but not attached yet (a recovery edge) — the
                # monitor repairs it; poll.
                time.sleep(min(0.05, remaining or 0.05))
                continue
            job.wait(timeout=min(0.5, remaining) if remaining else 0.5)
            if job.status in ("done", "failed"):
                return True
            if job.status == "migrated":
                # Terminal for the pool but not for the fleet: the
                # monitor is re-routing — don't spin on the pool's
                # already-settled condition.
                time.sleep(0.05)
            # loop re-reads the current pool job.

    def snapshot(self) -> Dict[str, Any]:
        device, job = self._current()
        out = job.snapshot() if job is not None else {"status": "queued"}
        out.update(
            fleet_job=self.id,
            device=(
                self._fleet._device_label(device)
                if device is not None
                else None
            ),
            status=self.status,
            migrations=len(self.migrations),
            recovered=out.get("recovered", False) or self.recovered,
            trace_id=self.trace_id or out.get("trace_id"),
            tenant=self.tenant,
            priority=self.priority,
            deadline_s=self.deadline_s,
        )
        return out

    def metrics(self):
        job = self._current()[1]
        return None if job is None else job.metrics()


def _fleet_replay(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the fleet journal into recoverable routing state (pure —
    testable without a fleet): last ``snapshot`` as base, later
    ``routed``/``migrated`` events on top. ``routes[fid]`` holds the
    CURRENT placement; ``migrations[fid]`` the count."""
    state: Dict[str, Any] = {
        "next_id": 0,
        "routes": {},
        "order": [],
        "idem": {},
        "counters": {},
        "migrations": {},
        "quiesced": set(),
    }

    def inc(name: str, n: int = 1) -> None:
        state["counters"][name] = state["counters"].get(name, 0) + n

    for rec in records:
        ev = rec["event"]
        if ev == "snapshot":
            s = rec["state"]
            state["next_id"] = s.get("next_id", state["next_id"])
            state["routes"] = {k: dict(v) for k, v in s.get("routes", {}).items()}
            state["order"] = [
                f for f in s.get("order", list(state["routes"]))
                if f in state["routes"]
            ]
            state["idem"] = dict(s.get("idem", {}))
            state["counters"] = dict(s.get("counters", {}))
            state["migrations"] = dict(s.get("migrations", {}))
            state["quiesced"] = set(s.get("quiesced", []))
            continue
        if ev == "recovered":
            continue
        if ev == "quiesced":
            state["quiesced"].add(rec["device"])
            inc("pools_quiesced")
            continue
        if ev == "woken":
            state["quiesced"].discard(rec["device"])
            inc("pools_woken")
            continue
        fid = rec.get("job")
        if fid is None:
            continue
        if ev == "routed":
            state["routes"][fid] = {
                "device": rec["device"],
                "pool_job": rec["pool_job"],
                "spec": rec.get("spec"),
                "idempotency_key": rec.get("idempotency_key"),
                "trace_id": rec.get("trace_id"),
                "tenant": rec.get("tenant", "default"),
                "priority": rec.get("priority", "batch"),
                "deadline_s": rec.get("deadline_s"),
                "symmetry": rec.get("symmetry"),
            }
            if fid not in state["order"]:
                state["order"].append(fid)
            if rec.get("idempotency_key"):
                state["idem"][rec["idempotency_key"]] = fid
            try:
                state["next_id"] = max(
                    state["next_id"], int(fid.rsplit("-", 1)[-1])
                )
            except ValueError:
                pass
            inc("submitted")
            inc("admitted")
            inc("routed")
        elif ev == "migrated":
            route = state["routes"].get(fid)
            if route is None:
                continue
            route["device"] = rec["to_device"]
            route["pool_job"] = rec["pool_job"]
            state["migrations"][fid] = state["migrations"].get(fid, 0) + 1
            inc("migrations")
    return state


class FleetService:
    """N per-device :class:`CheckerService` pools behind one
    ``submit``/``job``/``wait_all``/``gauges`` surface (see the module
    docstring for the routing/migration/durability contract). Also
    implements the session-registration surface the Explorer client uses
    (``check_session_capacity``/``register_interactive``/
    ``release_interactive``), so ``make_app(service=fleet)`` works
    unchanged."""

    def __init__(self, config: Optional[FleetConfig] = None, **overrides):
        if config is not None and overrides:
            raise TypeError(
                "pass either a FleetConfig or keyword overrides, not both "
                f"(got config and {sorted(overrides)})"
            )
        self._cfg = config or FleetConfig(**overrides)
        if self._cfg.devices < 1:
            raise ValueError("a fleet needs at least one device")
        self._lock = threading.Lock()
        #: Serializes session count-check + registration: the fleet-wide
        #: cap must not be exceeded by concurrent registrations racing
        #: the count (the pools' own locks only guard their PER-POOL cap).
        self._session_lock = threading.Lock()
        self._counters = Counters(FLEET_COUNTERS)
        self._jobs: Dict[str, FleetJob] = {}
        self._order: List[str] = []
        self._idem: Dict[str, str] = {}
        self._next_id = 0
        self._lost: set = set()  #: device indices declared dead
        self._quiesced: set = set()  #: elastic pools out of routing
        self._idle_since: Dict[int, float] = {}  #: monotonic idle marks
        self._closed = False
        self._monitor: Optional[threading.Thread] = None
        self._wake = threading.Event()  #: breaker listeners pulse this
        self._timers: List[threading.Timer] = []  #: armed device.lost
        self._journal: Optional[Journal] = None
        self._recovery: Optional[Dict[str, Any]] = None
        self.log = lambda msg: None
        trace_cfg = self._cfg.trace
        if trace_cfg is None:
            raw = os.environ.get("STPU_SERVICE_TRACE") or None
            trace_cfg = True if raw == "1" else raw
        if trace_cfg is True:
            trace_cfg = os.path.join(self._cfg.run_dir, "trace.jsonl")
        self._tracer = (resolve_tracer(trace_cfg) if trace_cfg else NULL_TRACER)
        if self._cfg.chaos:
            chaos_mod.install(self._cfg.chaos)
        # Per-device pools. Constructed AFTER the chaos install so a
        # pool-journal replay sees the plan; each pool replays its own
        # journal if its run dir has one.
        self.pools: List[CheckerService] = []
        for i in range(self._cfg.devices):
            self.pools.append(CheckerService(self._pool_config(i)))
        if self._cfg.journal:
            self._journal = Journal(
                os.path.join(self._cfg.run_dir, "fleet.jsonl"),
                keep=self._cfg.journal_keep,
                compact_every=self._cfg.journal_compact_every,
            )
            if os.path.exists(self._journal.path):
                self._recover()
        # A restart with live (requeued) work needs the monitor running
        # from the start — migrated stragglers and re-tripped breakers
        # are its job to repair.
        if any(not j.done for j in self._jobs.values()):
            self._ensure_monitor()

    def _pool_config(self, i: int) -> ServiceConfig:
        # Everything not overridden below inherits from the caller's pool
        # template — notably mux_k, so a batching fleet multiplexes
        # same-spec jobs WITHIN each device's pool (routing stays
        # whole-job; lanes never span devices).
        base = self._cfg.pool or ServiceConfig()
        return dataclasses.replace(
            base,
            run_dir=os.path.join(self._cfg.run_dir, f"device-{i}"),
            device=self._device_label(i),
            device_ordinal=i if self._cfg.pin_devices else None,
            breaker_mode="halt",
            breaker_listener=self._breaker_listener(i),
            # The fleet's spec rides into every pool so _worker_env
            # exports STPU_CHAOS to worker processes (checkpoint.torn
            # fires THERE); the pools' own installs are no-ops — install
            # is idempotent on a same-spec re-install, so the plan the
            # fleet installed in __init__ keeps its counters.
            chaos=self._cfg.chaos,
            # A tracing fleet traces its pools too (each to its own run
            # dir) unless the template pins an explicit choice.
            trace=(
                base.trace if base.trace is not None
                else (True if self._tracer.enabled else None)
            ),
        )

    def _device_label(self, i: int) -> str:
        return f"device-{i}"

    def _breaker_listener(self, i: int):
        def listener(state: str) -> None:
            self.log(f"device-{i} breaker {state}")
            if state == "open":
                # The monitor idle-exits once every job is terminal; a
                # later trip must bring it back for the evacuation pass.
                self._ensure_monitor()
            self._wake.set()
        return listener

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self) -> "FleetService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def close(self, kill: bool = True, timeout: float = 10.0) -> None:
        with self._lock:
            self._closed = True
            timers = list(self._timers)
        for timer in timers:
            # An armed chaos loss that hasn't fired dies with the fleet
            # (device_lost would no-op on _closed anyway — but a live
            # non-daemon timer would stall interpreter exit by after_s).
            timer.cancel()
        self._wake.set()
        if self._monitor is not None:
            self._monitor.join(timeout=timeout)
        for pool in self.pools:
            pool.close(kill=kill, timeout=timeout)
        if self._journal is not None:
            self._journal.close()

    def _ensure_monitor(self) -> None:
        # Check-and-start under the lock: two concurrent submits must
        # not both observe "no monitor" and start twin loops (twin
        # repair passes would double-journal migrations).
        with self._lock:
            if self._closed:
                return
            if self._monitor is None or not self._monitor.is_alive():
                self._monitor = threading.Thread(
                    target=self._monitor_loop, name="stpu-fleet-monitor",
                    daemon=True,
                )
                self._monitor.start()

    # -- durability --------------------------------------------------------

    def _jlog(self, event: str, **payload: Any) -> None:
        """Caller holds the fleet lock (mirrors the pools' _jlog)."""
        j = self._journal
        if j is None:
            return
        j.append(event, ts=time.time(), **payload)
        if j.compaction_due:
            j.compact(self._snapshot_payload(), ts=time.time())

    def _snapshot_payload(self) -> Dict[str, Any]:
        return {
            "next_id": self._next_id,
            "idem": dict(self._idem),
            "counters": self._counters.snapshot(),
            "order": list(self._order),
            "migrations": {
                fid: len(j.migrations)
                for fid, j in self._jobs.items()
                if j.migrations
            },
            "quiesced": sorted(self._quiesced),
            "routes": {
                fid: {
                    "device": j.device,
                    "pool_job": j.pool_job.id if j.pool_job else None,
                    # An orphan awaiting repair keeps its journaled spec
                    # through compaction: a crash before the repair pass
                    # runs must not turn it unrecoverable.
                    "spec": (
                        j.pool_job.spec if j.pool_job else j._orphan_spec
                    ),
                    "idempotency_key": j.idempotency_key,
                    "trace_id": j.trace_id,
                    "tenant": j.tenant,
                    "priority": j.priority,
                    "deadline_s": j.deadline_s,
                }
                for fid, j in self._jobs.items()
                # A reserved-but-still-routing handle must not be
                # snapshotted: replaying it would resurrect a route that
                # never existed (the `routed` event is the commit point).
                if j.pool_job is not None or j.recovered
            },
        }

    def _recover(self) -> None:
        """Replay ``fleet.jsonl`` routing over the already-replayed pools:
        re-attach each FleetJob to its routed pool job; adopt
        pool-restored idempotency keys a torn fleet tail lost (the pool
        journal is the job's source of truth); leave evacuated-but-never-
        resubmitted stragglers to the monitor's repair pass."""
        replay = read_journal(self._journal.path)
        state = _fleet_replay(replay.records)
        attached = 0
        orphaned = 0
        with self._lock:
            # Seq restores FIRST: the adoption/repair appends below must
            # continue the replayed sequence, not restart it at 1.
            self._journal.seq = (
                replay.records[-1]["seq"] if replay.records else 0
            )
            self._next_id = max(self._next_id, state["next_id"])
            self._idem.update(state["idem"])
            self._quiesced = {
                i for i in state["quiesced"]
                if isinstance(i, int) and 0 <= i < len(self.pools)
            }
            for name, value in state["counters"].items():
                if value and name != "jobs_recovered":
                    self._counters.inc(name, value)
            for fid in state["order"]:
                route = state["routes"][fid]
                fjob = FleetJob(
                    self, fid, idempotency_key=route.get("idempotency_key")
                )
                fjob.recovered = True
                fjob.trace_id = route.get("trace_id")
                fjob.tenant = route.get("tenant", "default")
                fjob.priority = route.get("priority", "batch")
                fjob.deadline_s = route.get("deadline_s")
                fjob.symmetry = route.get("symmetry")
                fjob.migrations = [
                    {"recovered": True}
                ] * state["migrations"].get(fid, 0)
                device = route.get("device")
                pool_job_id = route.get("pool_job")
                if (
                    device is not None
                    and 0 <= device < len(self.pools)
                    and pool_job_id is not None
                ):
                    try:
                        fjob.pool_job = self.pools[device].job(pool_job_id)
                        fjob.device = device
                        attached += 1
                    except KeyError:
                        fjob._orphan_spec = route.get("spec")
                        orphaned += 1
                else:
                    fjob._orphan_spec = route.get("spec")
                    orphaned += 1
                self._jobs[fid] = fjob
                self._order.append(fid)
                self._counters.inc("jobs_recovered")
            # Torn-tail repair: a pool may hold jobs (by idempotency key)
            # the fleet journal never recorded routing for — adopt them
            # rather than double-run on resubmission.
            known_pool_jobs = {
                (j.device, j.pool_job.id)
                for j in self._jobs.values()
                if j.pool_job is not None
            }
            for device, pool in enumerate(self.pools):
                for job in pool.jobs():
                    if job.kind != "batch" or job.idempotency_key is None:
                        continue
                    if (device, job.id) in known_pool_jobs:
                        continue
                    if job.idempotency_key.startswith("fleet-mig:"):
                        # An interrupted migration: the sibling pool
                        # journaled the resubmission but the fleet died
                        # before journaling `migrated`. Complete it —
                        # re-attach to the named fleet job instead of
                        # minting a duplicate (the pool job replays as
                        # live, so without this the straggler repair
                        # would double-run the work).
                        fid = job.idempotency_key.split(":")[1]
                        fjob = self._jobs.get(fid)
                        if fjob is not None and (
                            fjob.pool_job is None
                            or fjob.pool_job.status == "migrated"
                        ):
                            if fjob.trace_id is None:
                                fjob.trace_id = job.trace_id
                            from_device = fjob.device
                            fjob.migrations.append({"recovered": True})
                            fjob.device = device
                            fjob.pool_job = job
                            self._counters.inc("migrations")
                            self._jlog(
                                "migrated", job=fid,
                                from_device=from_device, to_device=device,
                                pool_job=job.id,
                                reason="recovered mid-migration",
                                seed=job.seed_checkpoint,
                            )
                            attached += 1
                        continue
                    if job.idempotency_key in self._idem:
                        continue
                    self._next_id += 1
                    fid = f"fjob-{self._next_id:04d}"
                    fjob = FleetJob(
                        self, fid, idempotency_key=job.idempotency_key
                    )
                    fjob.recovered = True
                    fjob.device = device
                    fjob.pool_job = job
                    fjob.trace_id = job.trace_id
                    fjob.tenant = job.tenant
                    fjob.priority = job.priority
                    fjob.deadline_s = job.deadline_s
                    self._jobs[fid] = fjob
                    self._order.append(fid)
                    self._idem[job.idempotency_key] = fid
                    self._counters.inc("jobs_recovered")
                    self._jlog(
                        "routed", job=fid, spec=job.spec, device=device,
                        pool_job=job.id,
                        idempotency_key=job.idempotency_key,
                        adopted=True,
                        trace_id=job.trace_id,
                        tenant=job.tenant, priority=job.priority,
                        deadline_s=job.deadline_s,
                    )
                    attached += 1
            self._recovery = {
                "records_replayed": len(replay.records),
                "torn": replay.torn,
                "routes_recovered": len(self._order),
                "attached": attached,
                "orphaned": orphaned,
            }
            self._journal.compact(self._snapshot_payload(), ts=time.time())
            self._jlog("recovered", **self._recovery)

    # -- routing -----------------------------------------------------------

    def _pool_load(self, i: int) -> int:
        g = self.pools[i].gauges()
        return g["queued"] + g["quarantined"] + g["running"]

    def _route_load(self, i: int, priority: Optional[str] = None) -> float:
        """Routing cost: total backlog, plus the same-class backlog again
        when the submission carries a priority — two devices equally busy
        overall tie-break toward the one with less SAME-class contention,
        so one tenant's interactive burst spreads instead of piling onto
        a single pool's interactive queue (docs/service.md
        "QoS & overload")."""
        g = self.pools[i].gauges()
        load = float(g["queued"] + g["quarantined"] + g["running"])
        if priority is not None:
            row = (g.get("qos") or {}).get("classes", {}).get(priority)
            if row:
                load += row.get("queued", 0) + row.get("running", 0)
        return load

    def _healthy_devices(self) -> List[int]:
        return [
            i for i in range(len(self.pools))
            if i not in self._lost and i not in self._quiesced
            and not self.pools[i].degraded
        ]

    def _alive_devices(self) -> List[int]:
        return [i for i in range(len(self.pools)) if i not in self._lost]

    # -- elastic pools (docs/service.md "QoS & overload") ------------------

    def quiesce_pool(self, i: int, reason: str = "idle") -> bool:
        """Take pool ``i`` out of routing (journaled ``quiesced`` event).
        Refused (False) when it would drop the active pool count below
        ``min_active``, or the pool is lost/already quiesced. A quiesce
        with work still on the pool is just a scale-down: the jobs
        evacuate and the monitor migrates them — the same journaled
        path a breaker trip takes."""
        with self._lock:
            if self._closed or i in self._quiesced or i in self._lost or not (
                0 <= i < len(self.pools)
            ):
                return False
            active = [
                d for d in range(len(self.pools))
                if d not in self._lost and d not in self._quiesced
            ]
            if len(active) <= max(1, self._cfg.min_active):
                return False
            self._quiesced.add(i)
            self._idle_since.pop(i, None)
            self._counters.inc("pools_quiesced")
            self._jlog("quiesced", device=i, reason=reason)
        self.log(f"device-{i} quiesced ({reason})")
        if self._pool_load(i):
            self.pools[i].evacuate(reason=f"device-{i} quiesced")
            self._ensure_monitor()
            self._wake.set()
        return True

    def wake_pool(self, i: int, reason: str = "pressure") -> bool:
        """Return a quiesced pool to routing (journaled ``woken``)."""
        with self._lock:
            if self._closed or i not in self._quiesced or i in self._lost:
                return False
            self._quiesced.discard(i)
            self._idle_since.pop(i, None)
            self._counters.inc("pools_woken")
            self._jlog("woken", device=i, reason=reason)
        self.log(f"device-{i} woken ({reason})")
        return True

    def _wake_for_pressure(self) -> Optional[int]:
        """Wake the lowest-numbered quiesced pool; None when there is
        nothing to wake."""
        with self._lock:
            candidates = sorted(self._quiesced - self._lost)
        for i in candidates:
            if self.wake_pool(i, reason="queue pressure"):
                return i
        return None

    def _elastic_sweep(self) -> None:
        """One monitor-cadence elastic pass: wake a pool when every
        active pool is backlogged past its in-flight capacity; quiesce
        pools idle past ``idle_quiesce_s`` (down to ``min_active``)."""
        with self._lock:
            if self._closed:
                return
            active = [
                i for i in range(len(self.pools))
                if i not in self._lost and i not in self._quiesced
            ]
            quiesced = sorted(self._quiesced - self._lost)
        if quiesced and active and all(
            self._pool_load(i) > max(self.pools[i]._cfg.max_inflight, 1)
            for i in active
        ):
            self.wake_pool(quiesced[0], reason="queue pressure")
            return
        now = time.monotonic()
        # Loads read OUTSIDE the fleet lock (gauges take each pool's own
        # lock — same ordering as every other fleet->pool call).
        loads = {i: self._pool_load(i) for i in active}
        idle_for: Dict[int, float] = {}
        with self._lock:
            for i in active:
                if loads[i] == 0:
                    since = self._idle_since.setdefault(i, now)
                    idle_for[i] = now - since
                else:
                    self._idle_since.pop(i, None)
        for i, idled in idle_for.items():
            if idled >= self._cfg.idle_quiesce_s:
                self.quiesce_pool(
                    i, reason=f"idle {self._cfg.idle_quiesce_s:g}s"
                )

    def submit(
        self,
        spec: str,
        *,
        max_seconds: Optional[float] = None,
        max_states: Optional[int] = None,
        chaos: Optional[Dict[str, Any]] = None,
        idempotency_key: Optional[str] = None,
        tenant: str = "default",
        priority: str = "batch",
        deadline_s: Optional[float] = None,
        symmetry: Optional[str] = None,
    ) -> FleetJob:
        """Route one batch job to the least-loaded healthy device —
        class-aware: same-class backlog counts double, so a class's
        burst spreads (host last resort when none is healthy; a fleet
        with quiesced elastic pools wakes one under pressure before
        either degrading or rejecting); returns the :class:`FleetJob`
        or raises :class:`AdmissionError` when every candidate rejects
        (the hint is the minimum Retry-After across devices — the
        soonest any of them expects room). ``tenant``/``priority``/
        ``deadline_s`` ride into the pool submission (per-pool quotas,
        fair-share, shedding) and are journaled on ``routed`` so a
        restart or migration keeps the class."""
        with self._lock:
            if self._closed:
                raise RuntimeError("fleet is closed")
            self._counters.inc("submitted")
            if idempotency_key is not None:
                known = self._jobs.get(self._idem.get(idempotency_key, ""))
                if known is not None:
                    self._counters.inc("idem_dedups")
                    return known
            # Reserve the fleet identity (and the key) BEFORE routing:
            # routing runs outside the lock, and a concurrent same-key
            # submit must dedupe onto THIS handle rather than race the
            # same work onto a second device.
            self._next_id += 1
            fjob = FleetJob(self, f"fjob-{self._next_id:04d}",
                            idempotency_key=idempotency_key)
            # The fleet mints the trace id — the pool job (and every
            # migration hop's resubmission) joins it rather than minting
            # its own, so one submission is ONE trace end to end.
            fjob.trace_id = new_trace_id()
            fjob.tenant = tenant
            fjob.priority = priority
            fjob.deadline_s = deadline_s
            fjob.symmetry = symmetry
            self._jobs[fjob.id] = fjob
            self._order.append(fjob.id)
            if idempotency_key is not None:
                self._idem[idempotency_key] = fjob.id
        # Seeded fleet chaos (deterministic for a deterministic
        # submission schedule): device.flaky fires per submission
        # ATTEMPT — it must inject into the chaos dict the pool submit
        # carries; device.lost fires per successful PLACEMENT (below) so
        # a rejected submission cannot swallow the seeded loss.
        try:
            flaky_inj = chaos_mod.fire("device.flaky")
            if flaky_inj is not None:
                chaos = dict(chaos or {})
                chaos.setdefault(
                    "freeze_at_depth", int(flaky_inj.get("depth", 3))
                )
                if flaky_inj.get("once", 1):
                    chaos.setdefault("marker", True)
            healthy = sorted(
                self._healthy_devices(),
                key=lambda i: self._route_load(i, priority),
            )
            pool_job: Optional[Job] = None
            device: Optional[int] = None
            forced_host = False
            rejections: List[AdmissionError] = []
            for i in healthy:
                try:
                    pool_job = self.pools[i].submit(
                        spec,
                        max_seconds=max_seconds,
                        max_states=max_states,
                        chaos=chaos,
                        idempotency_key=idempotency_key,
                        trace_id=fjob.trace_id,
                        tenant=tenant,
                        priority=priority,
                        deadline_s=deadline_s,
                        symmetry=symmetry,
                    )
                    device = i
                    break
                except AdmissionError as e:
                    rejections.append(e)
                    if e.retry_after_s is None:
                        # Budget/lint rejection: identical on every
                        # device — trying the siblings is pure waste.
                        break
            if pool_job is None and all(
                e.retry_after_s is not None for e in rejections
            ):
                # Elastic wake-on-pressure: a quiesced pool beats both
                # host degradation and a queue-full/shed rejection. (A
                # hint-less rejection — budget, lint — is identical on
                # every pool, so waking one wouldn't help.)
                woken = self._wake_for_pressure()
                if woken is not None:
                    try:
                        pool_job = self.pools[woken].submit(
                            spec,
                            max_seconds=max_seconds,
                            max_states=max_states,
                            chaos=chaos,
                            idempotency_key=idempotency_key,
                            trace_id=fjob.trace_id,
                            tenant=tenant,
                            priority=priority,
                            deadline_s=deadline_s,
                            symmetry=symmetry,
                        )
                        device = woken
                    except AdmissionError as e:
                        rejections.append(e)
            if pool_job is None and not rejections:
                # No healthy device at all: the last resort. Host engine
                # on the least-loaded ALIVE pool — degradation only when
                # EVERY device is open/lost, never as the first response.
                alive = sorted(self._alive_devices(), key=self._pool_load)
                if not alive:
                    raise self._reject(
                        fjob, AdmissionError("no devices left in the fleet")
                    )
                try:
                    pool_job = self.pools[alive[0]].submit(
                        spec,
                        max_seconds=max_seconds,
                        max_states=max_states,
                        chaos=chaos,
                        idempotency_key=idempotency_key,
                        engine="host",
                        trace_id=fjob.trace_id,
                        tenant=tenant,
                        priority=priority,
                        deadline_s=deadline_s,
                        symmetry=symmetry,
                    )
                    device = alive[0]
                    forced_host = True
                except AdmissionError as e:
                    rejections.append(e)
            if pool_job is None:
                hinted = [
                    e for e in rejections if e.retry_after_s is not None
                ]
                if hinted:
                    best = min(hinted, key=lambda e: e.retry_after_s)
                    err: AdmissionError = AdmissionError(
                        f"all devices rejected: {best.reason}",
                        retry_after_s=best.retry_after_s,
                    )
                else:
                    err = rejections[0] if rejections else AdmissionError(
                        "no devices accepted the job"
                    )
                raise self._reject(fjob, err)
        except AdmissionError:
            raise  # already unwound through _reject above
        except BaseException as e:
            # A non-admission failure (malformed-spec ValueError from
            # registry.parse, RuntimeError from a concurrently-closing
            # pool) must not leak the reserved handle as a permanently-
            # queued zombie: unwind it — the key stays retryable, any
            # deduped waiter settles — and re-raise the original.
            self._reject(fjob, AdmissionError(
                f"submit failed: {type(e).__name__}: {e}"
            ))
            raise
        lost_inj = chaos_mod.fire("device.lost")
        with self._lock:
            fjob.device = device
            fjob.pool_job = pool_job
            self._counters.inc("admitted")
            self._counters.inc("routed")
            if forced_host:
                self._counters.inc("host_last_resort")
            if flaky_inj is not None:
                self._counters.inc("device_flakes")
            self._jlog(
                "routed", job=fjob.id, spec=spec, device=device,
                pool_job=pool_job.id, idempotency_key=idempotency_key,
                host=forced_host or None,
                trace_id=fjob.trace_id,
                tenant=tenant, priority=priority, deadline_s=deadline_s,
                symmetry=symmetry,
            )
            landed_lost = device in self._lost
        if self._tracer.enabled:
            self._tracer.emit(
                "route",
                t0=time.monotonic(),
                dur=0.0,
                attrs={
                    "job": fjob.id, "spec": spec,
                    "device": self._device_label(device),
                    "pool_job": pool_job.id,
                    "host": bool(forced_host),
                },
                trace_id=fjob.trace_id,
            )
        if landed_lost and not forced_host:
            # device_lost ran while we were routing (its evacuation
            # sweep predates this placement): evacuate again so the
            # monitor migrates the just-landed job too, instead of
            # leaving it to wedge on the dead device.
            self.pools[device].evacuate(reason=f"device-{device} lost")
            self._wake.set()
        self._ensure_monitor()
        if lost_inj is not None:
            target = int(lost_inj.get("device", device))
            after_s = float(lost_inj.get("after_s", 1))
            self.log(
                f"chaos device.lost armed: device-{target} in {after_s}s"
            )
            timer = threading.Timer(after_s, self.device_lost, args=(target,))
            timer.daemon = True
            with self._lock:
                # Prune fired/cancelled timers so a long chaos soak
                # doesn't accumulate one dead Timer per loss.
                self._timers = [
                    t for t in self._timers if t.is_alive()
                ] + [timer]
            timer.start()
        return fjob

    def _reject(self, fjob: FleetJob, err: AdmissionError) -> AdmissionError:
        """Unwind a reserved-but-unplaced submission: unregister the
        handle (the caller may retry the key) and mark it terminal-failed
        so a concurrent waiter that deduped onto it mid-routing settles
        instead of polling forever. Returns ``err`` for the caller to
        raise."""
        with self._lock:
            self._counters.inc("rejected")
            self._jobs.pop(fjob.id, None)
            try:
                self._order.remove(fjob.id)
            except ValueError:
                pass
            key = fjob.idempotency_key
            if key is not None and self._idem.get(key) == fjob.id:
                del self._idem[key]
            fjob._rejected = getattr(err, "reason", None) or str(err)
        return err

    # -- failover ----------------------------------------------------------

    def device_lost(self, i: int) -> None:
        """Declare device ``i`` dead (the operator's — and the chaos
        layer's — entry point): its pool's workers are killed, its
        non-terminal jobs evacuate, and the monitor migrates them to
        healthy siblings. The pool object stays constructed so its
        terminal jobs remain queryable; routing never picks it again
        this incarnation (a restart re-probes all devices fresh)."""
        with self._lock:
            if self._closed or i in self._lost or not (
                0 <= i < len(self.pools)
            ):
                return
            self._lost.add(i)
            self._counters.inc("devices_lost")
        self.log(f"device-{i} LOST; evacuating its jobs")
        self.pools[i].evacuate(reason=f"device-{i} lost")
        self._ensure_monitor()
        self._wake.set()

    def _migrate_stragglers(self) -> int:
        """The repair pass (monitor loop + restart): every fleet job whose
        current pool job reads ``migrated`` is resubmitted to a healthy
        sibling, seeded with the victim's checkpoint rotation and spent
        wall-clock — and every recovered job a restart could NOT
        re-attach (orphaned: torn/lost pool journal, smaller fleet)
        re-routes from its journaled spec, or fails typed when even that
        is gone, so waiters never poll forever. Returns how many moved."""
        moved = 0
        with self._lock:
            pending = [
                fjob for fjob in self._jobs.values()
                if (
                    fjob.pool_job is not None
                    and fjob.pool_job.status == "migrated"
                )
                or (
                    fjob.pool_job is None
                    and fjob.recovered
                    and fjob._rejected is None
                )
            ]
        for fjob in pending:
            old = fjob.pool_job
            from_device = fjob.device
            if old is not None:
                seed = None
                if old.dir is not None:
                    seed = latest_valid_checkpoint(old.checkpoint_path)
                if seed is None:
                    # migrated twice before running
                    seed = old.seed_checkpoint
                spec = old.spec
                resume_kwargs = dict(
                    max_seconds=old.max_seconds,
                    max_states=old.max_states,
                    chaos=dict(old.chaos) or None,
                    spent_s=old.consumed_s,
                    resume_from=seed,
                    # Migration keeps the victim's trace: the new hop's
                    # spans stitch onto the same timeline.
                    trace_id=fjob.trace_id or old.trace_id,
                    # ... and its QoS identity: the new hop schedules in
                    # the same class under the same tenant's quotas.
                    tenant=old.tenant,
                    priority=old.priority,
                    deadline_s=old.deadline_s,
                    symmetry=old.symmetry,
                )
                reason = old.error
                requeues = old.requeues
            else:
                # Orphaned recovery: the victim pool's copy is gone, so
                # budgets/chaos/checkpoints died with it — re-route the
                # journaled spec from scratch on pool defaults.
                spec = fjob._orphan_spec
                if spec is None:
                    with self._lock:
                        fjob._rejected = (
                            "unrecoverable after fleet restart: the "
                            "routed spec was lost with the pool journal"
                        )
                    continue
                seed = None
                resume_kwargs = dict(
                    tenant=fjob.tenant,
                    priority=fjob.priority,
                    deadline_s=fjob.deadline_s,
                    symmetry=fjob.symmetry,
                )
                if fjob.trace_id:
                    resume_kwargs["trace_id"] = fjob.trace_id
                reason = "orphaned by fleet restart"
                requeues = 0
            healthy = sorted(
                self._healthy_devices(),
                key=lambda d: self._route_load(
                    d, resume_kwargs.get("priority")
                ),
            )
            if not healthy and self._wake_for_pressure() is not None:
                # Migrating onto a woken elastic pool beats forcing the
                # host engine.
                healthy = sorted(
                    self._healthy_devices(),
                    key=lambda d: self._route_load(
                        d, resume_kwargs.get("priority")
                    ),
                )
            candidates = healthy or sorted(
                self._alive_devices(), key=self._pool_load
            )
            if not candidates:
                continue  # nothing to move to; retry next sweep
            target = candidates[0]
            forced_host = not healthy
            try:
                new_job = self.pools[target].submit(
                    spec,
                    engine="host" if forced_host else "auto",
                    # Deterministic per-hop key: a fleet crash between
                    # the sibling's `submitted` append and our
                    # `migrated` append leaves the resubmission findable
                    # — the restart's _recover re-attaches it by this
                    # key instead of double-running (and a same-target
                    # retry in THIS incarnation dedupes at the pool).
                    idempotency_key=(
                        f"fleet-mig:{fjob.id}:{len(fjob.migrations) + 1}"
                    ),
                    **resume_kwargs,
                )
            except AdmissionError as e:
                self.log(f"migration of {fjob.id} to device-{target} "
                         f"rejected ({e.reason}); will retry")
                continue
            except RuntimeError:
                return moved  # target pool closing: the fleet is too
            except Exception as e:  # noqa: BLE001 - the verdict IS the handling
                # Unroutable (e.g. a journaled spec whose user family
                # isn't registered in this incarnation): a retry would
                # throw identically — fail typed so waiters settle
                # instead of the sweep dying and stalling every other
                # pending migration.
                with self._lock:
                    fjob._rejected = (
                        f"migration failed: {type(e).__name__}: {e}"
                    )
                self.log(f"{fjob.id} unroutable: {e!r}")
                continue
            with self._lock:
                fjob.migrations.append(
                    {
                        "from": from_device,
                        "to": target,
                        "reason": reason,
                        "requeues": requeues,
                        "seed": seed,
                        "unix_ts": time.time(),
                    }
                )
                fjob.device = target
                fjob.pool_job = new_job
                self._counters.inc("migrations")
                if forced_host:
                    self._counters.inc("host_last_resort")
                self._jlog(
                    "migrated", job=fjob.id, from_device=from_device,
                    to_device=target, pool_job=new_job.id,
                    reason=reason, seed=seed,
                    trace_id=fjob.trace_id,
                )
                landed_lost = target in self._lost
            if self._tracer.enabled:
                self._tracer.emit(
                    "migrate",
                    t0=time.monotonic(),
                    dur=0.0,
                    attrs={
                        "job": fjob.id,
                        "from_device": self._device_label(from_device)
                        if from_device is not None else None,
                        "device": self._device_label(target),
                        "pool_job": new_job.id,
                        "reason": reason,
                    },
                    trace_id=fjob.trace_id,
                )
            if landed_lost and not forced_host:
                # The target died while we migrated onto it: evacuate
                # again — the next sweep moves the job once more.
                self.pools[target].evacuate(
                    reason=f"device-{target} lost"
                )
                self._wake.set()
            self.log(
                f"{fjob.id} migrated device-{from_device} -> "
                f"device-{target} (seed={seed})"
            )
            moved += 1
        return moved

    def _monitor_loop(self) -> None:
        while True:
            self._wake.wait(timeout=self._cfg.monitor_interval_s)
            self._wake.clear()
            with self._lock:
                if self._closed:
                    return
            # Open breakers on non-lost devices: evacuate so the repair
            # pass can move their held jobs to healthy silicon. Skip when
            # NOTHING is healthy — with every breaker open the held jobs
            # are better off waiting for a probe-close than thrashing
            # into host-forced churn (host last resort applies to NEW
            # work; queued work migrates only when a healthy target
            # exists).
            try:
                healthy = self._healthy_devices()
                if healthy:
                    for i in self._alive_devices():
                        if i in healthy:
                            continue
                        pool = self.pools[i]
                        if pool.degraded and any(
                            j.kind == "batch" and not j.done
                            # Forced-host jobs ride out the outage in
                            # place (evacuate() skips them —
                            # device-independent).
                            and j.engine_force != "host"
                            for j in pool.jobs()
                        ):
                            self.log(
                                f"device-{i} breaker open; "
                                "evacuating its jobs"
                            )
                            pool.evacuate(reason=f"device-{i} breaker open")
                self._migrate_stragglers()
                if self._cfg.elastic:
                    self._elastic_sweep()
            except Exception as e:  # noqa: BLE001 - monitor must survive
                # A dead monitor stalls every pending migration and
                # hangs waiters; log the sweep's failure and keep going.
                self.log(f"fleet monitor sweep failed: {e!r}")
            with self._lock:
                if self._closed:
                    return
                # Idle exit: every fleet job terminal, nothing pending —
                # don't sweep every pool's locks forever on a long-lived
                # Explorer fleet. Clearing _monitor under the lock makes
                # the handoff race-free: submit()/device_lost()/an open-
                # breaker listener re-ensure a fresh monitor, and a job
                # inserted before this check reads as not-done.
                # (Field reads, not FleetJob.done — the property takes
                # this very lock through _current().)
                if (
                    all(
                        j._rejected is not None
                        or (
                            j.pool_job is not None
                            and j.pool_job.status in ("done", "failed")
                        )
                        for j in self._jobs.values()
                    )
                    and not self._wake.is_set()
                    # An elastic fleet keeps sweeping until the idle
                    # pools have quiesced down to min_active — only then
                    # is there nothing left for the monitor to do.
                    and (
                        not self._cfg.elastic
                        or len([
                            i for i in range(len(self.pools))
                            if i not in self._lost
                            and i not in self._quiesced
                        ]) <= max(1, self._cfg.min_active)
                    )
                ):
                    self._monitor = None
                    return

    # -- surface (mirrors CheckerService) ----------------------------------

    def job(self, fleet_id: str) -> FleetJob:
        return self._jobs[fleet_id]

    def jobs(self) -> List[FleetJob]:
        with self._lock:
            return [self._jobs[fid] for fid in self._order]

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        for fjob in self.jobs():
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                return all(j.done for j in self.jobs())
            if not fjob.wait(timeout=remaining):
                return False
        return True

    @property
    def degraded(self) -> bool:
        """True when NO device is healthy (every breaker open or device
        lost) — the fleet-level analogue of a pool's open breaker."""
        return not self._healthy_devices()

    def gauges(self) -> Dict[str, Any]:
        """Fleet-wide aggregates at the top level (the dashboard header
        and ``/.status``'s ``pool`` read these like a single pool's),
        per-device pool gauges under ``devices``."""
        devices = {
            self._device_label(i): dict(
                pool.gauges(),
                lost=(i in self._lost),
                quiesced=(i in self._quiesced),
            )
            for i, pool in enumerate(self.pools)
        }
        # Fleet-wide per-class/per-tenant rollup: count keys sum across
        # devices; weight is a config constant, taken from any row.
        qos_classes: Dict[str, Dict[str, Any]] = {}
        qos_tenants: Dict[str, Dict[str, Any]] = {}
        for d in devices.values():
            qos = d.get("qos") or {}
            for cls, row in (qos.get("classes") or {}).items():
                agg = qos_classes.setdefault(
                    cls, {"weight": row.get("weight")}
                )
                for k in ("queued", "running", "quarantined", "done",
                          "failed", "migrated", "served"):
                    agg[k] = agg.get(k, 0) + (row.get(k) or 0)
            for tenant, row in (qos.get("tenants") or {}).items():
                agg = qos_tenants.setdefault(tenant, {})
                for k in ("queued", "running", "done", "failed",
                          "spent_s"):
                    agg[k] = agg.get(k, 0) + (row.get(k) or 0)
        agg_keys = (
            "queued", "running", "quarantined", "interactive", "done",
            "failed", "migrated", "jobs_done", "jobs_failed",
            "wedge_verdicts", "crashes", "requeues", "degraded_jobs",
            "jobs_evacuated",
        )
        out: Dict[str, Any] = {
            k: sum(d.get(k, 0) or 0 for d in devices.values())
            for k in agg_keys
        }
        healthy = self._healthy_devices()
        with self._lock:
            out.update(
                fleet=True,
                devices=devices,
                device_count=len(self.pools),
                healthy_devices=len(healthy),
                lost_devices=sorted(self._lost),
                quiesced_devices=sorted(self._quiesced),
                elastic=self._cfg.elastic,
                qos={"classes": qos_classes, "tenants": qos_tenants},
                breaker={
                    # The fleet-level verdict the dashboard badge renders:
                    # open only when NO device can take device work.
                    "state": "closed" if healthy else "open",
                    "open_devices": [
                        self._device_label(i)
                        for i in range(len(self.pools))
                        if i in self._lost or self.pools[i].degraded
                    ],
                    "k": len(self.pools),
                    "consecutive_wedges": max(
                        (
                            d["breaker"]["consecutive_wedges"]
                            for d in devices.values()
                        ),
                        default=0,
                    ),
                    "opened_unix_ts": None,
                },
                journal=(
                    None
                    if self._journal is None
                    else {
                        "path": self._journal.path,
                        "records": self._journal.seq,
                        "since_compact": self._journal.since_compact,
                        "recovery": self._recovery,
                    }
                ),
                **self._counters.snapshot(),
            )
        return out

    def metrics(self) -> Dict[str, Any]:
        out = self.gauges()
        # Collect under the lock, snapshot outside it: FleetJob.snapshot
        # re-reads its placement through the fleet lock (non-reentrant).
        with self._lock:
            ordered = [(fid, self._jobs[fid]) for fid in self._order]
        out["jobs"] = {fid: fjob.snapshot() for fid, fjob in ordered}
        return out

    # -- per-job telemetry (Explorer endpoints) ----------------------------

    def _pool_of(self, fleet_id: str):
        fjob = self._jobs[fleet_id]  # KeyError -> 404, like a pool
        with self._lock:
            if fjob.pool_job is None or fjob.device is None:
                raise KeyError(fleet_id)
            return self.pools[fjob.device], fjob.pool_job

    def job_trace_chrome(self, fleet_id: str,
                         out_path: Optional[str] = None) -> Optional[str]:
        pool, job = self._pool_of(fleet_id)
        return pool.job_trace_chrome(job.id, out_path)

    @property
    def run_dir(self) -> str:
        return self._cfg.run_dir

    def merged_trace_chrome(self, out_path: Optional[str] = None) -> Optional[str]:
        """The fleet-wide merged timeline: ``obs.collect`` over the fleet
        run dir — the router's spans, every device pool's, every
        job/lane's — one Chrome trace with flow arrows across routing,
        attempts, and migration hops. Mtime-cached; the Explorer's
        ``GET /.trace.json`` polls this."""
        from ..obs import collect as collect_mod

        files = collect_mod.trace_files(self._cfg.run_dir)
        if not files:
            return None
        dst = out_path or os.path.join(self._cfg.run_dir, "trace.merged.json")
        try:
            dst_m = os.stat(dst).st_mtime
            fresh = all(os.stat(p).st_mtime <= dst_m for p in files)
        except OSError:
            fresh = False
        if not fresh:
            collect_mod.write(self._cfg.run_dir, dst)
        return dst

    def job_metrics_series(self, fleet_id: str,
                           window: Optional[int] = None):
        pool, job = self._pool_of(fleet_id)
        return pool.job_metrics_series(job.id, window=window)

    # -- interactive sessions (the Explorer client surface) ----------------

    def _session_counts(self) -> int:
        return sum(p.gauges()["interactive"] for p in self.pools)

    def _session_cap(self) -> int:
        if self._cfg.max_sessions is not None:
            return self._cfg.max_sessions
        return sum(p._cfg.max_sessions for p in self.pools)

    def _check_session_capacity_locked(self) -> None:
        """Caller holds ``_session_lock``."""
        if self._session_counts() >= self._session_cap():
            with self._lock:
                self._counters.inc("submitted")
                self._counters.inc("rejected")
            raise AdmissionError(
                f"interactive sessions full ({self._session_cap()})",
                retry_after_s=30.0,
            )
        # The chosen pool's own pre-check still applies at registration.

    def check_session_capacity(self) -> None:
        with self._session_lock:
            self._check_session_capacity_locked()

    def register_interactive(self, checker, *,
                             label: Optional[str] = None,
                             degraded: bool = False) -> Job:
        """Sessions spread to the alive pool with the fewest of them (an
        in-process checker has no device residency on the CPU box, but
        per-device accounting keeps ``/.pool`` honest on chip). Cap
        re-check and registration happen under one lock: two concurrent
        registrations must not both pass an N-1 count and land N+1
        sessions."""
        with self._session_lock:
            self._check_session_capacity_locked()
            candidates = self._alive_devices() or [0]
            target = min(
                candidates,
                key=lambda i: self.pools[i].gauges()["interactive"],
            )
            job = self.pools[target].register_interactive(
                checker, label=label, degraded=degraded
            )
            with self._lock:
                # Mirror the cap-rejection path's accounting (which incs
                # submitted+rejected): without these the fleet counters
                # read >100% session rejection rates.
                self._counters.inc("submitted")
                self._counters.inc("admitted")
            return job

    def release_interactive(self, job: Job) -> None:
        job._service.release_interactive(job)
