"""Reusable bit-packing toolkit for device-checkable (Packed) models.

The reference needs no per-model engine code: any ``Model`` works because
states live on the heap (``/root/reference/src/lib.rs:155-254``).  The device
engine instead needs fixed-width states, and round 1 hand-rolled a bespoke
codec per model (~200 LoC of shift arithmetic each).  This module is the
generic replacement: models *declare* layouts and get host pack/unpack and
jnp-traceable device accessors, with loud overflow detection (the packed
analogue of the reference's panics on broken invariants).

Pieces, bottom-up:

- :class:`Layout` / :class:`LayoutBuilder` — named bit-fields over uint32
  words.  Fields never span word boundaries; array fields are uniformly
  strided so a *traced* index can address them on device.
- :class:`SlotMultiset` — the fixed-width form of the non-duplicating
  multiset network (``network.rs:54-55``): K word-sized slots, each
  ``code << count_bits | count``, kept sorted so equal multisets pack to
  equal words (the packed analogue of the order-insensitive hashing in
  ``util.rs:134-156``).  ``count_bits=0`` degrades to a duplicating *set*
  (``network.rs:51-52``).
- :class:`FifoLanes` — the ordered network (``network.rs:57-67``): one
  bounded FIFO lane per directed flow; only heads are deliverable.
- :class:`BoundedHistory` — a fixed-width encoding of the backtracking
  consistency testers (``semantics/linearizability.rs:57-126``) for
  clients with statically bounded operation counts; converts exactly
  to/from :class:`~stateright_tpu.semantics._backtracking.BacktrackingTester`
  so packed actor models can carry the same auxiliary history the object
  models do.

Everything device-side is functional: ops take and return the state's word
vector ``words[W]`` (uint32) and fuse into the engine superstep.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np


def bits_for(maxval: int) -> int:
    """Field width (>=1) that holds values ``0..maxval``."""
    return max(int(maxval).bit_length(), 1)


class PackedModelAdapter:
    """Object-level ``Model`` surface for packed models that wrap an inner
    object model in ``self._inner`` (the pattern of the packed register and
    Paxos models): every Model-API call — ``init_states``, ``actions``,
    ``next_state``, ``properties``, ``within_boundary``, display hooks —
    resolves to the inner model via ``__getattr__``; only ``checker()`` must
    bind to the packed wrapper itself so ``spawn_xla`` sees the packed
    kernels alongside the object-level contract."""

    def checker(self):
        from .checker.builder import CheckerBuilder

        return CheckerBuilder(self)

    def packed_init(self):
        """Packed initial states: the inner model's, through ``pack``."""
        import numpy as np

        return np.stack([self.pack(s) for s in self._inner.init_states()])

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._inner, name)


class Field(NamedTuple):
    name: str
    bits: int  # bits per element
    count: int  # number of elements (1 for scalars)
    word: int  # first word index
    shift: int  # bit offset of element 0 in its word
    epw: int  # elements per word (array fields are word-aligned)
    is_array: bool  # declared via array()/words(): list-valued in pack/unpack


class OverflowError32(RuntimeError):
    """A value exceeded its declared field width at host pack time."""


class LayoutBuilder:
    """Accumulates fields; ``finish()`` freezes them into a :class:`Layout`.

    Scalars pack densely left-to-right within words.  Array fields are
    word-aligned with a fixed stride (``32 // bits`` elements per word) so
    device code can address element ``i`` with traced ``i``.
    """

    def __init__(self) -> None:
        self._fields: Dict[str, Field] = {}
        self._word = 0
        self._bit = 0

    def _align_word(self) -> None:
        if self._bit:
            self._word += 1
            self._bit = 0

    def uint(self, name: str, bits: int) -> "LayoutBuilder":
        """A scalar field of ``bits`` (1..32) bits."""
        if not 1 <= bits <= 32:
            raise ValueError(f"field {name}: bits must be 1..32, got {bits}")
        if name in self._fields:
            raise ValueError(f"duplicate field {name}")
        if self._bit + bits > 32:
            self._align_word()
        self._fields[name] = Field(
            name, bits, 1, self._word, self._bit, max(32 // bits, 1), False
        )
        self._bit += bits
        if self._bit == 32:
            self._align_word()
        return self

    def flag(self, name: str) -> "LayoutBuilder":
        return self.uint(name, 1)

    def array(self, name: str, count: int, bits: int) -> "LayoutBuilder":
        """``count`` elements of ``bits`` bits, word-aligned, uniformly
        strided (indexable with a traced index on device)."""
        if not 1 <= bits <= 32:
            raise ValueError(f"field {name}: bits must be 1..32, got {bits}")
        if name in self._fields:
            raise ValueError(f"duplicate field {name}")
        self._align_word()
        epw = 32 // bits
        self._fields[name] = Field(name, bits, count, self._word, 0, epw, True)
        self._word += (count + epw - 1) // epw
        return self

    def words(self, name: str, count: int) -> "LayoutBuilder":
        """``count`` full uint32 words (for sub-codecs like SlotMultiset)."""
        return self.array(name, count, 32)

    def finish(self) -> "Layout":
        self._align_word()
        return Layout(dict(self._fields), self._word)


class Layout:
    def __init__(self, fields: Dict[str, Field], words: int):
        self.fields = fields
        self.words = words

    # --- device/host accessors (xp-agnostic: jnp under trace, np on host) --

    def get(self, words, name: str, idx: Any = 0):
        """Read field ``name`` (element ``idx`` for arrays). ``idx`` may be
        a traced value for array fields."""
        f = self.fields[name]
        if f.bits == 32:
            return words[f.word + idx]
        mask = np.uint32((1 << f.bits) - 1)
        if not f.is_array:
            return (words[f.word] >> np.uint32(f.shift)) & mask
        w = f.word + idx // f.epw
        sh = (idx % f.epw) * f.bits
        return (words[w] >> _u32(sh)) & mask

    def set(self, words, name: str, value, idx: Any = 0):
        """Return a new word vector with field ``name`` set. jnp path only
        (host packing goes through :meth:`pack`)."""
        f = self.fields[name]
        mask = np.uint32((1 << f.bits) - 1) if f.bits < 32 else np.uint32(0xFFFFFFFF)
        value = _u32(value) & mask
        if f.bits == 32:
            return _word_update(words, f.word + idx, value)
        if not f.is_array:
            w = f.word
            sh = np.uint32(f.shift)
            inv = np.uint32(~(int(mask) << f.shift) & 0xFFFFFFFF)
            return words.at[w].set((words[w] & inv) | (value << sh))
        w = f.word + idx // f.epw
        sh = _u32((idx % f.epw) * f.bits)
        cleared = words[w] & ~(_u32(mask) << sh)
        return _word_update(words, w, cleared | (value << sh))

    # --- host codec --------------------------------------------------------

    def pack(self, **values: Any) -> np.ndarray:
        """Pack named values (ints, or sequences for array fields) into a
        fresh word vector; unset fields are zero. Overflow raises."""
        out = np.zeros(self.words, dtype=np.uint32)
        for name, value in values.items():
            f = self.fields[name]
            elems = list(value) if f.is_array else [value]
            if len(elems) > f.count:
                raise OverflowError32(f"{name}: {len(elems)} elements > {f.count}")
            limit = 1 << f.bits
            for i, v in enumerate(elems):
                v = int(v)
                if not 0 <= v < limit:
                    raise OverflowError32(
                        f"{name}[{i}] = {v} exceeds {f.bits}-bit field"
                    )
                w = f.word + i // f.epw
                sh = (i % f.epw) * f.bits if f.is_array else f.shift
                out[w] |= np.uint32(v << sh)
        return out

    def unpack(self, words) -> Dict[str, Any]:
        """Host inverse of :meth:`pack`: field name -> int or list of ints."""
        words = [int(w) for w in words]
        out: Dict[str, Any] = {}
        for name, f in self.fields.items():
            mask = (1 << f.bits) - 1 if f.bits < 32 else 0xFFFFFFFF
            if not f.is_array:
                out[name] = (words[f.word] >> f.shift) & mask
            else:
                out[name] = [
                    (words[f.word + i // f.epw] >> ((i % f.epw) * f.bits)) & mask
                    for i in range(f.count)
                ]
        return out


def _u32(x):
    """Coerce to uint32 under either numpy or jax tracing."""
    if isinstance(x, (int, np.integer)):
        return np.uint32(x)
    import jax.numpy as jnp

    return x.astype(jnp.uint32) if hasattr(x, "astype") else jnp.uint32(x)


#: Force the one-hot (True) or scatter (False) lowering of traced-index
#: word writes; None resolves by backend (one-hot off-CPU). Tests pin the
#: accelerator lowering's HLO on the CPU backend through this.
ONE_HOT_WRITES = None


def _word_update(vec, i, value):
    """``vec`` with element ``i`` (possibly traced) replaced by ``value``,
    WITHOUT a scatter: one-hot compare-iota + ``where`` over the (tiny)
    vector axis.

    This lowering is load-bearing for correctness on TPU. The natural
    ``vec.at[i].set(value)`` becomes a data-dependent one-element scatter
    inside the vmapped model kernels, and XLA:TPU silently DROPS a
    data-dependent subset of those scatters once the vmap batch reaches
    4096 (first seen round 5 on the paxos ``net`` presence-bit sends:
    count-exact at every bucket <= 2048, +530 phantom uniques at 4096 —
    ``tools/paxos_diag.py`` bisects it to this op, bit-level evidence in
    ``tpu_paxos_diag.log``). The one-hot form is pure elementwise
    select — the op class every backend lowers reliably — and the vectors
    here are model words/slots (W <= ~25), so the broadcast costs nothing
    against the scatter it replaces. Static indices take the same path;
    XLA folds the concrete compare-iota to a static update.

    The same failure family on the other backend: XLA:CPU miscompiles a
    transpose fused into a vmapped kernel (xla.py:_build_superstep_planes,
    round 3b). Model-kernel writes must stay in this helper.

    Backend-split: on CPU the one-element scatter is both correct (four
    rounds of exact counts) and O(1), while the one-hot form pays O(W)
    per write — measured as a multi-fold slowdown of the serializer-heavy
    consistency tests — so CPU keeps ``.at[i].set``. Accelerators take
    the one-hot path unconditionally. ``ONE_HOT_WRITES`` (None = by
    backend) lets the CPU test suite pin the accelerator lowering's HLO
    without a chip."""
    import jax
    import jax.numpy as jnp

    one_hot = ONE_HOT_WRITES
    if one_hot is None:
        one_hot = jax.default_backend() != "cpu"
    if not one_hot:
        return vec.at[i].set(jnp.asarray(value, vec.dtype))
    hot = jnp.arange(vec.shape[0], dtype=jnp.uint32) == _u32(i)
    return jnp.where(hot, jnp.asarray(value, vec.dtype), vec)


# --------------------------------------------------------------------------
# Sorted-slot multiset: the packed non-duplicating network.
# --------------------------------------------------------------------------


class SlotMultiset:
    """K word-sized slots holding a canonical (sorted) multiset of envelope
    codes.

    Slot encoding: ``(code + 1) << count_bits | count`` — the +1 reserves 0
    for EMPTY even for code 0. ``count`` is stored as count-1 (a present
    slot has count >= 1), so ``count_bits`` caps multiplicity at
    ``2**count_bits``. ``count_bits=0`` is the duplicating-set variant:
    presence only, deliver keeps the slot (redeliverable, network.rs:204).

    The slots are a view over a ``Layout`` words-field named ``field``;
    all ops return updated full word vectors, keeping slots sorted
    ascending (EMPTY=0 slots first) so equal multisets have equal words.
    """

    def __init__(self, layout: Layout, field: str, code_bits: int, count_bits: int):
        f = layout.fields[field]
        if f.bits != 32:
            raise ValueError("SlotMultiset requires a words() field")
        if code_bits + 1 + count_bits > 32:
            raise ValueError("code_bits + count_bits must fit a word (with +1 code)")
        self.layout = layout
        self.field = field
        self.k = f.count
        self.base = f.word
        self.code_bits = code_bits
        self.count_bits = count_bits
        self.max_count = 1 << count_bits

    # --- device ops --------------------------------------------------------

    def slots(self, words):
        import jax.numpy as jnp

        return jnp.asarray(words[self.base : self.base + self.k])

    def _with_slots(self, words, slots):
        import jax.numpy as jnp

        slots = jnp.sort(slots)  # canonical: EMPTY(0) first, then by code
        return words.at[self.base : self.base + self.k].set(slots)

    def decode(self, slots):
        """(codes[K], counts[K], present[K]) from raw slots."""
        import jax.numpy as jnp

        cb = jnp.uint32(self.count_bits)
        present = slots != 0
        codes = (slots >> cb) - jnp.where(present, jnp.uint32(1), jnp.uint32(0))
        counts = jnp.where(
            present, (slots & jnp.uint32(self.max_count - 1)) + jnp.uint32(1), 0
        ).astype(jnp.uint32)
        return codes, counts, present

    def send(self, words, code, enabled=True):
        """Add one instance of ``code``; returns ``(words', overflow)``.
        Overflow = no free slot for a new code, or count saturated."""
        import jax.numpy as jnp

        enabled = jnp.asarray(enabled)
        s = self.slots(words)
        cb = jnp.uint32(self.count_bits)
        code = _u32(code)
        encoded = (code + jnp.uint32(1)) << cb
        present = s != 0
        match = present & ((s >> cb) == (code + jnp.uint32(1)))
        has = jnp.any(match)
        if self.count_bits == 0:
            # Duplicating set: membership only.
            bumped = s
            count_ovf = jnp.bool_(False)
        else:
            at_max = match & (
                (s & jnp.uint32(self.max_count - 1)) == jnp.uint32(self.max_count - 1)
            )
            count_ovf = jnp.any(at_max)
            # A saturated count must NOT bump: the +1 would carry into the
            # code bits and decode as a different envelope. The word stays
            # unchanged and only the overflow flag reports the problem.
            bumped = jnp.where(match & ~at_max, s + jnp.uint32(1), s)
        first_empty = jnp.argmin(jnp.where(present, 1, 0))  # slots sorted: empties first
        can_insert = ~present[first_empty]
        inserted = _word_update(s, first_empty, encoded)
        s_new = jnp.where(has, bumped, jnp.where(can_insert, inserted, s))
        overflow = enabled & jnp.where(has, count_ovf, ~can_insert)
        s_new = jnp.where(enabled, s_new, s)
        return self._with_slots(words, s_new), overflow

    def remove_slot(self, words, i, enabled=True):
        """Remove one instance from slot ``i`` (deliver on a non-duplicating
        network, or drop); returns ``words'``. No-op when disabled."""
        import jax.numpy as jnp

        enabled = jnp.asarray(enabled)
        s = self.slots(words)
        si = s[i]
        last = (si & jnp.uint32(self.max_count - 1)) == 0 if self.count_bits else jnp.bool_(True)
        new_si = jnp.where(last, jnp.uint32(0), si - jnp.uint32(1))
        s = _word_update(s, i, jnp.where(enabled, new_si, si))
        return self._with_slots(words, s)

    # --- host codec --------------------------------------------------------

    def host_pack(self, code_counts: Sequence[Tuple[int, int]]) -> List[int]:
        """Sorted slot words from (code, count) pairs; raises loudly on
        capacity or width overflow (SURVEY §7 hard part 2)."""
        if len(code_counts) > self.k:
            raise OverflowError32(
                f"{len(code_counts)} distinct envelopes > {self.k} slots"
            )
        codes = [c for c, _n in code_counts]
        if len(set(codes)) != len(codes):
            raise OverflowError32(
                "duplicate envelope codes — merge counts before packing "
                "(duplicates would break canonical slot words)"
            )
        slots = []
        for code, count in code_counts:
            if not 0 <= code < (1 << self.code_bits):
                raise OverflowError32(f"envelope code {code} exceeds {self.code_bits} bits")
            if not 1 <= count <= self.max_count:
                raise OverflowError32(
                    f"envelope count {count} outside 1..{self.max_count}"
                )
            slots.append(((code + 1) << self.count_bits) | (count - 1))
        slots.sort()
        return [0] * (self.k - len(slots)) + slots

    def host_unpack(self, slot_words: Sequence[int]) -> List[Tuple[int, int]]:
        out = []
        for s in slot_words:
            s = int(s)
            if s == 0:
                continue
            code = (s >> self.count_bits) - 1
            count = (s & (self.max_count - 1)) + 1 if self.count_bits else 1
            out.append((code, count))
        return out


# --------------------------------------------------------------------------
# FIFO lanes: the packed ordered network.
# --------------------------------------------------------------------------


class FifoLanes:
    """P directed flows, each a bounded FIFO of up to ``depth`` message
    codes (the packed ``Ordered`` network, network.rs:57-67). Only lane
    heads are deliverable; deliver pops the head and shifts.

    Codes are stored +1 (0 = empty cell) in a strided array field of
    ``depth`` elements per lane, plus a length field per lane.
    """

    def __init__(
        self, builder: LayoutBuilder, name: str, lanes: int, depth: int, code_bits: int
    ):
        if code_bits + 1 > 32:
            raise ValueError("code_bits must leave room for the +1 empty sentinel")
        self.lanes = lanes
        self.depth = depth
        self.code_bits = code_bits
        self.cells = f"{name}_cells"
        self.lens = f"{name}_lens"
        builder.array(self.cells, lanes * depth, min(code_bits + 1, 32))
        builder.array(self.lens, lanes, max(depth.bit_length(), 1))
        self.layout: Optional[Layout] = None  # bound by finish()

    def bind(self, layout: Layout) -> "FifoLanes":
        self.layout = layout
        return self

    # --- device ops --------------------------------------------------------

    def length(self, words, lane):
        return self.layout.get(words, self.lens, lane)

    def head(self, words, lane):
        """(code, nonempty) of the lane head."""
        import jax.numpy as jnp

        raw = self.layout.get(words, self.cells, lane * self.depth)
        return raw - jnp.uint32(1), raw != 0

    def push(self, words, lane, code, enabled=True):
        """Append ``code``; returns (words', overflow)."""
        import jax.numpy as jnp

        enabled = jnp.asarray(enabled)
        n = self.length(words, lane)
        overflow = enabled & (n >= jnp.uint32(self.depth))
        ok = enabled & ~overflow
        idx = lane * self.depth + jnp.minimum(n, jnp.uint32(self.depth - 1)).astype(jnp.int32)
        old_cell = self.layout.get(words, self.cells, idx)
        new_cell = jnp.where(ok, _u32(code) + jnp.uint32(1), old_cell)
        words = self.layout.set(words, self.cells, new_cell, idx)
        words = self.layout.set(
            words, self.lens, jnp.where(ok, n + jnp.uint32(1), n), lane
        )
        return words, overflow

    def pop(self, words, lane, enabled=True):
        """Pop the head (deliver/drop); shifts the lane. Returns words'."""
        import jax.numpy as jnp

        enabled = jnp.asarray(enabled)
        n = self.length(words, lane)
        do = enabled & (n > 0)
        for j in range(self.depth - 1):
            idx = lane * self.depth + j
            nxt = self.layout.get(words, self.cells, idx + 1)
            cur = self.layout.get(words, self.cells, idx)
            words = self.layout.set(words, self.cells, jnp.where(do, nxt, cur), idx)
        tail = lane * self.depth + (self.depth - 1)
        cur = self.layout.get(words, self.cells, tail)
        words = self.layout.set(
            words, self.cells, jnp.where(do, jnp.uint32(0), cur), tail
        )
        words = self.layout.set(
            words, self.lens, jnp.where(do, n - jnp.uint32(1), n), lane
        )
        return words

    # --- host codec --------------------------------------------------------

    def host_pack_lane(self, codes: Sequence[int]) -> Tuple[List[int], int]:
        if len(codes) > self.depth:
            raise OverflowError32(f"{len(codes)} queued messages > depth {self.depth}")
        for c in codes:
            if not 0 <= c < (1 << self.code_bits):
                raise OverflowError32(f"message code {c} exceeds {self.code_bits} bits")
        cells = [c + 1 for c in codes] + [0] * (self.depth - len(codes))
        return cells, len(codes)


# --------------------------------------------------------------------------
# Bounded consistency-tester history.
# --------------------------------------------------------------------------


class BoundedHistory:
    """Fixed-width encoding of a :class:`BacktrackingTester` whose threads
    and per-thread operation counts are statically bounded (register-style
    scripted clients, register.rs:94-260).

    Per thread t (identified by its position in ``thread_ids``):
      - ``h{t}_n``        completed-op count (0..max_ops)
      - ``h{t}_fl``       in-flight op code + 1 (0 = none)
      - ``h{t}_flpre``    per-peer prereq index + 2 at invocation
                          (0 = no entry; the tester omits peers with empty
                          history, linearizability.rs:114-126)
      - ``h{t}_op/_ret``  completed op/ret codes (+1; 0 unused)
      - ``h{t}_pre``      per-(slot, peer) prereq index + 2
      - ``h_valid``       the is_valid_history poison bit

    Op/ret codes are model-supplied small ints (closed universes).
    Conversion to/from the live tester object is exact, so packed states
    fingerprint-distinguish histories exactly like object states do.
    """

    def __init__(
        self,
        builder: LayoutBuilder,
        thread_ids: Sequence[Any],
        max_ops: int,
        op_bits: int,
        ret_bits: int,
        real_time: bool = True,
    ):
        #: Whether invocations snapshot real-time prerequisites. True for
        #: LinearizabilityTester histories; False for
        #: SequentialConsistencyTester ones (sequential_consistency.rs
        #: records none) — the prereq fields then stay 0, so packed states
        #: collapse exactly like the host tester's equality does.
        self.real_time = real_time
        self.thread_ids = list(thread_ids)
        self.max_ops = max_ops
        self.op_bits = op_bits
        self.ret_bits = ret_bits
        T = len(self.thread_ids)
        self.peers = {
            t: [p for p in range(T) if p != t] for t in range(T)
        }
        pre_bits = max((max_ops + 2).bit_length(), 2)
        self.pre_bits = pre_bits
        builder.flag("h_valid")
        for t in range(T):
            builder.uint(f"h{t}_n", max(max_ops.bit_length(), 1))
            builder.uint(f"h{t}_fl", op_bits + 1)
            builder.array(f"h{t}_flpre", max(T - 1, 1), pre_bits)
            builder.array(f"h{t}_op", max_ops, op_bits + 1)
            builder.array(f"h{t}_ret", max_ops, ret_bits + 1)
            builder.array(f"h{t}_pre", max(max_ops * (T - 1), 1), pre_bits)
        self.layout: Optional[Layout] = None

    def bind(self, layout: Layout) -> "BoundedHistory":
        self.layout = layout
        return self

    # --- device ops --------------------------------------------------------

    def init_words(self, words):
        """Mark the empty history valid."""
        return self.layout.set(words, "h_valid", 1)

    def on_invoke(self, words, t: int, op_code, enabled=True):
        """Record an invocation on (static) thread ``t``: op in flight +
        real-time prereqs snapshot (linearizability.rs:114-126).

        An invoke while another op is in flight is a *protocol* violation:
        the tester poisons ``is_valid_history`` (consistency_tester
        HistoryError semantics) and so does this — ``h_valid`` is cleared,
        matching how ``record_invocations`` swallows the HistoryError but
        keeps the poisoned tester."""
        import jax.numpy as jnp

        enabled = jnp.asarray(enabled)
        L = self.layout
        # A poisoned history is frozen: the tester raises HistoryError on
        # every later call and record_* leave it unchanged.
        valid = L.get(words, "h_valid")
        enabled = enabled & (valid != 0)
        cur = L.get(words, f"h{t}_fl")
        misuse = enabled & (cur != 0)
        words = L.set(
            words, "h_valid", jnp.where(misuse, jnp.uint32(0), valid)
        )
        do = enabled & ~misuse
        new = jnp.where(do, _u32(op_code) + jnp.uint32(1), cur)
        words = L.set(words, f"h{t}_fl", new)
        if self.real_time:
            for pi, p in enumerate(self.peers[t]):
                pn = L.get(words, f"h{p}_n")
                # Tester semantics: peers with no completed ops are absent.
                pre = jnp.where(pn > 0, pn + jnp.uint32(1), jnp.uint32(0))  # (n-1)+2
                cur = L.get(words, f"h{t}_flpre", pi)
                words = L.set(words, f"h{t}_flpre", jnp.where(do, pre, cur), pi)
        return words

    def on_return(self, words, t: int, ret_code, enabled=True):
        """Record a return on thread ``t``: moves the in-flight op (with its
        prereqs) into the completed list. Returns ``(words', overflow)``.

        ``overflow`` is True when the completed list is full (the static
        ``max_ops`` bound is too small for a reachable history) — models
        must route it into ``packed_step``'s overflow output so the engine
        fails loudly instead of silently truncating the history. A return
        with no in-flight op is a protocol violation and poisons
        ``h_valid`` like the tester does."""
        import jax.numpy as jnp

        enabled = jnp.asarray(enabled)
        L = self.layout
        # Frozen once poisoned (see on_invoke).
        valid = L.get(words, "h_valid")
        enabled = enabled & (valid != 0)
        n = L.get(words, f"h{t}_n").astype(jnp.int32)
        fl = L.get(words, f"h{t}_fl")
        slot = jnp.minimum(n, self.max_ops - 1)
        misuse = enabled & (fl == 0)
        overflow = enabled & (fl != 0) & (n >= self.max_ops)
        words = L.set(words, "h_valid", jnp.where(misuse, jnp.uint32(0), valid))
        do = enabled & (fl != 0) & (n < self.max_ops)
        cur_op = L.get(words, f"h{t}_op", slot)
        words = L.set(words, f"h{t}_op", jnp.where(do, fl, cur_op), slot)
        cur_ret = L.get(words, f"h{t}_ret", slot)
        words = L.set(
            words, f"h{t}_ret", jnp.where(do, _u32(ret_code) + jnp.uint32(1), cur_ret), slot
        )
        npeer = max(len(self.peers[t]), 1)
        for pi, _ in enumerate(self.peers[t]):
            pre = L.get(words, f"h{t}_flpre", pi)
            idx = slot * npeer + pi
            cur = L.get(words, f"h{t}_pre", idx)
            words = L.set(words, f"h{t}_pre", jnp.where(do, pre, cur), idx)
            words = L.set(words, f"h{t}_flpre", jnp.where(do, jnp.uint32(0), pre), pi)
        words = L.set(words, f"h{t}_fl", jnp.where(do, jnp.uint32(0), fl))
        words = L.set(
            words,
            f"h{t}_n",
            jnp.where(do, (n + 1).astype(jnp.uint32), n.astype(jnp.uint32)),
        )
        return words, overflow

    def valid_with_no_return_geq(self, words, min_ret_code: int):
        """Device predicate: the history is unpoisoned AND no completed op
        returned a code ``>= min_ret_code``.

        This is the building block for conservative consistency predicates
        over register-style histories (``history_codecs`` assigns WriteOk
        code 0 and ReadOk codes ``>= 1``): with ``min_ret_code=1`` it reads
        "valid and no completed read", which is exact-in-one-direction for
        linearizability — completed-write-only histories always admit a
        legal serialization, so only flagged states need the host's exact
        backtracking serializer (SURVEY §7 M4a). Kept here so the +1
        slot-storage offset stays private to this class."""
        import jax.numpy as jnp

        L = self.layout
        ok = L.get(words, "h_valid") != 0
        threshold = jnp.uint32(min_ret_code + 1)  # slots store code+1; 0 = empty
        for t in range(len(self.thread_ids)):
            for j in range(self.max_ops):
                ok = ok & (L.get(words, f"h{t}_ret", j) < threshold)
        return ok

    # --- host codec --------------------------------------------------------

    def from_tester(self, tester, op_code, ret_code) -> Dict[str, Any]:
        """Field values for :meth:`Layout.pack` from a live tester.
        ``op_code``/``ret_code`` map op/ret objects to closed-universe ints."""
        T = len(self.thread_ids)
        values: Dict[str, Any] = {"h_valid": 1 if tester.is_valid_history else 0}
        for t in range(T):
            tid = self.thread_ids[t]
            completed = tester.history_by_thread.get(tid, [])
            if len(completed) > self.max_ops:
                raise OverflowError32(
                    f"thread {tid!r}: {len(completed)} completed ops > {self.max_ops}"
                )
            values[f"h{t}_n"] = len(completed)
            ops, rets, pres = [0] * self.max_ops, [0] * self.max_ops, [0] * max(
                self.max_ops * (T - 1), 1
            )
            for j, (prereqs, op, ret) in enumerate(completed):
                ops[j] = op_code(op) + 1
                rets[j] = ret_code(ret) + 1
                for pi, p in enumerate(self.peers[t]):
                    pid = self.thread_ids[p]
                    if pid in prereqs:
                        pres[j * max(T - 1, 1) + pi] = prereqs[pid] + 2
            values[f"h{t}_op"] = ops
            values[f"h{t}_ret"] = rets
            values[f"h{t}_pre"] = pres
            flpre = [0] * max(T - 1, 1)
            if tid in tester.in_flight_by_thread:
                prereqs, op = tester.in_flight_by_thread[tid]
                values[f"h{t}_fl"] = op_code(op) + 1
                for pi, p in enumerate(self.peers[t]):
                    pid = self.thread_ids[p]
                    if pid in prereqs:
                        flpre[pi] = prereqs[pid] + 2
            else:
                values[f"h{t}_fl"] = 0
            values[f"h{t}_flpre"] = flpre
        return values

    def to_tester(self, fields: Dict[str, Any], make_tester, code_op, code_ret):
        """Rebuild the tester from :meth:`Layout.unpack` output.
        ``make_tester()`` builds an empty tester; ``code_op``/``code_ret``
        invert the code maps."""
        tester = make_tester()
        tester.is_valid_history = bool(fields["h_valid"])
        T = len(self.thread_ids)
        for t in range(T):
            tid = self.thread_ids[t]
            n = fields[f"h{t}_n"]
            if n > 0 or fields[f"h{t}_fl"] != 0:
                tester.history_by_thread.setdefault(tid, [])
            for j in range(n):
                prereqs = {}
                for pi, p in enumerate(self.peers[t]):
                    raw = fields[f"h{t}_pre"][j * max(T - 1, 1) + pi]
                    if raw:
                        prereqs[self.thread_ids[p]] = raw - 2
                tester.history_by_thread[tid].append(
                    (
                        prereqs,
                        code_op(fields[f"h{t}_op"][j] - 1),
                        code_ret(fields[f"h{t}_ret"][j] - 1),
                    )
                )
            fl = fields[f"h{t}_fl"]
            if fl:
                prereqs = {}
                for pi, p in enumerate(self.peers[t]):
                    raw = fields[f"h{t}_flpre"][pi]
                    if raw:
                        prereqs[self.thread_ids[p]] = raw - 2
                tester.in_flight_by_thread[tid] = (prereqs, code_op(fl - 1))
        return tester
