"""Pallas TPU kernel for the hash-set probe/insert loop.

The default insert (``ops/hashset.py``) is pure XLA: each probe round elects
slot winners with a commutative scatter-min over an O(capacity) claim
buffer.  That is bandwidth-proportional to the *table*, which is the right
trade for huge frontier batches but wasteful for small ones (init seeding,
demand-driven expansion, shallow levels): a 2^24-slot table pays ~64 MB of
claim traffic per probe round regardless of batch size.

This kernel is the batch-proportional alternative: one sequential pass over
the batch with **in-place** table updates (``input_output_aliases``), each
element probing with dynamic size-1 slices.  Sequential execution makes
election trivial — earlier batch elements simply win, preserving the
default insert's lowest-index-wins determinism — and no O(capacity)
temporary exists at all.  The cost model is scalar probing (VPU scalar path
+ HBM latency), so it wins when ``batch << capacity`` and loses when the
batch is huge; ``insert_auto`` picks per call site.

Correctness is covered by differential tests against ``hashset.insert``
(CPU interpret mode; the driver's TPU bench exercises the compiled path).
Results are bit-identical whenever no lane overflows; under overflow the
two engines may fail *different* elements (parallel election vs. sequential
fill) — immaterial because every caller discards results and grows the
table on any overflow.
"""

from __future__ import annotations

from typing import Tuple

from .hashset import HashSet


def _available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except Exception:  # pragma: no cover
        return False


def insert_pallas(
    hs: HashSet,
    fp_hi,
    fp_lo,
    val_hi,
    val_lo,
    active,
    *,
    max_probes: int = 32,
    interpret: bool | None = None,
) -> Tuple[HashSet, "jax.Array", "jax.Array"]:
    """Drop-in replacement for ``hashset.insert`` (same contract: returns
    ``(hs', is_new, overflow)``; lowest batch index wins among in-batch
    duplicates)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if interpret is None:
        # Compiled lowering is only attempted on TPU; every other backend
        # (cpu, gpu) runs the interpreter — the kernel's scalar dynamic
        # indexing and ANY-space refs are Mosaic-oriented shapes.
        interpret = jax.default_backend() != "tpu"

    cap = hs.capacity
    m = fp_hi.shape[0]

    def kernel(
        fp_hi_ref,
        fp_lo_ref,
        val_hi_ref,
        val_lo_ref,
        active_ref,
        kh_in,
        kl_in,
        vh_in,
        vl_in,
        kh,
        kl,
        vh,
        vl,
        is_new_ref,
        ovf_ref,
    ):
        del kh_in, kl_in, vh_in, vl_in  # aliased to kh/kl/vh/vl outputs

        def body(i, _):
            f_hi = fp_hi_ref[i]
            f_lo = fp_lo_ref[i]
            is_active = active_ref[i]
            slot0 = (f_hi ^ (f_lo * jnp.uint32(0x9E3779B1))) & jnp.uint32(cap - 1)

            def probe(carry):
                slot, j, done, new, of = carry
                k_hi = kh[slot]
                k_lo = kl[slot]
                occupied = (k_hi != 0) | (k_lo != 0)
                match = occupied & (k_hi == f_hi) & (k_lo == f_lo)
                claim = ~occupied
                done2 = match | claim
                new2 = claim
                slot2 = jnp.where(
                    done2, slot, (slot + jnp.uint32(1)) & jnp.uint32(cap - 1)
                )
                return slot2, j + 1, done2, new2, of

            def probe_cond(carry):
                _slot, j, done, _new, _of = carry
                return ~done & (j < max_probes)

            slot, j, done, new, _ = jax.lax.while_loop(
                probe_cond,
                probe,
                (slot0, jnp.int32(0), ~is_active, jnp.bool_(False), jnp.bool_(False)),
            )

            @pl.when(is_active & new)
            def _():
                kh[slot] = f_hi
                kl[slot] = f_lo
                vh[slot] = val_hi_ref[i]
                vl[slot] = val_lo_ref[i]

            is_new_ref[i] = is_active & new
            ovf_ref[i] = is_active & ~done
            return 0

        jax.lax.fori_loop(0, m, body, 0)

    out_shapes = (
        jax.ShapeDtypeStruct((cap,), jnp.uint32),  # kh
        jax.ShapeDtypeStruct((cap,), jnp.uint32),  # kl
        jax.ShapeDtypeStruct((cap,), jnp.uint32),  # vh
        jax.ShapeDtypeStruct((cap,), jnp.uint32),  # vl
        jax.ShapeDtypeStruct((m,), jnp.bool_),  # is_new
        jax.ShapeDtypeStruct((m,), jnp.bool_),  # overflow
    )
    spec = pl.BlockSpec(memory_space=pl.ANY) if not interpret else pl.BlockSpec()
    specs = [pl.BlockSpec()] * 5 + [spec] * 4

    kh, kl, vh, vl, is_new, ovf = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        in_specs=specs,
        out_specs=(spec, spec, spec, spec, pl.BlockSpec(), pl.BlockSpec()),
        # Table planes update in place: inputs 5..8 alias outputs 0..3.
        input_output_aliases={5: 0, 6: 1, 7: 2, 8: 3},
        interpret=interpret,
    )(fp_hi, fp_lo, val_hi, val_lo, active, *hs)
    return HashSet(kh, kl, vh, vl), is_new, ovf


def insert_auto(hs, fp_hi, fp_lo, val_hi, val_lo, active, *, max_probes: int = 32):
    """Batch-size dispatch: the sequential Pallas kernel when the batch is
    tiny relative to the table (claim traffic would dominate), the XLA
    scatter-election insert otherwise.

    On TPU the *compiled* kernel is opt-in (``STATERIGHT_TPU_PALLAS=1``)
    until its Mosaic lowering is validated on hardware; any lowering failure
    falls back to the XLA insert, so callers never see the difference.
    """
    import os

    import jax

    from . import hashset

    m = fp_hi.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    enabled = not on_tpu or os.environ.get("STATERIGHT_TPU_PALLAS") == "1"
    if _available() and enabled and m * 64 < hs.capacity:
        try:
            return insert_pallas(
                hs, fp_hi, fp_lo, val_hi, val_lo, active, max_probes=max_probes
            )
        except Exception as e:  # pragma: no cover - TPU lowering gaps
            if not _is_lowering_failure(e):
                raise  # genuine bugs (shapes, OOM, tracer leaks) propagate
            global _warned_fallback
            if not _warned_fallback:
                _warned_fallback = True
                import warnings

                warnings.warn(
                    f"Pallas hash-insert failed to lower; falling back to the "
                    f"XLA insert for this process: {type(e).__name__}: {e}",
                    RuntimeWarning,
                    stacklevel=2,
                )
    return hashset.insert(
        hs, fp_hi, fp_lo, val_hi, val_lo, active, max_probes=max_probes
    )


_warned_fallback = False


def _is_lowering_failure(e: Exception) -> bool:
    """Whether ``e`` is a failed Mosaic/Pallas *lowering* (fall back to the
    XLA insert) as opposed to a genuine bug — shape mismatches, OOM, tracer
    leaks — which must propagate. Mosaic rejections can surface either as
    Python-level lowering exceptions or as an XLA runtime error whose
    message names Mosaic, so both are matched; other runtime errors (e.g.
    RESOURCE_EXHAUSTED) are not."""
    if isinstance(e, NotImplementedError):
        return True
    name = type(e).__name__
    if name in ("LoweringError", "LoweringException"):
        return True
    if name in ("XlaRuntimeError", "JaxRuntimeError") and (
        "Mosaic" in str(e) or "mosaic" in str(e)
    ):
        return True
    return False
