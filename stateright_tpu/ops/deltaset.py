"""Two-tier sort-merge visited set: sorted MAIN array + sorted DELTA.

The flat sorted set (``ops/sortedset.py``) pays one ``lax.sort`` of
``[capacity + batch]`` per level — at soak scale (2pc rm=10: a 2^27-row
table) that term dominates every level regardless of how small the level
is.  This structure bounds the per-level sort to the DELTA tier, LSM-style:

- **membership** against the main tier is a branchless binary-search
  descent (log2(C) rounds of gathers; candidates are probed in sorted
  order, so the access pattern is ascending — the high-locality gather
  case of ``tools/layout_probe.py``),
- **in-batch dedup + winner election + delta merge** is one sort of
  ``[delta_capacity + batch]`` (the sortedset pipeline, small tier only),
- when the merged delta would overflow, the same compiled program
  **flushes**: one sort of ``[C + Dcap + batch]`` folds the delta and the
  batch winners into main and empties the delta.  ``lax.cond`` picks the
  path on device, so flushes cost no host round-trip and no retry.

Amortization: the big sort runs once per ~(Dcap / level-batch) levels
instead of every level.  Same insert contract as the other structures
(is_new in batch order, lowest-index winner, parent values stored);
differential tests pin equality against them.

External layout contract: ``key_hi/key_lo/val_hi/val_lo`` expose the
CONCATENATED [main ‖ delta] planes (occupied rows non-(0,0), pads zero),
so the checkpoint codec and the native ParentMap consume this structure
unchanged.  (0xFFFFFFFF, 0xFFFFFFFF) is reserved exactly as in the flat
sorted set.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np


class DeltaSet(NamedTuple):
    """Tier planes: ``main_*`` rows ``[:n_main]`` sorted ascending by
    (hi, lo) and unique; ``delta_*`` rows ``[:n_delta]`` likewise; the two
    tiers are disjoint. Pads are (0, 0)."""

    main_key_hi: "jax.Array"  # [C] uint32
    main_key_lo: "jax.Array"
    main_val_hi: "jax.Array"
    main_val_lo: "jax.Array"
    delta_key_hi: "jax.Array"  # [Dc] uint32
    delta_key_lo: "jax.Array"
    delta_val_hi: "jax.Array"
    delta_val_lo: "jax.Array"
    n_main: "jax.Array"  # [] int32
    n_delta: "jax.Array"  # [] int32

    @property
    def capacity(self) -> int:
        """Total row slots (the growth policy's denominator)."""
        return self.main_key_hi.shape[0] + self.delta_key_hi.shape[0]

    @property
    def main_capacity(self) -> int:
        return self.main_key_hi.shape[0]

    @property
    def delta_capacity(self) -> int:
        return self.delta_key_hi.shape[0]

    # --- external layout contract (checkpoint / ParentMap) ---------------

    @property
    def key_hi(self):
        import jax.numpy as jnp

        return jnp.concatenate([self.main_key_hi, self.delta_key_hi])

    @property
    def key_lo(self):
        import jax.numpy as jnp

        return jnp.concatenate([self.main_key_lo, self.delta_key_lo])

    @property
    def val_hi(self):
        import jax.numpy as jnp

        return jnp.concatenate([self.main_val_hi, self.delta_val_hi])

    @property
    def val_lo(self):
        import jax.numpy as jnp

        return jnp.concatenate([self.main_val_lo, self.delta_val_lo])


#: Delta-tier rows as a fraction of main capacity (1/2**DELTA_SHIFT).
DELTA_SHIFT = 4
#: Floor on delta-tier rows. Module-level so tests/soaks can shrink it to
#: force the flush path on tiny state spaces (trace-time constant).
MIN_DELTA = 1024


def _delta_cap(capacity: int) -> int:
    return max(capacity >> DELTA_SHIFT, MIN_DELTA)


def make(capacity: int, xp) -> DeltaSet:
    """Empty set. ``capacity`` counts MAIN rows (power of two); the delta
    tier adds capacity/2**DELTA_SHIFT rows on top."""
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    dc = _delta_cap(capacity)
    zc = xp.zeros((capacity,), dtype=xp.uint32)
    zd = xp.zeros((dc,), dtype=xp.uint32)
    zero = xp.asarray(0, dtype=xp.int32)
    return DeltaSet(zc, zc, zc, zc, zd, zd, zd, zd, zero, zero)


def from_entries(key_hi, key_lo, val_hi, val_lo, capacity: int, xp) -> DeltaSet:
    """Host-side bulk build (checkpoint restore): everything lands sorted
    in the main tier; the delta starts empty."""
    key_hi = np.asarray(key_hi, np.uint32)
    key_lo = np.asarray(key_lo, np.uint32)
    val_hi = np.asarray(val_hi, np.uint32)
    val_lo = np.asarray(val_lo, np.uint32)
    n = len(key_hi)
    if capacity < n or capacity & (capacity - 1):
        raise ValueError(f"capacity {capacity} cannot hold {n} entries")
    order = np.lexsort((key_lo, key_hi))
    planes = []
    for a in (key_hi[order], key_lo[order], val_hi[order], val_lo[order]):
        out = np.zeros(capacity, np.uint32)
        out[:n] = a
        planes.append(xp.asarray(out))
    dc = _delta_cap(capacity)
    zd = xp.zeros((dc,), dtype=xp.uint32)
    return DeltaSet(
        *planes, zd, zd, zd, zd,
        xp.asarray(n, dtype=xp.int32), xp.asarray(0, dtype=xp.int32),
    )


def _bsearch_member(key_hi, key_lo, n, q_hi, q_lo):
    """Branchless lower-bound membership of (q_hi, q_lo) batches in the
    sorted prefix ``[:n]`` of the key planes."""
    import jax.numpy as jnp

    cap = key_hi.shape[0]
    off = jnp.zeros(q_hi.shape, jnp.int32)
    step = cap
    while step > 1:
        step //= 2
        mid = off + step
        kh = key_hi[mid - 1]
        kl = key_lo[mid - 1]
        less = (kh < q_hi) | ((kh == q_hi) & (kl < q_lo))
        off = jnp.where((mid <= n) & less, mid, off)
    at = jnp.minimum(off, cap - 1)
    return (off < n) & (key_hi[at] == q_hi) & (key_lo[at] == q_lo), at


def insert(
    ds: DeltaSet,
    fp_hi,
    fp_lo,
    val_hi,
    val_lo,
    active,
    *,
    max_probes: int = 0,  # signature compatibility; unused
) -> Tuple[DeltaSet, "jax.Array", "jax.Array"]:
    """Same contract as ``sortedset.insert``: ``is_new`` in original batch
    order (lowest-index winner among in-batch duplicates of keys in
    neither tier); winners' values stored; ``overflow`` True only when even
    a flush cannot fit the merged set in main (the caller grows and
    retries; the returned set is then invalid)."""
    import jax
    import jax.numpy as jnp

    C = ds.main_capacity
    Dc = ds.delta_capacity
    m = fp_hi.shape[0]
    full = jnp.uint32(0xFFFFFFFF)

    # --- shared prologue: candidate sort + membership + winner election --
    # Values reach sorted-batch order either as payload operands of the
    # prologue sort or by two random [m]-lane gathers afterwards — the
    # same trade ``sortedset`` resolves per backend (the round-5 chip
    # A/B: random gathers at scale lose to payload-through-sort on TPU,
    # win on 1-core CPU). Results are bit-identical. The u64 key-packing
    # knob (STPU_SORTEDSET_KEYS=packed) is honored here too — silently
    # falling back would record a pair-lowering soak as a packed
    # measurement.
    from .sortedset import _pack64, _unpack64, _via_packed, _via_sort

    kh = jnp.where(active, fp_hi, full)
    kl = jnp.where(active, fp_lo, full)
    ticket = jnp.arange(m, dtype=jnp.int32)
    via_packed = _via_packed()
    if via_packed:
        k64 = _pack64(kh, kl, jnp)
        sk64, st, sv64 = jax.lax.sort(
            (k64, ticket, _pack64(val_hi, val_lo, jnp)), num_keys=2
        )
        skh, skl = _unpack64(sk64, jnp)
        vh, vl = _unpack64(sv64, jnp)
    elif _via_sort():
        skh, skl, st, vh, vl = jax.lax.sort(
            (kh, kl, ticket, val_hi, val_lo), num_keys=3
        )
    else:
        skh, skl, st = jax.lax.sort((kh, kl, ticket), num_keys=3)
        # Winner values, aligned with the sorted batch.
        vh = val_hi[st]
        vl = val_lo[st]
    run_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), (skh[1:] != skh[:-1]) | (skl[1:] != skl[:-1])]
    )
    real = ~((skh == full) & (skl == full))
    # Membership probes run on the SORTED batch: ascending access pattern.
    in_main, _ = _bsearch_member(ds.main_key_hi, ds.main_key_lo, ds.n_main, skh, skl)
    in_delta, _ = _bsearch_member(
        ds.delta_key_hi, ds.delta_key_lo, ds.n_delta, skh, skl
    )
    winner = run_start & real & ~in_main & ~in_delta
    n_win = jnp.sum(winner, dtype=jnp.int32)

    # is_new back to batch order: inverse permutation by one sort.
    _, winner_in_order = jax.lax.sort((st, winner.astype(jnp.int32)), num_keys=1)
    is_new = winner_in_order.astype(jnp.bool_)

    new_total_delta = ds.n_delta + n_win
    # Delta-full reports as the structure's overflow: the CALLER runs the
    # flush (``maintain``) as its own host-invoked program and retries the
    # level through the engine's standard overflow protocol — exactly the
    # grow-and-retry shape. The flush was originally a ``lax.cond`` branch
    # inside this program, but a conditional carrying a main-capacity sort
    # reproducibly FAULTS the XLA:TPU runtime ("TPU worker crashed —
    # kernel fault", observed at both 2^22 and 2^27 main tiers, round 5),
    # and host-side branching costs one retried level per ~(Dc / batch)
    # levels — noise against the amortization it buys. The returned set is
    # truncated on overflow and must be discarded, like sortedset's.
    overflow = new_total_delta > Dc

    # Merge winners into the delta tier: one sort of [Dc + m].
    dk_valid = jnp.arange(Dc) < ds.n_delta
    if via_packed:
        dk64 = jnp.concatenate(
            [jnp.where(dk_valid, _pack64(ds.delta_key_hi, ds.delta_key_lo, jnp),
                       jnp.uint64(0xFFFFFFFFFFFFFFFF)),
             jnp.where(winner, _pack64(skh, skl, jnp), jnp.uint64(0xFFFFFFFFFFFFFFFF))]
        )
        dv64 = jnp.concatenate(
            [_pack64(ds.delta_val_hi, ds.delta_val_lo, jnp),
             jnp.where(winner, _pack64(vh, vl, jnp), jnp.uint64(0))]
        )
        mk64, mv64 = jax.lax.sort((dk64, dv64), num_keys=1)
        mkh, mkl = _unpack64(mk64, jnp)
        mvh, mvl = _unpack64(mv64, jnp)
    else:
        dkh = jnp.concatenate(
            [jnp.where(dk_valid, ds.delta_key_hi, full),
             jnp.where(winner, skh, full)]
        )
        dkl = jnp.concatenate(
            [jnp.where(dk_valid, ds.delta_key_lo, full),
             jnp.where(winner, skl, full)]
        )
        dvh = jnp.concatenate([ds.delta_val_hi, jnp.where(winner, vh, 0)])
        dvl = jnp.concatenate([ds.delta_val_lo, jnp.where(winner, vl, 0)])
        mkh, mkl, mvh, mvl = jax.lax.sort((dkh, dkl, dvh, dvl), num_keys=2)
    row_ok = jnp.arange(Dc) < jnp.minimum(new_total_delta, Dc)
    z = jnp.uint32(0)
    out = DeltaSet(
        ds.main_key_hi, ds.main_key_lo, ds.main_val_hi, ds.main_val_lo,
        jnp.where(row_ok, mkh[:Dc], z),
        jnp.where(row_ok, mkl[:Dc], z),
        jnp.where(row_ok, mvh[:Dc], z),
        jnp.where(row_ok, mvl[:Dc], z),
        ds.n_main,
        jnp.minimum(new_total_delta, Dc),
    )
    return out, is_new, overflow


def maintain(ds: DeltaSet) -> Tuple[DeltaSet, "jax.Array"]:
    """Fold the delta tier into main: one sort of [C + Dc], delta empties.
    The flush half of the LSM design, as a standalone jittable program
    (see the overflow note in :func:`insert` for why it is NOT a
    ``lax.cond`` branch inside the insert). Returns ``(ds', overflow)``;
    overflow means the merged set does not fit main — the caller grows
    (``grow`` folds the delta anyway) and discards ``ds'``."""
    import jax
    import jax.numpy as jnp

    from .sortedset import _pack64, _unpack64, _via_packed

    C = ds.main_capacity
    Dc = ds.delta_capacity
    full = jnp.uint32(0xFFFFFFFF)
    mk_valid = jnp.arange(C) < ds.n_main
    dk_valid = jnp.arange(Dc) < ds.n_delta
    if _via_packed():
        full64 = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        ak64 = jnp.concatenate(
            [jnp.where(mk_valid, _pack64(ds.main_key_hi, ds.main_key_lo, jnp),
                       full64),
             jnp.where(dk_valid, _pack64(ds.delta_key_hi, ds.delta_key_lo, jnp),
                       full64)]
        )
        av64 = jnp.concatenate(
            [_pack64(ds.main_val_hi, ds.main_val_lo, jnp),
             _pack64(ds.delta_val_hi, ds.delta_val_lo, jnp)]
        )
        mk64, mv64 = jax.lax.sort((ak64, av64), num_keys=1)
        mkh, mkl = _unpack64(mk64, jnp)
        mvh, mvl = _unpack64(mv64, jnp)
    else:
        akh = jnp.concatenate(
            [jnp.where(mk_valid, ds.main_key_hi, full),
             jnp.where(dk_valid, ds.delta_key_hi, full)]
        )
        akl = jnp.concatenate(
            [jnp.where(mk_valid, ds.main_key_lo, full),
             jnp.where(dk_valid, ds.delta_key_lo, full)]
        )
        avh = jnp.concatenate([ds.main_val_hi, ds.delta_val_hi])
        avl = jnp.concatenate([ds.main_val_lo, ds.delta_val_lo])
        mkh, mkl, mvh, mvl = jax.lax.sort((akh, akl, avh, avl), num_keys=2)
    n_new_main = ds.n_main + ds.n_delta
    overflow = n_new_main > C
    row_ok = jnp.arange(C) < jnp.minimum(n_new_main, C)
    z = jnp.uint32(0)
    zd = jnp.zeros((Dc,), jnp.uint32)
    out = DeltaSet(
        jnp.where(row_ok, mkh[:C], z),
        jnp.where(row_ok, mkl[:C], z),
        jnp.where(row_ok, mvh[:C], z),
        jnp.where(row_ok, mvl[:C], z),
        zd, zd, zd, zd,
        jnp.minimum(n_new_main, C),
        jnp.asarray(0, jnp.int32),
    )
    return out, overflow


_maintain_jitted = None


def maintain_jit(ds: DeltaSet) -> Tuple[DeltaSet, "jax.Array"]:
    """``maintain`` under a module-cached ``jax.jit`` (a fresh ``jax.jit``
    wrapper per call would recompile the flush every flush)."""
    global _maintain_jitted
    if _maintain_jitted is None:
        import jax

        _maintain_jitted = jax.jit(maintain)
    return _maintain_jitted(ds)


def insert_lane_words(ds: DeltaSet, m: int) -> int:
    """32-bit words carried as ``lax.sort`` operands by one :func:`insert`
    with an ``m``-lane batch (the cost-law telemetry; see
    ``sortedset.insert_lane_words``). The table-scale flush
    (:func:`maintain`) is host-invoked and amortized, so it is not a
    per-level term. Membership is bsearch gathers — no sorted lanes."""
    from .sortedset import _via_sort

    Dc = ds.delta_capacity
    # Prologue: 5-word sort (keys+ticket+values as payload, packed or
    # pair) or 3-word gather-family sort; inverse permutation 2 words;
    # delta merge 4 words (2 key + 2 value, packed or pair).
    prologue = m * (5 if _via_sort() else 3)
    return prologue + m * 2 + (Dc + m) * 4


def lookup(ds: DeltaSet, fp_hi, fp_lo, *, max_probes: int = 0):
    """Batched membership + value lookup across both tiers."""
    import jax.numpy as jnp

    hit_m, at_m = _bsearch_member(ds.main_key_hi, ds.main_key_lo, ds.n_main, fp_hi, fp_lo)
    hit_d, at_d = _bsearch_member(
        ds.delta_key_hi, ds.delta_key_lo, ds.n_delta, fp_hi, fp_lo
    )
    z = jnp.uint32(0)
    vh = jnp.where(
        hit_m, ds.main_val_hi[at_m], jnp.where(hit_d, ds.delta_val_hi[at_d], z)
    )
    vl = jnp.where(
        hit_m, ds.main_val_lo[at_m], jnp.where(hit_d, ds.delta_val_lo[at_d], z)
    )
    return hit_m | hit_d, vh, vl


def grow(ds: DeltaSet, new_capacity: int, xp) -> DeltaSet:
    """Grow the main tier (plane copy) and rescale the delta tier,
    folding any delta contents into main so tier invariants hold."""
    if new_capacity < ds.main_capacity:
        raise ValueError("delta set cannot shrink")
    # Host-side: materialize occupied rows of both tiers, rebuild. The
    # minimum delta tier (1024 rows) can out-hold a tiny main, so size the
    # new main for the actual occupancy, not just the caller's doubling.
    kh = np.asarray(ds.key_hi)
    kl = np.asarray(ds.key_lo)
    vh = np.asarray(ds.val_hi)
    vl = np.asarray(ds.val_lo)
    occ = (kh != 0) | (kl != 0)
    n = int(occ.sum())
    while new_capacity < 2 * n:
        new_capacity *= 2
    return from_entries(kh[occ], kl[occ], vh[occ], vl[occ], new_capacity, xp)
