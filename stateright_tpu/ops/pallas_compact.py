"""Pallas stream-compaction kernels: order-preserving compaction in O(n).

The engine's largest per-level op is the grid-compaction sort —
(W+1 operands) x (A*F lanes) of ``lax.sort``. Under the state-major
flatten (the "bsearch" layout) its only job is ORDER-PRESERVING stream
compaction of P uint32 lane arrays by a mask into a ``[P, cap]`` output.
A sort is O(n log^2 n) data passes; these kernels are O(n): TPU pallas
grids execute blocks SEQUENTIALLY on a core, so a running output offset
lives in SMEM scratch across grid steps and every HBM write is a
contiguous, B-aligned chunk DMA — no scatters
(docs/backend_pathologies.md #2/#5 never enter the picture).

Per block b of B lanes:
  1. local ranks: inclusive cumsum of the mask block,
  2. in-VMEM block compaction: output slot j pulls the lane holding the
     (j+1)-th set bit via a one-hot [B, B] contraction at
     ``Precision.HIGHEST`` — each output sums exactly ONE nonzero
     product of 16-bit-valued f32s, so the result is exact; the default
     bf16 MXU pass would silently truncate the u16 halves (8-bit
     mantissa), which is why the precision pin is load-bearing,
  3. survivors append into a [P, 2B] VMEM ring at the running offset;
     full B-aligned chunks DMA to the HBM output,
  4. the garbage tail of each chunk is overwritten by the next flush
     (sequential grid = no race); lanes at and past the total survivor
     count are UNSPECIFIED — callers re-mask (the engine's zero-pad
     contract is applied outside the kernel).

Inputs are SEPARATE 1-D lane refs (not one stacked [P, M] array): the
engine's lanes already exist as independent buffers, and a pre-kernel
``jnp.stack`` would cost a full extra read+write of the grid — against
the kernel's whole point.

``compact_pallas`` keeps the output VMEM-resident (probe/testing shape);
``compact_pallas_staged`` is the engine-scale variant. Equality against
the sort lowering is pinned by ``tests/test_pallas_compact.py`` and the
engine differential; whether it is FASTER on chip is the
``tools/pallas_compact.py`` A/B's question.
"""

from __future__ import annotations

from typing import Sequence


def _as_lanes(planes):
    """Accept either a [P, M] array (tools/tests convenience) or a
    sequence of [M] lanes (the engine's zero-copy form)."""
    if hasattr(planes, "ndim"):
        assert planes.ndim == 2
        return [planes[p] for p in range(planes.shape[0])]
    return list(planes)


def _block_compact(mask_ref, plane_refs, B: int):
    """Shared block body: local compaction of P lane blocks [B] by a [B]
    mask block via the one-hot contraction. Returns ``(compacted [P, B],
    n_b)`` — survivors dense at the front, tail unspecified."""
    import jax
    import jax.numpy as jnp

    P = len(plane_refs)
    m = mask_ref[:].astype(jnp.int32)
    # Inclusive prefix sum as a lower-triangular [B, B] contraction:
    # Mosaic has no cumsum lowering inside TC kernels (first-silicon
    # probe, 2026-08-02), and the MXU form is the TPU-native prefix sum
    # anyway. 0/1 operands with <=B-term f32 accumulation are exact at
    # HIGHEST (same argument as the payload gather below).
    ii = jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)
    tri = (ii >= jj).astype(jnp.float32)
    incl = jax.lax.dot_general(
        tri,
        m.astype(jnp.float32).reshape(B, 1),
        (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).reshape(B).astype(jnp.int32)
    n_b = jnp.sum(m)
    i_rank = jnp.where(m > 0, incl - 1, -1)
    sel = (ii == i_rank[None, :]).astype(jnp.float32)
    blk = jnp.stack([r[:] for r in plane_refs])  # [P, B], VMEM-local
    # Mosaic has no direct u32<->f32 cast; both halves are <= 0xFFFF so
    # the i32 hop is value-exact in each direction.
    lo16 = (blk & jnp.uint32(0xFFFF)).astype(jnp.int32).astype(jnp.float32)
    hi16 = (blk >> jnp.uint32(16)).astype(jnp.int32).astype(jnp.float32)
    gathered = jax.lax.dot_general(
        sel,
        jnp.concatenate([lo16, hi16], axis=0).T,
        (((1,), (0,)), ((), ())),
        # Exactness pin — see the module docstring. DEFAULT would run a
        # single bf16 pass and truncate the 16-bit payload halves.
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    compacted = gathered[:, :P].T.astype(jnp.int32).astype(jnp.uint32) | (
        gathered[:, P:].T.astype(jnp.int32).astype(jnp.uint32) << jnp.uint32(16)
    )
    return compacted, n_b


def compact_pallas(
    mask, planes, cap: int, *, block: int = 1024, interpret: bool = False
):
    """Order-preserving stream compaction of P uint32 lanes [M] by
    ``mask`` [M] into [P, cap], output VMEM-resident (small caps only).
    Lanes at index >= sum(mask) are UNSPECIFIED. M and cap must be
    multiples of ``block``."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lanes = _as_lanes(planes)
    P = len(lanes)
    M = lanes[0].shape[0]
    assert mask.shape == (M,)
    assert M % block == 0 and cap % block == 0, (M, cap, block)

    def kernel(mask_ref, *rest):
        plane_refs, out_ref, off_ref = rest[:P], rest[P], rest[P + 1]
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _init():
            off_ref[0] = 0

        compacted, n_b = _block_compact(mask_ref, plane_refs, block)
        off = off_ref[0]

        @pl.when(off + block <= cap)
        def _store():
            out_ref[:, pl.ds(off, block)] = compacted

        off_ref[0] = off + n_b

    lane_spec = pl.BlockSpec((block,), lambda b: (b,))
    return pl.pallas_call(
        kernel,
        grid=(M // block,),
        in_specs=[lane_spec] * (1 + P),
        out_specs=pl.BlockSpec((P, cap), lambda b: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((P, cap), lanes[0].dtype),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(mask, *lanes)


def compact_pallas_staged(
    mask, planes, cap: int, *, block: int = 1024, interpret: bool = False
):
    """The engine-scale variant: output lives in HBM; survivors stream
    through a [P, 2B] VMEM ring and flush to the output in B-aligned
    chunk DMAs. SMEM carries (total appended, flushed chunks) across the
    sequential grid. Unspecified lanes as in :func:`compact_pallas`."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lanes = _as_lanes(planes)
    P = len(lanes)
    M = lanes[0].shape[0]
    assert mask.shape == (M,)
    assert M % block == 0 and cap % block == 0, (M, cap, block)
    B = block
    n_blocks = M // B

    def kernel(mask_ref, *rest):
        plane_refs = rest[:P]
        out_ref, stage, cnt, sem = rest[P], rest[P + 1], rest[P + 2], rest[P + 3]
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _init():
            cnt[0] = 0  # survivors appended
            cnt[1] = 0  # chunks flushed

        compacted, n_b = _block_compact(mask_ref, plane_refs, B)
        t, c = cnt[0], cnt[1]
        p = t - c * B  # append position within the ring, in [0, B)

        # Once flushing is frozen at the cap (survivor overflow — the
        # engine discards and retries the level), t keeps growing while
        # c does not; appending would then address past the 2B ring.
        # Mosaic documents OOB access as undefined behavior, so skip.
        @pl.when(p + B <= 2 * B)
        def _append():
            stage[:, pl.ds(p, B)] = compacted

        t = t + n_b
        cnt[0] = t

        def flush(chunk_idx):
            dma = pltpu.make_async_copy(
                stage.at[:, pl.ds(0, B)],
                out_ref.at[:, pl.ds(chunk_idx * B, B)],
                sem,
            )
            dma.start()
            dma.wait()

        @pl.when((t - c * B >= B) & ((c + 1) * B <= cap))
        def _flush_full():
            flush(c)
            # Slide the ring: the second half becomes the first.
            stage[:, pl.ds(0, B)] = stage[:, pl.ds(B, B)]
            cnt[1] = c + 1

        @pl.when(b == n_blocks - 1)
        def _flush_tail():
            c2 = cnt[1]

            @pl.when((cnt[0] > c2 * B) & ((c2 + 1) * B <= cap))
            def _():
                flush(c2)

    lane_spec = pl.BlockSpec((B,), lambda b: (b,))
    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[lane_spec] * (1 + P),
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((P, cap), lanes[0].dtype),
        scratch_shapes=[
            pltpu.VMEM((P, 2 * B), lanes[0].dtype),
            pltpu.SMEM((2,), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(mask, *lanes)
