"""Pallas stream-compaction kernels: order-preserving compaction in O(n).

The engine's largest per-level op is the grid-compaction sort —
(W+1 operands) x (A*F lanes) of ``lax.sort``. Under the state-major
flatten (the "bsearch" layout) its only job is ORDER-PRESERVING stream
compaction of P uint32 lane arrays by a mask into a ``[P, cap]`` output.
A sort is O(n log^2 n) data passes; these kernels are O(n): TPU pallas
grids execute blocks SEQUENTIALLY on a core, so a running output offset
lives in SMEM scratch across grid steps and every HBM write is a
contiguous, B-aligned chunk DMA — no scatters
(docs/backend_pathologies.md #2/#5 never enter the picture).

Mosaic constrains the design twice over (registry #6 and the r5e
first-silicon compile): there is no ``cumsum`` lowering inside TC
kernels, and a ``vector_store`` at a DYNAMIC lane offset must be
provably 128-aligned — so the obvious "compact to block front, store at
running offset p" shape does not compile. Both land on the same
TPU-native answer, the MXU one-hot contraction:

Per block b of B lanes (ring state: ``stage`` [P, 2B] VMEM, SMEM carry
``(t, c)`` = survivors appended / chunks flushed, ``p = t - c*B``):
  1. local ranks: inclusive prefix sum of the mask block as a
     lower-triangular [B, B] contraction (0/1 operands, f32
     accumulation at ``Precision.HIGHEST`` — exact at any plausible B),
  2. ring-targeted scatter-as-matmul: survivor s of the block belongs
     at ring position ``i_rank[s] + p``; ``sel[s, j] = (j == i_rank[s]
     + p)`` is a [B, 2B] one-hot, and ``(lanes as two f32 16-bit
     halves) @ sel`` lands every survivor in place in one MXU pass.
     Each output column sums at most ONE nonzero product of
     16-bit-valued f32s, so the result is exact; the default bf16 MXU
     pass would silently truncate the u16 halves (8-bit mantissa) —
     the precision pin is load-bearing,
  3. the ring updates as a full aligned read-modify-write:
     ``stage = where(hit, contrib, stage)`` with ``hit`` = sel's
     column-any — no dynamic-offset store exists in the program,
  4. full B-chunks DMA to the output at ``c*B`` (chunk-aligned by
     construction) and the ring slides by one static B; the garbage
     tail past the total survivor count is UNSPECIFIED — callers
     re-mask (the engine's zero-pad contract is applied outside).

Overflow (survivors past ``cap``) is drop-safe by construction: once
flushing freezes at the cap, ``p`` grows past 2B and every sel column
test fails — nothing is written, nothing is out of bounds.

Inputs are SEPARATE 1-D lane refs (not one stacked [P, M] array): the
engine's lanes already exist as independent buffers, and a pre-kernel
``jnp.stack`` would cost a full extra read+write of the grid — against
the kernel's whole point.

``compact_pallas_staged`` is the kernel (the former separate
VMEM-output ``compact_pallas`` died in the rework — its dynamic-offset
output store was the rejected shape). Equality against the sort
lowering is pinned by
``tests/test_pallas_compact.py`` and the engine differential; whether
it is FASTER on chip is the ``tools/pallas_compact.py`` A/B's question.
"""

from __future__ import annotations

from typing import Sequence


def _as_lanes(planes):
    """Accept either a [P, M] array (tools/tests convenience) or a
    sequence of [M] lanes (the engine's zero-copy form)."""
    if hasattr(planes, "ndim"):
        assert planes.ndim == 2
        return [planes[p] for p in range(planes.shape[0])]
    return list(planes)


def tri_inclusive(m_i32, B: int):
    """Inclusive prefix sum of a 0/1 [B] vector as the lower-triangular
    MXU contraction — Mosaic has no cumsum lowering inside TC kernels
    (registry #6). 0/1 operands with <= B-term f32 accumulation are
    exact at HIGHEST at any plausible block size."""
    import jax
    import jax.numpy as jnp

    ii = jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)
    tri = (ii >= jj).astype(jnp.float32)
    return (
        jax.lax.dot_general(
            tri,
            m_i32.astype(jnp.float32).reshape(B, 1),
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        .reshape(B)
        .astype(jnp.int32)
    )


def split16(u32, jnp):
    """u32 -> (lo16, hi16) as f32 via the i32 hop (no direct u32<->f32
    cast on Mosaic; both halves <= 0xFFFF are value-exact — registry
    #6). The exactness-critical half of the scatter-as-matmul trick:
    16-bit-valued f32s survive a HIGHEST-precision contraction exactly,
    where the default bf16 pass would truncate them."""
    lo = (u32 & jnp.uint32(0xFFFF)).astype(jnp.int32).astype(jnp.float32)
    hi = (u32 >> jnp.uint32(16)).astype(jnp.int32).astype(jnp.float32)
    return lo, hi


def fuse16(lo_f32, hi_f32, jnp):
    """Inverse of :func:`split16` after an exact contraction."""
    return lo_f32.astype(jnp.int32).astype(jnp.uint32) | (
        hi_f32.astype(jnp.int32).astype(jnp.uint32) << jnp.uint32(16)
    )


def ring_fold(stage, arrays, tgt, B: int):
    """Fold u32 source lanes into a [P, 2B] VMEM ring: lane s of every
    array lands at ring position ``tgt[s]`` (-1 or >= 2B = dropped —
    the flush-frozen overflow path is drop-safe by construction, no
    out-of-bounds access exists). The scatter-as-matmul core shared by
    pallas_compact and pallas_merge: a [S, 2B] one-hot contraction of
    the 16-bit halves at ``Precision.HIGHEST`` — each output column
    sums at most ONE nonzero product of 16-bit-valued f32s, so the
    result is exact; the default bf16 MXU pass would silently truncate
    the u16 halves (8-bit mantissa) — the precision pin is
    load-bearing. Mosaic has no direct u32<->f32 cast; the i32 hop is
    value-exact for the <= 0xFFFF halves (registry #6)."""
    import jax
    import jax.numpy as jnp

    P = len(arrays)
    S = tgt.shape[0]
    jr = jax.lax.broadcasted_iota(jnp.int32, (S, 2 * B), 1)
    sel = (jr == tgt.reshape(S, 1)).astype(jnp.float32)
    blk = jnp.stack(list(arrays))  # [P, S]
    lo16, hi16 = split16(blk, jnp)
    contrib = jax.lax.dot_general(
        jnp.concatenate([lo16, hi16], axis=0),  # [2P, S]
        sel,  # [S, 2B]
        (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # [2P, 2B]
    packed = fuse16(contrib[:P], contrib[P:], jnp)
    hit = jnp.sum(sel, axis=0, keepdims=True) > 0.5  # [1, 2B]
    stage[:, :] = jnp.where(hit, packed, stage[:, :])


def _ring_update(mask_ref, plane_refs, stage, p, B: int):
    """Block body: fold this block's mask-selected survivors into the
    ring at running offset ``p`` (compaction targets = local rank + p).
    Returns ``n_b``, the block's survivor count."""
    import jax
    import jax.numpy as jnp

    m = mask_ref[:].astype(jnp.int32)
    incl = tri_inclusive(m, B)
    # Block survivor total = the inclusive prefix sum's last element —
    # NOT jnp.sum(m): Mosaic has no integer-reduction lowering (the
    # stpu-lint STPU005 pre-flight catches the reduce_sum shape), and
    # the triangular contraction already computed the answer.
    n_b = incl[B - 1]
    tgt = jnp.where(m > 0, incl - 1 + p, -1)
    ring_fold(stage, [r[:] for r in plane_refs], tgt, B)
    return n_b


def compact_pallas_staged(
    mask, planes, cap: int, *, block: int = 512, interpret: bool = False
):
    """Order-preserving stream compaction of P uint32 lanes [M] by
    ``mask`` [M] into [P, cap] (HBM output): survivors stream through a
    [P, 2B] VMEM ring and flush to the output in B-aligned chunk DMAs.
    SMEM carries (total appended, flushed chunks) across the sequential
    grid. Lanes at index >= sum(mask) are UNSPECIFIED — callers mask.
    M and cap must be multiples of ``block``."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lanes = _as_lanes(planes)
    P = len(lanes)
    M = lanes[0].shape[0]
    assert mask.shape == (M,)
    assert M % block == 0 and cap % block == 0, (M, cap, block)
    B = block
    n_blocks = M // B

    def kernel(mask_ref, *rest):
        plane_refs = rest[:P]
        out_ref, stage, cnt, sem = rest[P], rest[P + 1], rest[P + 2], rest[P + 3]
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _init():
            cnt[0] = 0  # survivors appended
            cnt[1] = 0  # chunks flushed

        t, c = cnt[0], cnt[1]
        p = t - c * B  # append position within the ring, in [0, B)
        n_b = _ring_update(mask_ref, plane_refs, stage, p, B)
        t = t + n_b
        cnt[0] = t

        def flush(chunk_idx):
            dma = pltpu.make_async_copy(
                stage.at[:, pl.ds(0, B)],
                out_ref.at[:, pl.ds(chunk_idx * B, B)],
                sem,
            )
            dma.start()
            dma.wait()

        @pl.when((t - c * B >= B) & ((c + 1) * B <= cap))
        def _flush_full():
            flush(c)
            # Slide the ring: the second half becomes the first.
            stage[:, pl.ds(0, B)] = stage[:, pl.ds(B, B)]
            cnt[1] = c + 1

        @pl.when(b == n_blocks - 1)
        def _flush_tail():
            c2 = cnt[1]

            @pl.when((cnt[0] > c2 * B) & ((c2 + 1) * B <= cap))
            def _():
                flush(c2)

    lane_spec = pl.BlockSpec((B,), lambda b: (b,))
    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[lane_spec] * (1 + P),
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((P, cap), lanes[0].dtype),
        scratch_shapes=[
            pltpu.VMEM((P, 2 * B), lanes[0].dtype),
            pltpu.SMEM((2,), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(mask, *lanes)


