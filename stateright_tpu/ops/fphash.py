"""64-bit fingerprints of bit-packed states, as two uint32 lanes.

The reference fingerprints states with a fixed-key 64-bit hash
(``/root/reference/src/lib.rs:327-336``); stability across runs is part of
the contract because witness paths are reconstructed from fingerprints later.

TPUs have no native 64-bit integer path worth using for this, so the device
fingerprint is two independent 32-bit lanes in **Zobrist form** (the classic
state-hash structure in explicit-state model checkers): each word is mixed
with a position key through a murmur3 fmix32 finalizer (public-domain
constants), the per-word digests are XOR-folded across the width, and one
final fmix32 avalanches the fold.  Two reasons for this shape over a
sequential per-word chain:

- it vectorizes across the word axis (the chain forces ~8*W dependent scalar
  ops per lane on the VPU; the fold is elementwise work plus a log-free XOR
  reduction), and
- XLA:CPU's LLVM pipeline *hangs* (minutes to forever, superlinearly in W)
  optimizing kernels where a W-deep mul/shift chain is fused into wide
  consumers — observed on packed-Paxos supersteps at W=25, threshold ~W=10.

The same function runs under numpy on the host — ``stateright_tpu.xla`` uses
the host flavor during path reconstruction — and in C++
(``native/hostkit.cpp``), so three-way agreement is load-bearing and covered
by differential tests.

The pairs (0, 0) (the EMPTY sentinel of both visited-set layouts) and
(0xFFFFFFFF, 0xFFFFFFFF) (the sorted set's pad key, ops/sortedset.py) are
reserved and remapped.
"""

from __future__ import annotations

# fmix32 constants (murmur3 finalizer, public domain).
_C1 = 0x85EBCA6B
_C2 = 0xC2B2AE35
# Per-lane seeds; arbitrary fixed odd constants (stability is what matters).
_SEED_HI = 0x9E3779B9
_SEED_LO = 0x517CC1B7
_WORD_MIX_HI = 0x2545F491
_WORD_MIX_LO = 0x85157AF5


def _fmix32(h, xp):
    u = xp.uint32
    h = h ^ (h >> u(16))
    h = h * u(_C1)
    h = h ^ (h >> u(13))
    h = h * u(_C2)
    h = h ^ (h >> u(16))
    return h


def _overflow_ok(xp):
    """numpy warns on (intended, wrapping) uint32 overflow; jnp does not."""
    import contextlib

    import numpy as _np

    return contextlib.nullcontext() if xp is not _np else _np.errstate(over="ignore")


def _finalize(fold_hi, fold_lo, xp):
    """Seeded avalanche over the per-word fold, plus the reserved-pair
    remap: (0, 0) is the EMPTY sentinel of both visited-set layouts and
    (0xFFFFFFFF, 0xFFFFFFFF) the sorted set's in-sort pad key. One
    implementation — the contract is load-bearing and differentially
    tested against the C++ mirror."""
    u = xp.uint32
    hi = _fmix32(fold_hi ^ u(_SEED_HI), xp)
    lo = _fmix32(fold_lo ^ u(_SEED_LO), xp)
    is_empty = (hi == u(0)) & (lo == u(0))
    lo = xp.where(is_empty, u(1), lo)
    is_full = (hi == u(0xFFFFFFFF)) & (lo == u(0xFFFFFFFF))
    lo = xp.where(is_full, u(0xFFFFFFFE), lo)
    return hi, lo


def fingerprint_words(words, xp):
    """Fingerprint packed states: ``[..., W] uint32 -> ([...], [...])``
    (hi, lo) uint32 lanes.

    ``xp`` is the array namespace: ``numpy`` on host, ``jax.numpy`` under
    jit.  Both produce identical bits.
    """
    import numpy as _np

    with _overflow_ok(xp):
        u = xp.uint32
        w_count = words.shape[-1]
        idx = _np.arange(1, w_count + 1, dtype=_np.uint64)
        pos_hi = xp.asarray((0x9E3779B9 * idx & 0xFFFFFFFF).astype(_np.uint32))
        pos_lo = xp.asarray((0x61C88647 * idx & 0xFFFFFFFF).astype(_np.uint32))
        words = words.astype(xp.uint32)
        # Per-word position-keyed digests (elementwise over the width)...
        m_hi = _fmix32(words * u(_WORD_MIX_HI) + pos_hi, xp)
        m_lo = _fmix32(words * u(_WORD_MIX_LO) + pos_lo, xp)
        # ...XOR-folded (order-free, so swapping unequal positions still
        # changes the fold through the position keys)...
        fold_hi = m_hi[..., 0]
        fold_lo = m_lo[..., 0]
        for i in range(1, w_count):
            fold_hi = fold_hi ^ m_hi[..., i]
            fold_lo = fold_lo ^ m_lo[..., i]
        # ...then one avalanche + reserved-pair remap.
        return _finalize(fold_hi, fold_lo, xp)


def fingerprint_planes(planes, xp):
    """``fingerprint_words`` over plane-major state buffers: ``planes`` is a
    ``[W, ...]`` array (or a W-sequence of same-shape arrays), one plane per
    packed word.  Bit-identical to ``fingerprint_words(words)`` where
    ``words[..., w] == planes[w]`` — the engine's structure-of-arrays layout
    keeps state words in separate lanes because XLA:TPU tiles the minor two
    dims to (8, 128): a ``[N, W]`` row buffer with W=2 pads 2 lanes to 128,
    a ~64x memory-traffic blowup on every elementwise op and gather.
    """
    with _overflow_ok(xp):
        u = xp.uint32
        fold_hi = fold_lo = None
        for i in range(len(planes)):
            pos_hi = u((0x9E3779B9 * (i + 1)) & 0xFFFFFFFF)
            pos_lo = u((0x61C88647 * (i + 1)) & 0xFFFFFFFF)
            w = planes[i].astype(xp.uint32)
            m_hi = _fmix32(w * u(_WORD_MIX_HI) + pos_hi, xp)
            m_lo = _fmix32(w * u(_WORD_MIX_LO) + pos_lo, xp)
            fold_hi = m_hi if fold_hi is None else fold_hi ^ m_hi
            fold_lo = m_lo if fold_lo is None else fold_lo ^ m_lo
        return _finalize(fold_hi, fold_lo, xp)


def fingerprint_u64(words, xp) -> "int | object":
    """Convenience: fingerprint as a python-int-compatible 64-bit value
    (host-side use only)."""
    hi, lo = fingerprint_words(words, xp)
    return (int(hi) << 32) | int(lo)
