"""64-bit fingerprints of bit-packed states, as two uint32 lanes.

The reference fingerprints states with a fixed-key 64-bit hash
(``/root/reference/src/lib.rs:327-336``); stability across runs is part of
the contract because witness paths are reconstructed from fingerprints later.

TPUs have no native 64-bit integer path worth using for this, so the device
fingerprint is two independent 32-bit murmur3-style lanes (fmix32 finalizer
constants, public domain) over the state words.  The same function runs under
numpy on the host — ``stateright_tpu.xla`` uses the host flavor during path
reconstruction, so host/device agreement is load-bearing and covered by
differential tests.

The pair (0, 0) is reserved as the hash-set EMPTY sentinel and is remapped.
"""

from __future__ import annotations

# fmix32 constants (murmur3 finalizer, public domain).
_C1 = 0x85EBCA6B
_C2 = 0xC2B2AE35
# Per-lane seeds; arbitrary fixed odd constants (stability is what matters).
_SEED_HI = 0x9E3779B9
_SEED_LO = 0x517CC1B7
_WORD_MIX_HI = 0x2545F491
_WORD_MIX_LO = 0x85157AF5


def _fmix32(h, xp):
    u = xp.uint32
    h = h ^ (h >> u(16))
    h = h * u(_C1)
    h = h ^ (h >> u(13))
    h = h * u(_C2)
    h = h ^ (h >> u(16))
    return h


def fingerprint_words(words, xp):
    """Fingerprint packed states: ``[..., W] uint32 -> ([...], [...])``
    (hi, lo) uint32 lanes.

    ``xp`` is the array namespace: ``numpy`` on host, ``jax.numpy`` under
    jit.  Both produce identical bits.
    """
    import contextlib

    import numpy as _np

    # numpy warns on (intended, wrapping) uint32 overflow; jnp does not.
    ctx = _np.errstate(over="ignore") if xp is _np else contextlib.nullcontext()
    with ctx:
        u = xp.uint32
        w_count = words.shape[-1]
        hi = xp.full(words.shape[:-1], _SEED_HI, dtype=xp.uint32)
        lo = xp.full(words.shape[:-1], _SEED_LO, dtype=xp.uint32)
        for i in range(w_count):
            w = words[..., i].astype(xp.uint32)
            hi = _fmix32(hi ^ (w * u(_WORD_MIX_HI) + u(i + 1)), xp)
            lo = _fmix32(
                lo ^ (w * u(_WORD_MIX_LO) + u(0x61C88647 * (i + 1) & 0xFFFFFFFF)), xp
            )
        # Reserve (0, 0) for the hash-set EMPTY sentinel.
        is_sentinel = (hi == u(0)) & (lo == u(0))
        lo = xp.where(is_sentinel, u(1), lo)
        return hi, lo


def fingerprint_u64(words, xp) -> "int | object":
    """Convenience: fingerprint as a python-int-compatible 64-bit value
    (host-side use only)."""
    hi, lo = fingerprint_words(words, xp)
    return (int(hi) << 32) | int(lo)
