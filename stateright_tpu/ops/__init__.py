"""Device-side building blocks for the XLA checker engine.

- :mod:`fphash` — 64-bit (2x uint32 lane) fingerprints of packed states,
  computed identically by numpy (host) and jnp (device).
- :mod:`hashset` — a functional open-addressing hash set in device HBM with
  deterministic batched insert, the TPU replacement for the reference's
  concurrent visited map (``/root/reference/src/checker/bfs.rs:29-31``).
"""

from . import fphash, hashset

__all__ = ["fphash", "hashset"]
