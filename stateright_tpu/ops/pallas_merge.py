"""Pallas streaming merge-insert: the sorted-set insert in O(n).

``sortedset.insert`` pays two table-scale multi-operand ``lax.sort``s
per level — the merge of [table ‖ batch] and the keep-compaction —
~(C+m) log^2 (C+m) comparator passes each, the dominant per-level cost
in the round-5 chip cost law (BASELINE.md). But the table is ALREADY
sorted (structure invariant) and the batch can be pre-sorted at [m]
cost, so the table-scale work is a pure two-way sorted MERGE with
adjacent-key dedup — O(C+m), and a natural sequential-grid pallas
kernel.

The kernel composes the op shapes this repo has chip evidence for and
avoids every pinned pathology (docs/backend_pathologies.md): no
scatters (#2), no wide sorts (#3), no ``lax.cond`` around big ops
(#4), no in-kernel cumsum or u32<->f32 casts and no dynamic-offset
vector stores (#6) — placement is the ring-targeted one-hot MXU
contraction proven in ``ops/pallas_compact.py``, and the only
dynamic-offset accesses are chunk DMAs.

Scheme (block B, chunk k = merged positions [kB, (k+1)B)):

  host/XLA side (``_merge_partition``): classic merge-path diagonal
  binary search, vectorized over all n_chunks+1 diagonals — [ii, jj]
  with ii[k]+jj[k] = kB such that the chunk consumes exactly
  table[ii[k]:ii[k+1]] and batch[jj[k]:jj[k+1]]. Pads (all-ones keys)
  merge like ordinary largest keys, so the partition needs no dynamic
  row counts. Ties break table-first (<=), which IS the reference
  semantics: an existing row beats an equal-key candidate
  (sortedset.insert's ticket rule, reference dfs.rs/bfs.rs dedup).

  kernel, per chunk (sequential grid, SMEM carries):
    1. DMA table[ii[k]:ii[k]+B] and batch[jj[k]:jj[k]+B] (stacked
       [4, B] planes each: key_hi, key_lo, val_hi, val_lo),
    2. block-local cross-ranks by [B, B] lexicographic pair-compare +
       row-sum: pos(a[u]) = u + ii[k] + jj[k] + #{b < a[u]} - kB,
       pos(b[v]) = v + jj[k] + ii[k] + #{a <= b[v]} - kB; the
       merge-path band theorem makes block-local ranks exact for
       in-chunk elements and provably >= B for the overhang, so
       ``pos < B`` masks the chunk's own elements,
    3. assemble the merged chunk (keys, values, is_batch flag) by one
       [2B, B] one-hot contraction,
    4. keep rule on the merged chunk: real table rows always; a real
       batch element iff its key differs from the PREVIOUS merged
       element's key (SMEM key-carry across chunks) — in-batch
       duplicate runs keep only their first (lowest ticket, by the
       presort), table-equal candidates die (table went first),
    5. survivors stream into the [4, 2B] output ring at the running
       offset (one-hot, triangular-matmul prefix sums); full chunks
       DMA to the new table at chunk-aligned offsets. Keep flags of
       the chunk's batch elements stream in batch-sorted order
       through a second [1, 2B] ring -> the ``is_new`` plane,
    6. survivor total past the output capacity freezes flushing
       (drop-safe by construction, as in pallas_compact) and reports
       overflow for the caller's grow-and-retry protocol.

``merge_insert`` wraps partition + kernel and returns the merged
planes RAW: rows at and past min(n_keep, C) are unspecified ring
garbage, and the caller MUST re-mask before treating the result as a
table (``sortedset.insert`` under ``STPU_SORTEDSET_INSERT=pallas``
zeroes them, restoring the structure's pad convention, and routes
``is_new`` back to batch order with one [m] sort — all remaining
sorts are batch-scale).

Exactness: every one-hot contraction sums at most one nonzero product
of 16-bit-valued f32 halves, and prefix sums accumulate <= 2B 0/1
terms — exact at ``Precision.HIGHEST`` (the same pin, and the same
bf16-truncation hazard, as pallas_compact).

CPU-exact via interpret mode; chip acceptance of the arbitrary-offset
input DMAs is THE open question for the next tunnel window
(tools/pallas_merge.py is the probe). If Mosaic's alignment rules
extend to DMA sources, the fallback is align-down + an in-register
one-hot shift; not built until the probe demands it.
"""

from __future__ import annotations

from typing import Tuple


def _pair_le(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al <= bl))


def _pair_lt(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al < bl))


def _merge_partition(tkh, tkl, ckh, ckl, B: int):
    """Merge-path diagonals for padded sorted planes: table [C], batch
    [m] -> (ii, jj) int32 [n_chunks + 1] with ii[k] + jj[k] = k*B,
    ii monotone. For diagonal d: ii[k] is the LARGEST i in
    [max(0, d-m), min(C, d)] with t[i-1] <= c[d-i] (table-first ties);
    found by log2 rounds of vectorized bisection (tiny: n_chunks+1
    lanes of [C]-gathers)."""
    import jax.numpy as jnp

    C = tkh.shape[0]
    m = ckh.shape[0]
    n_chunks = (C + m) // B
    d = jnp.arange(n_chunks + 1, dtype=jnp.int32) * B
    lo = jnp.maximum(0, d - m)
    hi = jnp.minimum(C, d)
    # Invariant: P(lo) holds (vacuous at i == max(0, d-m)), P(hi+1)
    # fails; bisect for the largest i with P(i) = t[i-1] <= c[d-i].
    steps = max(1, (C + m).bit_length())
    for _ in range(steps):
        mid = (lo + hi + 1) >> 1  # in (lo, hi]
        ti = jnp.clip(mid - 1, 0, C - 1)
        cj = jnp.clip(d - mid, 0, m - 1)
        ok = _pair_le(tkh[ti], tkl[ti], ckh[cj], ckl[cj])
        # mid == lo means the bracket is closed; d - mid < 0 cannot
        # happen (mid <= hi <= d).
        take = ok | (mid <= lo)
        lo = jnp.where(take, jnp.maximum(lo, mid), lo)
        hi = jnp.where(take, hi, jnp.minimum(hi, mid - 1))
    return lo, d - lo


def _onehot_place(stacked_f32, sel, jax, jnp):
    """[(rows), S] @ one-hot [S, T] at HIGHEST — exact placement."""
    return jax.lax.dot_general(
        stacked_f32,
        sel,
        (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )


def merge_insert(
    table,  # [4, C] u32 planes (key_hi, key_lo, val_hi, val_lo), key-sorted,
    #         pad rows carry the all-ones key
    batch,  # [4, m] u32 planes, key-sorted with ticket tie-break, all-ones pads
    *,
    block: int = 512,
    interpret: bool = False,
) -> Tuple["jax.Array", "jax.Array", "jax.Array"]:
    """Merge-dedup ``batch`` into ``table``: returns ``(merged [4, C],
    keep_batch [m] bool in BATCH-SORTED order, n_keep [] int32 — the
    TOTAL survivor count, > C meaning overflow)``. Rows of ``merged``
    at and past min(n_keep, C) are UNSPECIFIED (callers re-mask); on
    overflow the merged planes are truncated and must be discarded.
    C and m must be multiples of ``block``."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .pallas_compact import fuse16, ring_fold, split16, tri_inclusive

    C = table.shape[1]
    m = batch.shape[1]
    B = block
    assert table.shape[0] == 4 and batch.shape[0] == 4
    assert C % B == 0 and m % B == 0, (C, m, B)
    n_chunks = (C + m) // B

    ii, jj = _merge_partition(table[0], table[1], batch[0], batch[1], B)

    # Overhang pad: chunk loads read [idx, idx + B) with idx <= C (resp.
    # m); one extra all-ones block keeps every DMA in bounds.
    ones = jnp.full((4, B), jnp.uint32(0xFFFFFFFF))
    tpad = jnp.concatenate([table, ones], axis=1)
    bpad = jnp.concatenate([batch, ones], axis=1)

    def kernel(ii_ref, jj_ref, t_ref, b_ref, out_ref, new_ref, n_ref,
               ablk, bblk, ring, ring2, cnt, sems):
        full = jnp.uint32(0xFFFFFFFF)
        k = pl.program_id(0)

        @pl.when(k == 0)
        def _init():
            n_ref[0] = 0
            cnt[0] = 0  # survivors appended (ring 1)
            cnt[1] = 0  # ring-1 chunks flushed
            cnt[2] = 0  # ring-2 chunks flushed
            # Carry init = the all-ones bit pattern (i32 -1): no real
            # key equals it, so the first merged element never dedups
            # against the carry.
            cnt[3] = jnp.int32(-1)  # carry key_hi (prev merged)
            cnt[4] = jnp.int32(-1)  # carry key_lo

        i0 = ii_ref[k]
        j0 = jj_ref[k]
        dj = jj_ref[k + 1] - j0

        cp_a = pltpu.make_async_copy(
            t_ref.at[:, pl.ds(i0, B)], ablk, sems.at[0]
        )
        cp_b = pltpu.make_async_copy(
            b_ref.at[:, pl.ds(j0, B)], bblk, sems.at[1]
        )
        cp_a.start()
        cp_b.start()
        cp_a.wait()
        cp_b.wait()

        akh, akl = ablk[0], ablk[1]
        bkh, bkl = bblk[0], bblk[1]
        u = jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)  # a index
        v = jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)  # b index
        # rank_b[u] = #{v : b[v] < a[u]}; rank_a[v] = #{u : a[u] <= b[v]}
        lt_ba = _pair_lt(bkh[None, :], bkl[None, :], akh[:, None], akl[:, None])
        # Rank counts reduce in f32 (exact: counts <= B << 2^24) — Mosaic
        # has no integer-reduction lowering (stpu-lint STPU005).
        rank_b = jnp.sum(lt_ba.astype(jnp.float32), axis=1).astype(jnp.int32)
        rank_a = jnp.sum((~lt_ba).astype(jnp.float32), axis=0).astype(
            jnp.int32
        )  # #{a <= b[v]}

        base = i0 + j0 - k * B  # == 0, kept symbolic for clarity
        pos_a = jax.lax.broadcasted_iota(jnp.int32, (B,), 0) + rank_b + base
        pos_b = jax.lax.broadcasted_iota(jnp.int32, (B,), 0) + rank_a + base
        in_a = pos_a < B
        in_b = pos_b < B

        # Merged-chunk assembly: one [2B, B] one-hot. Rows = a lanes
        # then b lanes; out-of-chunk lanes target -1 (no column).
        tgt = jnp.concatenate(
            [jnp.where(in_a, pos_a, -1), jnp.where(in_b, pos_b, -1)]
        )
        colm = jax.lax.broadcasted_iota(jnp.int32, (2 * B, B), 1)
        sel = (colm == tgt[:, None]).astype(jnp.float32)
        planes = []
        for p in range(4):
            lo_a, hi_a = split16(ablk[p], jnp)
            lo_b, hi_b = split16(bblk[p], jnp)
            planes.append(jnp.concatenate([lo_a, lo_b]))
            planes.append(jnp.concatenate([hi_a, hi_b]))
        isb = jnp.concatenate(
            [jnp.zeros((B,), jnp.float32), jnp.ones((B,), jnp.float32)]
        )
        placed = _onehot_place(
            jnp.concatenate(
                [jnp.stack(planes), isb.reshape(1, 2 * B)], axis=0
            ),
            sel,
            jax,
            jnp,
        )  # [9, B]
        mkh = fuse16(placed[0], placed[1], jnp)
        mkl = fuse16(placed[2], placed[3], jnp)
        mvh = fuse16(placed[4], placed[5], jnp)
        mvl = fuse16(placed[6], placed[7], jnp)
        is_batch = placed[8] > 0.5

        # Keep rule (module docstring step 4). The SMEM key-carry round-
        # trips through i32 (same-width conversions are modular — bit
        # patterns survive).
        carry_kh = jnp.full((1,), cnt[3], jnp.int32).astype(jnp.uint32)
        carry_kl = jnp.full((1,), cnt[4], jnp.int32).astype(jnp.uint32)
        prev_kh = jnp.concatenate([carry_kh, mkh[:-1]])
        prev_kl = jnp.concatenate([carry_kl, mkl[:-1]])
        real = ~((mkh == full) & (mkl == full))
        differs = (mkh != prev_kh) | (mkl != prev_kl)
        keep = real & (~is_batch | differs)
        cnt[3] = mkh[B - 1].astype(jnp.int32)
        cnt[4] = mkl[B - 1].astype(jnp.int32)

        # Ring 1: survivors (4 planes) at the running offset — the
        # shared scatter-as-matmul ring fold (pallas_compact).
        t_cnt, c1 = cnt[0], cnt[1]
        p1 = t_cnt - c1 * B
        k_i32 = keep.astype(jnp.int32)
        incl = tri_inclusive(k_i32, B)
        # Survivor total = the prefix sum's last element (no integer
        # reduce_sum in Mosaic; stpu-lint STPU005).
        n_k = incl[B - 1]
        tgt1 = jnp.where(keep, incl - 1 + p1, -1)
        ring_fold(ring, [mkh, mkl, mvh, mvl], tgt1, B)
        t_cnt = t_cnt + n_k
        cnt[0] = t_cnt

        def flush1(chunk_idx):
            dma = pltpu.make_async_copy(
                ring.at[:, pl.ds(0, B)],
                out_ref.at[:, pl.ds(chunk_idx * B, B)],
                sems.at[2],
            )
            dma.start()
            dma.wait()

        @pl.when((t_cnt - c1 * B >= B) & ((c1 + 1) * B <= C))
        def _flush_full1():
            flush1(c1)
            ring[:, pl.ds(0, B)] = ring[:, pl.ds(B, B)]
            cnt[1] = c1 + 1

        # Ring 2: keep flags of this chunk's batch elements, in batch
        # order. Element v of the b block (v < dj) was consumed by this
        # chunk; its keep flag sits at merged position pos_b[v] —
        # gather it with sel's b half (one [B, B] @ [B, 1]).
        sel_b = sel[B:, :]  # [B, B]; row v one-hot at pos_b[v] (or 0)
        # flag_v[v] = keep[pos_b[v]] = sum_x keep[x] * sel_b[v, x]:
        # contract both operands on their LAST dim (no transpose — a
        # transpose fused into compute is registry #1's shape on CPU).
        flag_v = jax.lax.dot_general(
            keep.astype(jnp.float32).reshape(1, B),
            sel_b,
            (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        ).reshape(B)  # [B] f32; rows past dj are 0 via empty one-hots
        c2 = cnt[2]
        p2 = j0 - c2 * B
        vv = jax.lax.broadcasted_iota(jnp.int32, (B,), 0)
        tgt2 = jnp.where(vv < dj, vv + p2, -1)
        col2 = jax.lax.broadcasted_iota(jnp.int32, (B, 2 * B), 1)
        sel2 = (col2 == tgt2[:, None]).astype(jnp.float32)
        placed2 = _onehot_place(flag_v.reshape(1, B), sel2, jax, jnp)
        hit2 = jnp.sum(sel2, axis=0, keepdims=True) > 0.5
        ring2[:, :] = jnp.where(hit2, placed2, ring2[:, :])
        j_end = j0 + dj

        def flush2(chunk_idx):
            dma = pltpu.make_async_copy(
                ring2.at[:, pl.ds(0, B)],
                new_ref.at[:, pl.ds(chunk_idx * B, B)],
                sems.at[3],
            )
            dma.start()
            dma.wait()

        # Ring 2 needs no tail flush and no freeze guard: every batch
        # element writes exactly one flag, j_end reaches exactly m
        # (a multiple of B), and eager flushing keeps the residue < B —
        # so the final residue is ≡ 0 (mod B) AND < B, i.e. zero, and
        # (c2+1)*B <= j_end <= m always holds at flush time.
        @pl.when(j_end - c2 * B >= B)
        def _flush_full2():
            flush2(c2)
            ring2[:, pl.ds(0, B)] = ring2[:, pl.ds(B, B)]
            cnt[2] = c2 + 1

        @pl.when(k == n_chunks - 1)
        def _tail():
            n_ref[0] = cnt[0]
            c1f = cnt[1]

            @pl.when((cnt[0] > c1f * B) & ((c1f + 1) * B <= C))
            def _():
                flush1(c1f)

    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    merged, flags, n_keep = pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[smem_spec, smem_spec, any_spec, any_spec],
        out_specs=[any_spec, any_spec, smem_spec],
        out_shape=[
            jax.ShapeDtypeStruct((4, C), jnp.uint32),
            jax.ShapeDtypeStruct((1, m), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((4, B), jnp.uint32),  # a block
            pltpu.VMEM((4, B), jnp.uint32),  # b block
            pltpu.VMEM((4, 2 * B), jnp.uint32),  # ring 1
            pltpu.VMEM((1, 2 * B), jnp.float32),  # ring 2
            pltpu.SMEM((5,), jnp.int32),
            pltpu.SemaphoreType.DMA((4,)),
        ],
        interpret=interpret,
    )(ii, jj, tpad, bpad)
    return merged, flags.reshape(m) > 0.5, n_keep[0]
