"""Device-resident open-addressing hash set with deterministic batched insert.

This is the TPU-native replacement for the reference's concurrent visited
map — ``DashMap<Fingerprint, Option<Fingerprint>>`` with its insert-if-vacant
race (``/root/reference/src/checker/bfs.rs:29-31, 349-363``).  On a TPU there
are no atomics to lean on; instead each probe round elects one winner per
slot with a commutative scatter-min (order-independent, hence deterministic),
winners claim their slot with conflict-free scatters, and losers keep probing.

Layout: four uint32 planes of length ``capacity`` (a power of two) —
``key_hi``/``key_lo`` hold the 64-bit fingerprint, ``val_hi``/``val_lo`` hold
the predecessor fingerprint used for witness-path reconstruction (the same
parent-pointer scheme as bfs.rs:351).  EMPTY is key == (0, 0);
``fphash.fingerprint_words`` never produces that pair.

Everything is functional (donated/threaded through jit) and shape-static, so
the whole super-step fuses into one XLA program.  Per-round cost is
O(batch): the slot election scatters into a claim buffer of size
``~2*batch`` indexed by ``slot mod B`` rather than a full ``[capacity]``
plane — a false conflict (two different slots sharing a claim index) only
delays the loser to the next round, so correctness and the min-index
determinism are unaffected while insert bandwidth scales with the batch,
not the table.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple


class HashSet(NamedTuple):
    key_hi: "jax.Array"  # [C] uint32
    key_lo: "jax.Array"  # [C] uint32
    val_hi: "jax.Array"  # [C] uint32
    val_lo: "jax.Array"  # [C] uint32

    @property
    def capacity(self) -> int:
        return self.key_hi.shape[0]


def make(capacity: int, xp) -> HashSet:
    """An empty hash set with ``capacity`` slots (power of two)."""
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    z = xp.zeros((capacity,), dtype=xp.uint32)
    return HashSet(z, z, z, z)


def insert(
    hs: HashSet,
    fp_hi,
    fp_lo,
    val_hi,
    val_lo,
    active,
    *,
    max_probes: int = 32,
) -> Tuple[HashSet, "jax.Array", "jax.Array"]:
    """Insert a batch of fingerprints; returns ``(hs', is_new, overflow)``.

    - ``is_new[i]``: the fingerprint was not present and this batch element
      won the slot (exactly one winner among in-batch duplicates; the winner
      is the lowest batch index, for determinism).
    - ``overflow[i]``: still unresolved after ``max_probes`` genuine probe
      advances (slots occupied by *other* keys) — the caller must
      grow/rehash (the reference leans on DashMap resizing; here growth is
      an explicit host-driven rehash). Election losses in the claim buffer
      do NOT count against the budget: a loss means some other element
      completed that round, so retries make global progress and growing
      the table (which cannot change claim contention) is never the wrong
      remedy for a reported overflow.

    Shape-static, jit-friendly; all elections are commutative scatter-mins,
    so results do not depend on scatter execution order.
    """
    import jax
    import jax.numpy as jnp

    cap = hs.capacity
    mask = jnp.uint32(cap - 1)
    m = fp_hi.shape[0]
    ticket = jnp.arange(m, dtype=jnp.int32)
    sentinel = jnp.int32(2**31 - 1)
    # Claim buffer: a power of two >= 2*batch (capped at the table size),
    # indexed by the low bits of the slot. Distinct slots sharing a claim
    # index is a *false conflict*: the election loser keeps its slot and
    # retries next round, so results stay exact — this is what makes insert
    # bandwidth O(batch) instead of O(capacity).
    claim_cap = 16
    while claim_cap < 2 * m:
        claim_cap *= 2
    claim_cap = min(claim_cap, cap)
    cmask = jnp.uint32(claim_cap - 1)

    slot0 = ((fp_hi ^ (fp_lo * jnp.uint32(0x9E3779B1))) & mask).astype(jnp.int32)
    done0 = ~active
    is_new0 = jnp.zeros((m,), dtype=jnp.bool_)
    probes0 = jnp.zeros((m,), dtype=jnp.int32)

    def round_fn(carry):
        rnd, slot, probes, done, is_new, key_hi, key_lo, val_hi_t, val_lo_t = carry
        live = ~done & (probes < max_probes)
        kh = key_hi[slot]
        kl = key_lo[slot]
        occupied = (kh != 0) | (kl != 0)
        match = live & occupied & (kh == fp_hi) & (kl == fp_lo)
        done = done | match
        cand = live & ~match & ~occupied
        # Elect one winner per claim index: lowest batch index (scatter-min
        # is commutative => deterministic regardless of execution order).
        # Same-slot candidates share a claim index, so winners have unique
        # slots even when the buffer is smaller than the table.
        cidx = (slot.astype(jnp.uint32) & cmask).astype(jnp.int32)
        claim = jnp.full((claim_cap,), sentinel, dtype=jnp.int32)
        claim = claim.at[cidx].min(jnp.where(cand, ticket, sentinel))
        winner = cand & (claim[cidx] == ticket)
        # Winners have unique slots; their writes are conflict-free.
        # Losers are routed out of range and dropped.
        widx = jnp.where(winner, slot, cap)
        key_hi = key_hi.at[widx].set(fp_hi, mode="drop")
        key_lo = key_lo.at[widx].set(fp_lo, mode="drop")
        val_hi_t = val_hi_t.at[widx].set(val_hi, mode="drop")
        val_lo_t = val_lo_t.at[widx].set(val_lo, mode="drop")
        is_new = is_new | winner
        done = done | winner
        # Advance only probes blocked by a different key — and only those
        # count against the max_probes budget. Election losers retry the
        # same slot without spending budget (they may be in-batch
        # duplicates of the new winner and must observe its key next
        # round; their loss implies the winner completed, so rounds still
        # make global progress).
        bump = live & occupied & ~match
        probes = probes + bump.astype(jnp.int32)
        slot = jnp.where(
            bump,
            ((slot.astype(jnp.uint32) + jnp.uint32(1)) & mask).astype(jnp.int32),
            slot,
        )
        return rnd + 1, slot, probes, done, is_new, key_hi, key_lo, val_hi_t, val_lo_t

    def round_cond(carry):
        rnd, _slot, probes, done, *_rest = carry
        # Early exit once every element is resolved or out of probe
        # budget. Every round either completes an element or bumps one
        # toward its budget, so this terminates within m + max_probes
        # rounds; `rnd` caps it absolutely as a belt-and-braces bound.
        return (rnd < max_probes + m) & jnp.any(~done & (probes < max_probes))

    _, slot, probes, done, is_new, key_hi, key_lo, val_hi_t, val_lo_t = (
        jax.lax.while_loop(
            round_cond, round_fn, (jnp.int32(0), slot0, probes0, done0, is_new0, *hs)
        )
    )
    overflow = ~done
    return HashSet(key_hi, key_lo, val_hi_t, val_lo_t), is_new, overflow


def lookup(hs: HashSet, fp_hi, fp_lo, *, max_probes: int = 32):
    """Batched membership + value lookup: returns ``(found, val_hi, val_lo)``."""
    import jax.numpy as jnp

    cap = hs.capacity
    mask = jnp.uint32(cap - 1)
    slot = ((fp_hi ^ (fp_lo * jnp.uint32(0x9E3779B1))) & mask).astype(jnp.int32)
    found = jnp.zeros(fp_hi.shape, dtype=jnp.bool_)
    vh = jnp.zeros(fp_hi.shape, dtype=jnp.uint32)
    vl = jnp.zeros(fp_hi.shape, dtype=jnp.uint32)
    live = jnp.ones(fp_hi.shape, dtype=jnp.bool_)
    for _ in range(max_probes):
        kh = hs.key_hi[slot]
        kl = hs.key_lo[slot]
        occupied = (kh != 0) | (kl != 0)
        match = live & occupied & (kh == fp_hi) & (kl == fp_lo)
        vh = jnp.where(match, hs.val_hi[slot], vh)
        vl = jnp.where(match, hs.val_lo[slot], vl)
        found = found | match
        live = live & occupied & ~match
        slot = ((slot.astype(jnp.uint32) + jnp.uint32(1)) & mask).astype(jnp.int32)
    return found, vh, vl
