"""Sort-merge visited set: the TPU-native dedup structure.

The round-2 visited set (``ops/hashset.py``) is an open-addressing table
whose batched insert runs claim-election rounds of gathers and scatters.
That shape is right for CPUs and wrong for TPUs: XLA:TPU executes the
per-round scatters effectively serially, and the on-chip cost model
(BASELINE.md, ``tpu_microbench.log``) measured the insert at 0.24 M ins/s
for a 2^22 batch — 17.3 seconds — while ``lax.sort`` moved the same batch
in ~3 ms.  On a TPU, **sort is the hash table**.

This module keeps the visited set as a key-sorted array instead.  One
multi-key ``lax.sort`` of ``[visited ‖ candidates]`` per level performs,
simultaneously:

- membership (a candidate equal to a visited key lands in that key's run,
  behind it),
- in-batch dedup with the same determinism rule as the hash insert (the
  lowest original batch index wins: the original index is the sort's
  tie-break key),
- the merge (survivors are already in key order; a stable compaction
  restores the dense sorted prefix).

It replaces the concurrent visited map of the reference's BFS core
(``/root/reference/src/checker/bfs.rs:29-31, 349-363``) just like the
hash set did, stores the same parent-fingerprint values for witness
reconstruction, and its planes keep the hash set's external layout
contract — occupied rows have non-(0,0) keys, pads are zeros — so the
checkpoint codec and the native ``ParentMap`` consume either structure
unchanged.  ``(0xFFFFFFFF, 0xFFFFFFFF)`` is additionally reserved (the
in-sort pad sentinel, remapped by ``ops/fphash.py`` exactly like (0,0)).

Unlike the hash set there is no probe budget and no rehash: growth is a
plain copy into bigger planes, and capacity overflow is detected exactly
(merged count > capacity) rather than probabilistically.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Tuple

import numpy as np

#: How ``insert`` moves the value planes and the merged keys into place:
#: ``"gather"`` sorts 3 operands and recovers values/rows with post-sort
#: gathers (fewest sorted bytes); ``"sort"`` carries them as sort payload
#: operands (no random gathers). The round-5 on-chip A/B settled it: the
#: sort family is 2.3x faster end-to-end on TPU (random gathers at table
#: scale dominate the per-level cost, tpu_profile_r5.log) while gather
#: wins on 1-core CPU — so ``"auto"`` (the default) resolves per backend
#: at trace time. Results are bit-identical; differentially tested. The
#: env var makes the on-chip A/B a process restart.
VALUES_VIA = os.environ.get("STPU_SORTEDSET_VALUES", "auto")

#: Key/value lane width for the insert's sorts: ``"pair"`` keeps the
#: (hi, lo) u32 planes (3 key operands + 2 payloads); ``"packed"`` folds
#: them into u64 lanes (2 keys + 1 payload — ~40% fewer sorted
#: lane-bytes IF the backend sorts u64 at u32 rates; CPU measured 0.62x,
#: tools/sortbench.py). Packed mode requires ``jax_enable_x64`` and the
#: sort-values family; results are bit-identical either way
#: (differential-tested). Trace-time constant like VALUES_VIA.
KEYS_VIA = os.environ.get("STPU_SORTEDSET_KEYS", "pair")

#: Insert lowering: ``"sort"`` = the two table-scale multi-operand
#: ``lax.sort``s below; ``"pallas"`` = the O(C+m) streaming merge
#: kernel (``ops/pallas_merge.py``) — the table-scale log^2 term
#: disappears and every remaining sort is batch-scale. Opt-in pending
#: the chip A/B (tools/pallas_merge.py); CPU runs the kernel in
#: interpret mode (slow, exact). Trace-time constant like VALUES_VIA.
INSERT_VIA = os.environ.get("STPU_SORTEDSET_INSERT", "sort")


def _via_sort() -> bool:
    if VALUES_VIA == "auto":
        import jax

        return jax.default_backend() != "cpu"
    return VALUES_VIA == "sort"


def _pack64(hi, lo, jnp):
    """(hi, lo) u32 pair -> one u64 lane, ordering-preserving."""
    return (hi.astype(jnp.uint64) << 32) | lo.astype(jnp.uint64)


def _unpack64(x, jnp):
    return (x >> 32).astype(jnp.uint32), x.astype(jnp.uint32)


def _via_packed() -> bool:
    if KEYS_VIA != "packed":
        return False
    import jax

    if not jax.config.jax_enable_x64:
        raise ValueError(
            "STPU_SORTEDSET_KEYS=packed requires jax_enable_x64 (u64 sort "
            "lanes); enable it before first backend use"
        )
    if not _via_sort():
        raise ValueError(
            "STPU_SORTEDSET_KEYS=packed composes with the sort-values "
            "family only (STPU_SORTEDSET_VALUES=sort)"
        )
    return True


class SortedSet(NamedTuple):
    """First ``n`` rows of the planes are sorted ascending by (hi, lo) and
    unique; rows at ``n`` and beyond are (0, 0) pads."""

    key_hi: "jax.Array"  # [C] uint32
    key_lo: "jax.Array"  # [C] uint32
    val_hi: "jax.Array"  # [C] uint32
    val_lo: "jax.Array"  # [C] uint32
    n: "jax.Array"  # [] int32 — occupied prefix length

    @property
    def capacity(self) -> int:
        return self.key_hi.shape[0]


def make(capacity: int, xp) -> SortedSet:
    """An empty sorted set with ``capacity`` row slots (power of two)."""
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    z = xp.zeros((capacity,), dtype=xp.uint32)
    return SortedSet(z, z, z, z, xp.asarray(0, dtype=xp.int32))


def from_entries(key_hi, key_lo, val_hi, val_lo, capacity: int, xp) -> SortedSet:
    """Host-side bulk build from unique (key, value) pairs (checkpoint
    restore, init seeding).  Sorts once with numpy; no device round-trips."""
    key_hi = np.asarray(key_hi, np.uint32)
    key_lo = np.asarray(key_lo, np.uint32)
    val_hi = np.asarray(val_hi, np.uint32)
    val_lo = np.asarray(val_lo, np.uint32)
    n = len(key_hi)
    if capacity < n or capacity & (capacity - 1):
        raise ValueError(f"capacity {capacity} cannot hold {n} sorted entries")
    order = np.lexsort((key_lo, key_hi))
    planes = []
    for a in (key_hi[order], key_lo[order], val_hi[order], val_lo[order]):
        out = np.zeros(capacity, np.uint32)
        out[:n] = a
        planes.append(xp.asarray(out))
    return SortedSet(*planes, xp.asarray(n, dtype=xp.int32))


def insert(
    ss: SortedSet,
    fp_hi,
    fp_lo,
    val_hi,
    val_lo,
    active,
    *,
    max_probes: int = 0,  # accepted for hashset signature compatibility; unused
) -> Tuple[SortedSet, "jax.Array", "jax.Array"]:
    """Insert a batch; returns ``(ss', is_new, overflow)``.

    Semantics match ``hashset.insert`` exactly: ``is_new[i]`` (in the
    original batch order) marks the single winner among in-batch
    duplicates — the lowest batch index — of a key not already present;
    winners' values are stored; ``overflow`` (scalar) reports that the
    merged set does not fit the capacity, in which case the caller grows
    and retries (the returned set is truncated and must be discarded).
    """
    import jax
    import jax.numpy as jnp

    cap = ss.capacity
    m = fp_hi.shape[0]
    full = jnp.uint32(0xFFFFFFFF)

    if INSERT_VIA == "pallas":
        blk = _pallas_insert_block(cap, m)
        if blk:
            return _insert_via_merge(ss, fp_hi, fp_lo, val_hi, val_lo,
                                     active, blk)
        # Shapes below the kernel block fall through to the sort
        # lowering, bit-identically (same convention as compact_1d).

    # Pad rows (unoccupied visited slots, inactive candidates) get the
    # reserved all-ones key so they sort to the tail as one run.
    vis_valid = jnp.arange(cap) < ss.n
    kh = jnp.concatenate([jnp.where(vis_valid, ss.key_hi, full), jnp.where(active, fp_hi, full)])
    kl = jnp.concatenate([jnp.where(vis_valid, ss.key_lo, full), jnp.where(active, fp_lo, full)])
    # Tie-break ticket = position in the concatenated input: visited row i
    # carries i (< cap), candidate i carries cap + i — so visited rows sort
    # ahead of any equal-key candidate and in-batch duplicates resolve to
    # the lowest original index, making the key triple unique (visited keys
    # are unique by invariant) and the pipeline deterministic by
    # construction. The ticket doubles as the gather index that recovers
    # values AFTER the sort: values ride one gather each instead of two
    # extra sort operands (a sort operand is ~log^2 n data passes, a gather
    # is one).
    ticket = jnp.arange(cap + m, dtype=jnp.int32)

    via_sort = _via_sort()
    via_packed = _via_packed()
    if via_packed:
        # u64-folded lanes: (key64, ticket) as keys, value64 as payload —
        # 3 operands instead of 5 on the dominant merge sort. The u64
        # key orders exactly as the (hi, lo) pair; the all-ones pad maps
        # to the all-ones u64.
        k64 = (kh.astype(jnp.uint64) << 32) | kl.astype(jnp.uint64)
        v64 = (
            jnp.concatenate([ss.val_hi, val_hi]).astype(jnp.uint64) << 32
        ) | jnp.concatenate([ss.val_lo, val_lo]).astype(jnp.uint64)
        sk64, st, sv64 = jax.lax.sort((k64, ticket, v64), num_keys=2)
        skh = (sk64 >> 32).astype(jnp.uint32)
        skl = sk64.astype(jnp.uint32)
    elif via_sort:
        vh = jnp.concatenate([ss.val_hi, val_hi])
        vl = jnp.concatenate([ss.val_lo, val_lo])
        skh, skl, st, svh, svl = jax.lax.sort((kh, kl, ticket, vh, vl), num_keys=3)
    else:
        skh, skl, st = jax.lax.sort((kh, kl, ticket), num_keys=3)

    run_start = jnp.concatenate(
        [
            jnp.ones((1,), jnp.bool_),
            (skh[1:] != skh[:-1]) | (skl[1:] != skl[:-1]),
        ]
    )
    real = ~((skh == full) & (skl == full))
    is_cand = st >= cap
    winner = run_start & is_cand & real  # run has no visited row, lowest ticket
    keep = real & (winner | ~is_cand)  # surviving = old rows + new winners
    new_n = jnp.sum(keep, dtype=jnp.int32)
    overflow = new_n > cap

    # Stable compaction of survivors to the front keeps them key-sorted.
    row_ok = jnp.arange(cap) < jnp.minimum(new_n, cap)
    z = jnp.uint32(0)
    if via_packed:
        ckey = jnp.where(keep, jnp.int32(0), jnp.int32(1))
        _, ck64, cv64 = jax.lax.sort(
            (ckey, sk64, sv64), num_keys=1, is_stable=True
        )
        nkh = jnp.where(row_ok, (ck64[:cap] >> 32).astype(jnp.uint32), z)
        nkl = jnp.where(row_ok, ck64[:cap].astype(jnp.uint32), z)
        nvh = jnp.where(row_ok, (cv64[:cap] >> 32).astype(jnp.uint32), z)
        nvl = jnp.where(row_ok, cv64[:cap].astype(jnp.uint32), z)
    elif via_sort:
        # Payload-through-sort: the compaction permutation moves every
        # plane inside one more sort (keep-rank is the key), no gathers.
        ckey = jnp.where(keep, jnp.int32(0), jnp.int32(1))
        _, ckh, ckl, cvh, cvl = jax.lax.sort(
            (ckey, skh, skl, svh, svl), num_keys=1, is_stable=True
        )
        nkh = jnp.where(row_ok, ckh[:cap], z)
        nkl = jnp.where(row_ok, ckl[:cap], z)
        nvh = jnp.where(row_ok, cvh[:cap], z)
        nvl = jnp.where(row_ok, cvl[:cap], z)
    else:
        order = jnp.argsort(~keep, stable=True)[:cap]
        nkh = jnp.where(row_ok, skh[order], z)
        nkl = jnp.where(row_ok, skl[order], z)
        # Values of surviving rows, via their pre-sort position.
        vh = jnp.concatenate([ss.val_hi, val_hi])
        vl = jnp.concatenate([ss.val_lo, val_lo])
        src = st[order]
        nvh = jnp.where(row_ok, vh[src], z)
        nvl = jnp.where(row_ok, vl[src], z)

    # Route is_new back to original batch order.
    if via_sort:
        # Scatter-free: sorting (ticket, winner) by ticket is the inverse
        # permutation; candidate lanes are the tail cap:.
        _, winner_in_order = jax.lax.sort(
            (st, winner.astype(jnp.int32)), num_keys=1
        )
        is_new = winner_in_order[cap:].astype(jnp.bool_)
    else:
        # Winner tickets are unique, so the scatter is conflict-free;
        # non-winners are routed out of range.
        idx = jnp.where(winner, st - cap, m)
        is_new = jnp.zeros((m,), jnp.bool_).at[idx].set(True, mode="drop")

    return SortedSet(nkh, nkl, nvh, nvl, jnp.minimum(new_n, cap)), is_new, overflow


def _insert_via_merge(ss, fp_hi, fp_lo, val_hi, val_lo, active, blk):
    """``insert`` by the O(C+m) pallas streaming merge
    (ops/pallas_merge.py): one BATCH-scale presort, the kernel, one
    batch-scale inverse sort — no table-scale sort anywhere. Returns
    the identical contract, bit-for-bit (pinned by
    tests/test_pallas_merge.py's engine differential)."""
    import jax
    import jax.numpy as jnp

    from .pallas_merge import merge_insert

    cap = ss.capacity
    m = fp_hi.shape[0]
    full = jnp.uint32(0xFFFFFFFF)

    # Batch presort by (key, ticket): lowest batch index first within
    # equal keys, so the kernel's keep-first rule elects the reference
    # winner. Inactive rows get the all-ones key (never real).
    kh = jnp.where(active, fp_hi, full)
    kl = jnp.where(active, fp_lo, full)
    ticket = jnp.arange(m, dtype=jnp.int32)
    skh, skl, st, svh, svl = jax.lax.sort(
        (kh, kl, ticket, val_hi, val_lo), num_keys=3
    )

    vis_valid = jnp.arange(cap) < ss.n
    table = jnp.stack(
        [
            jnp.where(vis_valid, ss.key_hi, full),
            jnp.where(vis_valid, ss.key_lo, full),
            ss.val_hi,
            ss.val_lo,
        ]
    )
    batch = jnp.stack([skh, skl, svh, svl])
    interp = jax.default_backend() == "cpu"
    merged, keep_sorted, n_keep = merge_insert(
        table, batch, block=blk, interpret=interp
    )

    overflow = n_keep > cap
    new_n = jnp.minimum(n_keep, cap)
    row_ok = jnp.arange(cap) < new_n
    z = jnp.uint32(0)
    out = SortedSet(
        jnp.where(row_ok, merged[0], z),
        jnp.where(row_ok, merged[1], z),
        jnp.where(row_ok, merged[2], z),
        jnp.where(row_ok, merged[3], z),
        new_n,
    )
    # is_new back to batch order: sorting (ticket, flag) by ticket is
    # the inverse permutation — batch-scale, scatter-free.
    _, in_order = jax.lax.sort(
        (st, keep_sorted.astype(jnp.int32)), num_keys=1
    )
    return out, in_order.astype(jnp.bool_), overflow


def _pallas_insert_block(cap: int, m: int) -> int:
    """The streaming-merge kernel block :func:`insert` will use at these
    shapes, or 0 when they fall through to the sort lowering — ONE
    predicate shared by the insert and its lane-words telemetry, so the
    cost law can't silently drift from the actual lowering."""
    blk = int(os.environ.get("STPU_PALLAS_BLOCK", "512"))
    if cap % blk == 0 and m % blk == 0 and cap >= blk and m >= blk:
        return blk
    return 0


def insert_lane_words(ss: SortedSet, m: int) -> int:
    """32-bit words carried as ``lax.sort`` operands by one :func:`insert`
    with an ``m``-lane batch at this table's capacity — the engine's
    cost-law telemetry (round-5 law: per-level time ~ sorted lane-words
    x log^2 n). Counts sort operands only; post-sort gathers and the
    scatter ``is_new`` route are not sorted lanes. Tracks the same
    trace-time lowering knobs the insert resolves."""
    cap = ss.capacity
    if INSERT_VIA == "pallas" and _pallas_insert_block(cap, m):
        # Batch-scale only: 5-operand presort + 2-operand inverse.
        return m * 7
    n = cap + m
    if _via_sort():
        # Packed or pair, the sorted WORDS agree (packed trades operand
        # streams, not bytes): 5-word merge + 5-word compaction + 2-word
        # inverse permutation.
        return n * 12
    # Gather family: 3-operand merge + 2-operand compaction argsort;
    # values and is_new move by gather/scatter.
    return n * 5


def lookup(ss: SortedSet, fp_hi, fp_lo, *, max_probes: int = 0):
    """Batched membership + value lookup: ``(found, val_hi, val_lo)``.
    Branchless lower-bound descent — log2(capacity) rounds of gathers,
    no scatters (the shape ``ops/hashset.lookup`` used probe rounds for)."""
    import jax.numpy as jnp

    cap = ss.capacity
    off = jnp.zeros(fp_hi.shape, jnp.int32)
    step = cap
    while step > 1:
        step //= 2
        mid = off + step
        kh = ss.key_hi[mid - 1]
        kl = ss.key_lo[mid - 1]
        less = (kh < fp_hi) | ((kh == fp_hi) & (kl < fp_lo))
        off = jnp.where((mid <= ss.n) & less, mid, off)
    at = jnp.minimum(off, cap - 1)
    hit = (off < ss.n) & (ss.key_hi[at] == fp_hi) & (ss.key_lo[at] == fp_lo)
    vh = jnp.where(hit, ss.val_hi[at], jnp.uint32(0))
    vl = jnp.where(hit, ss.val_lo[at], jnp.uint32(0))
    return hit, vh, vl


def grow(ss: SortedSet, new_capacity: int, xp) -> SortedSet:
    """Capacity growth is a plain copy — no rehash (the sorted invariant
    is capacity-independent, unlike hash slot assignment)."""
    if new_capacity < ss.capacity:
        raise ValueError("sorted set cannot shrink")
    pad = new_capacity - ss.capacity
    planes = [
        xp.concatenate([p, xp.zeros((pad,), dtype=xp.uint32)])
        for p in (ss.key_hi, ss.key_lo, ss.val_hi, ss.val_lo)
    ]
    return SortedSet(*planes, ss.n)
