"""Core model abstraction: ``Model``, ``Property``, ``Expectation``.

TPU-native re-design of the reference's central trait
(``/root/reference/src/lib.rs:155-325``).  A ``Model`` describes a
nondeterministic transition system: initial states, the actions enabled in a
state, and a (partial) transition function.  Checkers search the induced state
graph for property violations.

Models checked on TPU additionally implement the :class:`PackedModel`
protocol (see ``stateright_tpu/xla.py``), which exposes the same transition
system as a jittable fixed-width kernel over bit-packed state words.  The
object-level API here is the semantic contract and the CPU oracle.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, List, Optional, Tuple


class Expectation(Enum):
    """Whether a property must hold always, eventually, or sometimes.

    Mirrors lib.rs:318-325.
    """

    ALWAYS = "always"
    EVENTUALLY = "eventually"
    SOMETIMES = "sometimes"


@dataclass(frozen=True)
class Property:
    """A named predicate over (model, state). Mirrors lib.rs:261-305.

    - ``always``: safety; the checker seeks a counterexample.
    - ``sometimes``: reachability; the checker seeks an example.
    - ``eventually``: liveness (terminal-state based; only correct on acyclic
      paths — the checker replicates the reference's documented false-negative
      semantics for cycles/DAG joins, lib.rs:283-287).
    """

    expectation: Expectation
    name: str
    condition: Callable[[Any, Any], bool]

    @staticmethod
    def always(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        return Property(Expectation.ALWAYS, name, condition)

    @staticmethod
    def eventually(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        return Property(Expectation.EVENTUALLY, name, condition)

    @staticmethod
    def sometimes(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        return Property(Expectation.SOMETIMES, name, condition)


class Model:
    """The primary abstraction: a nondeterministic transition system.

    Mirrors the reference's ``Model`` trait (lib.rs:155-254).  Subclasses
    implement ``init_states``, ``actions``, and ``next_state``; everything
    else has default implementations.
    """

    def init_states(self) -> List[Any]:
        """Returns the initial possible states."""
        raise NotImplementedError

    def actions(self, state: Any, actions: List[Any]) -> None:
        """Appends the actions possible from ``state`` to ``actions``."""
        raise NotImplementedError

    def next_state(self, last_state: Any, action: Any) -> Optional[Any]:
        """Applies ``action``; ``None`` indicates the action is a no-op."""
        raise NotImplementedError

    def format_action(self, action: Any) -> str:
        """Intuitive representation of an action (e.g. for the Explorer)."""
        return repr(action)

    def format_step(self, last_state: Any, action: Any) -> Optional[str]:
        """Intuitive representation of a step (e.g. for the Explorer)."""
        next_state = self.next_state(last_state, action)
        return None if next_state is None else repr(next_state)

    def as_svg(self, path: Any) -> Optional[str]:
        """An SVG representation of a path for this model (Explorer pane)."""
        return None

    def next_steps(self, last_state: Any) -> List[Tuple[Any, Any]]:
        """The (action, state) steps that follow ``last_state``.

        Mirrors lib.rs:196-210.
        """
        actions: List[Any] = []
        self.actions(last_state, actions)
        steps = []
        for action in actions:
            state = self.next_state(last_state, action)
            if state is not None:
                steps.append((action, state))
        return steps

    def next_states(self, last_state: Any) -> List[Any]:
        """The states that follow ``last_state``. Mirrors lib.rs:214-221."""
        actions: List[Any] = []
        self.actions(last_state, actions)
        states = []
        for action in actions:
            state = self.next_state(last_state, action)
            if state is not None:
                states.append(state)
        return states

    def properties(self) -> List[Property]:
        """The expected properties for this model."""
        return []

    def property(self, name: str) -> Property:
        """Looks up a property by name; raises if absent (lib.rs:229-239)."""
        for p in self.properties():
            if p.name == name:
                return p
        available = [p.name for p in self.properties()]
        raise KeyError(f"Unknown property. requested={name}, available={available}")

    def within_boundary(self, state: Any) -> bool:
        """Whether ``state`` is inside the checked state space."""
        return True

    def checker(self) -> "CheckerBuilder":
        """Instantiates a CheckerBuilder for this model (lib.rs:247-253)."""
        from .checker.builder import CheckerBuilder

        return CheckerBuilder(self)
