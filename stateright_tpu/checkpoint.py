"""Checkpoint/resume for the XLA checkers.

The reference has no checkpointing — a run is memory-resident and
single-shot (SURVEY.md §5). With the visited set resident in device HBM,
host-side checkpointing becomes an explicit feature of this framework: long
checks (or preemptible TPU time) can stop after any super-step and resume
later, on a different chip count.

Format (``np.savez_compressed``): the *logical* search state, independent of
any engine's memory layout —

- the visited set as compacted ``(fingerprint, parent)`` pairs (four uint32
  lanes),
- the frontier as packed state rows + eventually-bit words,
- scalar progress counters and discovery pins,
- model identity metadata (class name + packed geometry), validated on
  restore.

Restoring *rebuilds* the hash table by insertion, so a checkpoint written by
the single-chip engine loads into the sharded engine (and vice versa), and
capacities may differ across save/restore.

Crash-safety (the recovery stack, docs/observability.md "Recovery"): a
checkpoint is the thing a run falls back to after the axon tunnel wedges, so
the file itself must survive the failure modes around it —

- **atomic**: writes land in a same-directory temp file and go live via
  ``os.replace``; a SIGKILL mid-save can never tear the live file;
- **self-verifying**: the metadata embeds a SHA-256 over every payload
  array, recomputed on load — truncation, foreign writers, or bit rot
  raise the typed :class:`CheckpointCorrupt`, never a bare zipfile
  traceback;
- **rotating**: ``save_checkpoint(..., keep=K)`` shifts the previous file
  to ``<path>.1`` (and so on, retaining the last K), so a reader that finds
  the newest rotation corrupt falls back to the one before it —
  :func:`latest_valid_checkpoint` is that fallback, and the supervisor
  (``stateright_tpu/supervise.py``) resumes from it automatically.

In-loop auto-checkpointing (``spawn_xla(checkpoint_to=...)``) rides on
:class:`AutoCheckpointer`: the engines call :meth:`AutoCheckpointer.maybe`
between supersteps — the quiescent points where the device state is a pure
function of host-visible arrays — and it decides cadence (every N committed
levels or every N seconds).
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import chaos

# v2: fingerprints moved to the Zobrist-form hash (ops/fphash.py) and the
# metadata gained the model-config digest; v1 checkpoints persist fingerprints
# under the old hash and must be rejected, not silently resumed.
# v3: the metadata embeds a payload SHA-256 (``payload_sha256``) and loads
# verify it — a v2 file has no digest to trust, so it is rejected as an
# unsupported format, like v1.
FORMAT_VERSION = 3

#: Payload members of the archive, in digest order. The order is part of the
#: format: the digest is a running hash over these arrays' bytes.
PAYLOAD_KEYS = (
    "key_hi",
    "key_lo",
    "val_hi",
    "val_lo",
    "frontier",
    "frontier_ebits",
)


class CheckpointCorrupt(Exception):
    """A checkpoint file that cannot be trusted: torn/truncated mid-write,
    unreadable as an archive, missing payload members, or failing its
    embedded payload digest. Callers (the supervisor, bench resume) catch
    this and fall back to the previous rotation — see
    :func:`latest_valid_checkpoint`."""


def _normalize(path: str) -> str:
    """np.savez appends '.npz' when absent; normalize both ends so any path
    round-trips. An existing exact FILE (a rotation like ``ck.npz.1``) wins
    over suffix normalization; a directory never does — an extensionless
    save target colliding with a directory name must still resolve to the
    deterministic ``<path>.npz``, not an IsADirectoryError at replace."""
    if path.endswith(".npz") or os.path.isfile(path):
        return path
    return path + ".npz"


def model_digest(model) -> str:
    """A digest of the model's *configuration*, not just its geometry: the
    packed initial states pin every config knob that shapes the transition
    system (field layouts, history presence, actor counts), so a checkpoint
    cannot silently resume into a differently-configured instance of the
    same model class."""
    rows = np.ascontiguousarray(np.asarray(model.packed_init(), dtype=np.uint32))
    h = hashlib.sha256()
    h.update(repr((rows.shape, model.state_words, model.max_actions)).encode())
    h.update(rows.tobytes())
    return h.hexdigest()[:16]


def _payload_digest(arrays: Dict[str, np.ndarray]) -> str:
    """SHA-256 over every payload array's identity (name, shape, dtype) and
    bytes, in :data:`PAYLOAD_KEYS` order — the self-verification the loader
    recomputes."""
    h = hashlib.sha256()
    for key in PAYLOAD_KEYS:
        a = np.ascontiguousarray(arrays[key])
        h.update(key.encode())
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def save_checkpoint(checker, path: str, keep: int = 1) -> None:
    """Writes the checker's logical search state. Valid after any number of
    ``_run_block`` calls (between super-steps the device state is quiescent).

    The write is atomic (temp file + ``os.replace``: a kill mid-save leaves
    the previous file intact, never a torn one) and rotating: with
    ``keep=K > 1`` the previous live file shifts to ``<path>.1`` (``.1`` to
    ``.2``, ...), retaining the last K checkpoints so a corrupt newest
    rotation still leaves a valid fallback."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    # The sharded engine's planes can span non-addressable devices under
    # jax.distributed; its _host_read allgathers them. Single-chip arrays
    # are process-local, so plain np.asarray suffices there.
    read = getattr(checker, "_host_read", np.asarray)
    table = checker._table
    kh = read(table.key_hi)
    kl = read(table.key_lo)
    vh = read(table.val_hi)
    vl = read(table.val_lo)
    occ = (kh != 0) | (kl != 0)

    frontier_rows, frontier_ebits = _live_frontier(checker)

    arrays = {
        "key_hi": kh[occ],
        "key_lo": kl[occ],
        "val_hi": vh[occ],
        "val_lo": vl[occ],
        "frontier": np.asarray(frontier_rows, dtype=np.uint32),
        "frontier_ebits": np.asarray(frontier_ebits, dtype=np.uint32),
    }
    meta = {
        "format_version": FORMAT_VERSION,
        "model": type(checker._model).__name__,
        "init_digest": model_digest(checker._model),
        "state_words": checker._W,
        "max_actions": checker._A,
        "property_names": checker._prop_names,
        # Symmetry identity (stateright_tpu/sym, docs/symmetry.md): the
        # resolved tag — None (off), "spec:<hash12>" (the spec-compiled
        # kernel), or "model:packed_representative". A resume into a
        # DIFFERENT canonicalization would dedup new states against a
        # differently-keyed table, silently corrupting counts, so
        # validate_symmetry fails such resumes typed.
        "symmetry": getattr(checker, "_sym_tag", None),
        "depth": checker._depth,
        "max_depth": checker._max_depth,
        "state_count": checker._state_count,
        "unique_count": checker._unique_count,
        "found_names": {k: int(v) for k, v in checker._found_names.items()},
        "exhausted": checker._exhausted,
        "target_reached": checker._target_reached,
        # is_done() is WIDER than the two flags above (frontier-empty and
        # all-properties-found complete a run without setting either), so
        # completion checks must read this, not re-derive it from flags.
        "done": bool(checker.is_done()),
        "payload_sha256": _payload_digest(arrays),
        "written_unix_ts": time.time(),
    }
    dst = _normalize(path)
    # Same-directory temp (os.replace must not cross filesystems), with a
    # .npz suffix so np.savez does not append its own.
    tmp = f"{dst}.tmp-{os.getpid()}.npz"
    # Sweep temps orphaned by a predecessor killed mid-save — SIGKILL from
    # the watchdog is this system's DESIGNED failure mode, and the
    # finally-unlink below never runs under it. At soak scale each orphan
    # is a multi-GB file; the supervisor never overlaps two live writers
    # on one base path, so any other-pid temp is a dead worker's litter.
    for stale in glob.glob(f"{glob.escape(dst)}.tmp-*.npz"):
        if stale != tmp:
            try:
                os.unlink(stale)
            except OSError:
                pass
    try:
        np.savez_compressed(
            tmp,
            meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
            **arrays,
        )
        if keep > 1 and os.path.exists(dst):
            for i in range(keep - 1, 1, -1):
                older = f"{dst}.{i - 1}"
                if os.path.exists(older):
                    os.replace(older, f"{dst}.{i}")
            os.replace(dst, f"{dst}.1")
        os.replace(tmp, dst)
        inj = chaos.fire("checkpoint.torn", size=os.path.getsize(dst))
        if inj is not None:
            # Deterministic fault injection (stateright_tpu/chaos.py):
            # tear the just-written live rotation at byte ``at`` — the
            # corrupt-newest shape latest_valid_checkpoint falls back
            # from. No-op unless an STPU_CHAOS plan names it.
            chaos.tear_file(dst, inj.get("at", 1))
    finally:
        # Only a failed save leaves the temp behind (success replaced it).
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _live_frontier(checker):
    """The valid frontier rows + ebits, engine-layout-agnostic."""
    from .parallel.sharded import ShardedXlaChecker

    if isinstance(checker, ShardedXlaChecker):
        D, Fl, W = checker._D, checker._Fl, checker._W
        rows = checker._host_read(checker._frontier).reshape(D, Fl, W)
        ebits = checker._host_read(checker._frontier_ebits).reshape(D, Fl)
        counts = checker._host_read(checker._counts)
        live_rows = [rows[d, : counts[d]] for d in range(D)]
        live_ebits = [ebits[d, : counts[d]] for d in range(D)]
        return (
            np.concatenate(live_rows) if live_rows else rows[:0, 0],
            np.concatenate(live_ebits) if live_ebits else ebits[:0, 0],
        )
    n = checker._frontier_count
    return (
        checker._frontier_rows_host(),
        np.asarray(checker._frontier_ebits)[:n],
    )


def _read_archive(path: str):
    """The raw (meta, arrays) of a checkpoint archive; every way a torn or
    foreign file can fail to parse is converted to the typed
    :class:`CheckpointCorrupt` (a missing file stays ``FileNotFoundError``
    — "no checkpoint yet" and "checkpoint destroyed" are different verdicts
    to a supervisor)."""
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            arrays = {k: np.asarray(z[k]) for k in PAYLOAD_KEYS if k in z}
    except FileNotFoundError:
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"{path}: unreadable checkpoint ({type(e).__name__}: {e})"
        ) from e
    return meta, arrays


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Reads a checkpoint into plain host arrays + metadata. Raises
    :class:`CheckpointCorrupt` on a torn/truncated/digest-mismatched file
    (so callers can fall back to the previous rotation) and ``ValueError``
    on a readable file of an unsupported format version."""
    p = _normalize(path)
    meta, arrays = _read_archive(p)
    if meta.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"unsupported checkpoint format {meta.get('format_version')}"
        )
    missing = [k for k in PAYLOAD_KEYS if k not in arrays]
    if missing:
        raise CheckpointCorrupt(f"{p}: missing payload members {missing}")
    digest = _payload_digest(arrays)
    if meta.get("payload_sha256") != digest:
        raise CheckpointCorrupt(
            f"{p}: payload digest mismatch "
            f"({meta.get('payload_sha256')} != {digest}) — torn or tampered"
        )
    return {"meta": meta, **arrays}


def rotations(path: str) -> List[str]:
    """Existing rotation files for ``path``, newest first: the live file,
    then ``.1``, ``.2``, ... (contiguous — the shift in
    :func:`save_checkpoint` never leaves gaps)."""
    p = _normalize(path)
    out = [p] if os.path.exists(p) else []
    i = 1
    while True:
        candidate = f"{p}.{i}"
        if not os.path.exists(candidate):
            break
        out.append(candidate)
        i += 1
    return out


def latest_valid_checkpoint(path: str, *, with_meta: bool = False):
    """The newest rotation of ``path`` that loads and verifies clean, or
    None. This is the supervisor's automatic fallback: a truncated newest
    file is skipped (typed, not crashed on) in favor of the previous
    rotation. ``with_meta=True`` returns ``(path, meta)`` instead —
    verification already paid the full decompress+digest pass, so callers
    that want the meta (bench's resume validation) must not load the
    winning file a second time; misses return ``(None, None)``."""
    for candidate in rotations(path):
        try:
            meta = load_checkpoint(candidate)["meta"]
        except (CheckpointCorrupt, ValueError):
            continue
        return (candidate, meta) if with_meta else candidate
    return (None, None) if with_meta else None


def validate_model(meta: Dict[str, Any], model, prop_names) -> None:
    """A checkpoint is only loadable into the model that wrote it."""
    problems = []
    if meta["model"] != type(model).__name__:
        problems.append(f"model {meta['model']!r} != {type(model).__name__!r}")
    if meta["state_words"] != model.state_words:
        problems.append(
            f"state_words {meta['state_words']} != {model.state_words}"
        )
    if meta["max_actions"] != model.max_actions:
        problems.append(f"max_actions {meta['max_actions']} != {model.max_actions}")
    digest = model_digest(model)
    if meta["init_digest"] != digest:
        problems.append(
            f"model config digest {meta['init_digest']} != {digest} "
            "(same class, different configuration)"
        )
    if meta["property_names"] != list(prop_names):
        problems.append(
            f"properties {meta['property_names']} != {list(prop_names)}"
        )
    if problems:
        raise ValueError(
            "checkpoint does not match this model: " + "; ".join(problems)
        )


def validate_symmetry(meta: Dict[str, Any], sym_tag) -> None:
    """A checkpoint is only loadable into a checker with the SAME
    canonicalization identity (``_sym_tag``): the visited table's keys
    are fingerprints of canonical forms, so resuming under a different
    symmetry config (off vs on, or a changed spec) would silently
    mis-dedup every state inserted after the resume. Checkpoints written
    before the symmetry tier lack the key and skip this check (they
    predate spec kernels, so their canonicalization matches whatever the
    model's packed_representative still computes)."""
    if "symmetry" not in meta:
        return
    if meta["symmetry"] != sym_tag:
        raise ValueError(
            f"checkpoint symmetry mismatch: written with "
            f"{meta['symmetry']!r}, resuming with {sym_tag!r} — a resume "
            f"must keep the same spawn_xla(symmetry=)/STPU_SYMMETRY "
            f"config (and spec) the checkpoint was written under"
        )


def _parse_every(every):
    """Cadence spec -> ``(levels, seconds)`` (exactly one is set). An int
    (or digit string) is committed BFS levels; a string with an ``s``
    suffix is wall-clock seconds (``"45s"``, ``"2.5s"``)."""
    if isinstance(every, bool):
        raise ValueError(f"checkpoint_every must be an int or 'Ns': {every!r}")
    if isinstance(every, int):
        levels = every
        if levels < 1:
            raise ValueError(f"checkpoint_every levels must be >= 1: {levels}")
        return levels, None
    s = str(every).strip()
    if s.endswith("s"):
        seconds = float(s[:-1])
        if seconds <= 0:
            raise ValueError(f"checkpoint_every seconds must be > 0: {s!r}")
        return None, seconds
    try:
        return _parse_every(int(s))
    except ValueError:
        raise ValueError(
            f"checkpoint_every must be an int (levels) or 'Ns' (seconds): "
            f"{every!r}"
        ) from None


class AutoCheckpointer:
    """In-loop auto-checkpoint cadence for the device engines.

    The engines call :meth:`maybe` at every quiescent point (between
    supersteps, after commit bookkeeping); this object decides whether a
    checkpoint is due — every ``checkpoint_every`` committed levels, or
    every that many seconds with an ``"Ns"`` spec — and routes the write
    through ``checker.save_checkpoint`` (which owns the obs span, the
    ``checkpoints_written`` counter, and the ``last_checkpoint`` gauge).
    Cadence is *checked* at dispatch boundaries, so under fused dispatch the
    effective granularity is the dispatch block (up to
    ``levels_per_dispatch`` levels), never mid-device-call.
    """

    #: Default cadence when ``checkpoint_to`` is set without an explicit
    #: ``checkpoint_every``: a wall-clock minute — soak-friendly (bounded
    #: re-exploration after a wedge) without per-level write amplification.
    DEFAULT_EVERY = "60s"
    DEFAULT_KEEP = 3

    def __init__(self, path: str, every=None, keep: Optional[int] = None):
        self.path = path
        self.every_levels, self.every_seconds = _parse_every(
            self.DEFAULT_EVERY if every is None else every
        )
        self.keep = self.DEFAULT_KEEP if keep is None else int(keep)
        if self.keep < 1:
            raise ValueError(f"checkpoint_keep must be >= 1: {self.keep}")
        self._last_depth: Optional[int] = None
        self._last_time: Optional[float] = None

    @classmethod
    def resolve(cls, checkpoint_to, checkpoint_every, checkpoint_keep):
        """The spawn-kwarg/env resolution every engine shares:
        ``checkpoint_to`` (env ``STPU_CHECKPOINT_TO``) arms auto-
        checkpointing; ``checkpoint_every`` (env ``STPU_CHECKPOINT_EVERY``)
        and ``checkpoint_keep`` (env ``STPU_CHECKPOINT_KEEP``) tune it.
        Returns None when off. NOTE: the env path arms EVERY checker in the
        process onto one file — fine for single-checker tools (soak
        workers); multi-checker processes (bench's matrix) must pass
        ``checkpoint_to`` explicitly per checker instead."""
        path = checkpoint_to or os.environ.get("STPU_CHECKPOINT_TO") or None
        if path is None:
            return None
        every = (
            checkpoint_every
            if checkpoint_every is not None
            else os.environ.get("STPU_CHECKPOINT_EVERY") or None
        )
        keep = (
            checkpoint_keep
            if checkpoint_keep is not None
            else os.environ.get("STPU_CHECKPOINT_KEEP") or None
        )
        return cls(path, every, None if keep is None else int(keep))

    def arm(self, depth: int) -> None:
        """Baseline the cadence at the checker's starting point (fresh init
        or restore) — the first interval is measured from here, so a
        just-resumed checker does not immediately rewrite the checkpoint it
        resumed from."""
        self._last_depth = depth
        self._last_time = time.monotonic()

    def due(self, depth: int) -> bool:
        if self._last_depth is None:
            self.arm(depth)
            return False
        if self.every_levels is not None:
            return depth - self._last_depth >= self.every_levels
        return time.monotonic() - self._last_time >= self.every_seconds

    def maybe(self, checker) -> bool:
        """Write a checkpoint if one is due; returns whether it wrote."""
        depth = checker._depth
        if not self.due(depth):
            return False
        checker.save_checkpoint(self.path, keep=self.keep)
        self._last_depth = depth
        self._last_time = time.monotonic()
        return True
