"""Checkpoint/resume for the XLA checkers.

The reference has no checkpointing — a run is memory-resident and
single-shot (SURVEY.md §5). With the visited set resident in device HBM,
host-side checkpointing becomes an explicit feature of this framework: long
checks (or preemptible TPU time) can stop after any super-step and resume
later, on a different chip count.

Format (``np.savez_compressed``): the *logical* search state, independent of
any engine's memory layout —

- the visited set as compacted ``(fingerprint, parent)`` pairs (four uint32
  lanes),
- the frontier as packed state rows + eventually-bit words,
- scalar progress counters and discovery pins,
- model identity metadata (class name + packed geometry), validated on
  restore.

Restoring *rebuilds* the hash table by insertion, so a checkpoint written by
the single-chip engine loads into the sharded engine (and vice versa), and
capacities may differ across save/restore.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

# v2: fingerprints moved to the Zobrist-form hash (ops/fphash.py) and the
# metadata gained the model-config digest; v1 checkpoints persist fingerprints
# under the old hash and must be rejected, not silently resumed.
FORMAT_VERSION = 2


def _normalize(path: str) -> str:
    """np.savez appends '.npz' when absent; normalize both ends so any path
    round-trips."""
    return path if path.endswith(".npz") else path + ".npz"


def model_digest(model) -> str:
    """A digest of the model's *configuration*, not just its geometry: the
    packed initial states pin every config knob that shapes the transition
    system (field layouts, history presence, actor counts), so a checkpoint
    cannot silently resume into a differently-configured instance of the
    same model class."""
    import hashlib

    rows = np.ascontiguousarray(np.asarray(model.packed_init(), dtype=np.uint32))
    h = hashlib.sha256()
    h.update(repr((rows.shape, model.state_words, model.max_actions)).encode())
    h.update(rows.tobytes())
    return h.hexdigest()[:16]


def save_checkpoint(checker, path: str) -> None:
    """Writes the checker's logical search state. Valid after any number of
    ``_run_block`` calls (between super-steps the device state is quiescent).
    """
    # The sharded engine's planes can span non-addressable devices under
    # jax.distributed; its _host_read allgathers them. Single-chip arrays
    # are process-local, so plain np.asarray suffices there.
    read = getattr(checker, "_host_read", np.asarray)
    table = checker._table
    kh = read(table.key_hi)
    kl = read(table.key_lo)
    vh = read(table.val_hi)
    vl = read(table.val_lo)
    occ = (kh != 0) | (kl != 0)

    frontier_rows, frontier_ebits = _live_frontier(checker)

    meta = {
        "format_version": FORMAT_VERSION,
        "model": type(checker._model).__name__,
        "init_digest": model_digest(checker._model),
        "state_words": checker._W,
        "max_actions": checker._A,
        "property_names": checker._prop_names,
        "depth": checker._depth,
        "max_depth": checker._max_depth,
        "state_count": checker._state_count,
        "unique_count": checker._unique_count,
        "found_names": {k: int(v) for k, v in checker._found_names.items()},
        "exhausted": checker._exhausted,
        "target_reached": checker._target_reached,
    }
    np.savez_compressed(
        _normalize(path),
        meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        key_hi=kh[occ],
        key_lo=kl[occ],
        val_hi=vh[occ],
        val_lo=vl[occ],
        frontier=frontier_rows,
        frontier_ebits=frontier_ebits,
    )


def _live_frontier(checker):
    """The valid frontier rows + ebits, engine-layout-agnostic."""
    from .parallel.sharded import ShardedXlaChecker

    if isinstance(checker, ShardedXlaChecker):
        D, Fl, W = checker._D, checker._Fl, checker._W
        rows = checker._host_read(checker._frontier).reshape(D, Fl, W)
        ebits = checker._host_read(checker._frontier_ebits).reshape(D, Fl)
        counts = checker._host_read(checker._counts)
        live_rows = [rows[d, : counts[d]] for d in range(D)]
        live_ebits = [ebits[d, : counts[d]] for d in range(D)]
        return (
            np.concatenate(live_rows) if live_rows else rows[:0, 0],
            np.concatenate(live_ebits) if live_ebits else ebits[:0, 0],
        )
    n = checker._frontier_count
    return (
        checker._frontier_rows_host(),
        np.asarray(checker._frontier_ebits)[:n],
    )


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Reads a checkpoint into plain host arrays + metadata."""
    with np.load(_normalize(path)) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        if meta.get("format_version") != FORMAT_VERSION:
            raise ValueError(
                f"unsupported checkpoint format {meta.get('format_version')}"
            )
        return {
            "meta": meta,
            "key_hi": z["key_hi"],
            "key_lo": z["key_lo"],
            "val_hi": z["val_hi"],
            "val_lo": z["val_lo"],
            "frontier": z["frontier"],
            "frontier_ebits": z["frontier_ebits"],
        }


def validate_model(meta: Dict[str, Any], model, prop_names) -> None:
    """A checkpoint is only loadable into the model that wrote it."""
    problems = []
    if meta["model"] != type(model).__name__:
        problems.append(f"model {meta['model']!r} != {type(model).__name__!r}")
    if meta["state_words"] != model.state_words:
        problems.append(
            f"state_words {meta['state_words']} != {model.state_words}"
        )
    if meta["max_actions"] != model.max_actions:
        problems.append(f"max_actions {meta['max_actions']} != {model.max_actions}")
    digest = model_digest(model)
    if meta["init_digest"] != digest:
        problems.append(
            f"model config digest {meta['init_digest']} != {digest} "
            "(same class, different configuration)"
        )
    if meta["property_names"] != list(prop_names):
        problems.append(
            f"properties {meta['property_names']} != {list(prop_names)}"
        )
    if problems:
        raise ValueError(
            "checkpoint does not match this model: " + "; ".join(problems)
        )
