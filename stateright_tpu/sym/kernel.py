"""Spec-compiled canonicalization kernels (device + bit-exact host twin).

:func:`compile_canon` turns a :class:`~stateright_tpu.sym.spec.SymmetrySpec`
into a ``words[W] -> words[W]`` function the engines vmap over frontier
rows right before fingerprinting. The kernel is a stable odd-even
transposition sorting network over the group's blocks:

- block keys compare lexicographically over the lanes in declaration
  order; a comparator swaps only on STRICT greater-than, which makes the
  adjacent-transposition network a stable sort — bit-identical to the
  host twin's ``sorted(..., key=block_tuple)``;
- a comparator's conditional swap is a pure ``jnp.where`` select over
  the per-lane value vectors (no gather, no scatter — the op class every
  backend lowers reliably, see ``packing._word_update``'s docstring for
  the pinned TPU scatter-drop miscompile this family of kernels must
  avoid);
- reassembly clears each touched word's group bits with a static mask
  and ORs the sorted lane values back at their static shifts, writing
  the word through ``packing._word_update`` at a static index (folds to
  a static update; STPU001's static-index exemption).

Network cost is ``count*(count-1)/2`` comparators per group — counts
here are process counts (<= ~8), so the whole canonicalization fuses
into the superstep for free against the table-scale sorts it shrinks.
"""

from __future__ import annotations

from typing import Any, Callable, List

import numpy as np

from .spec import SymmetrySpec, SymmetryUnsupported


def _comparator_rounds(count: int) -> List[List[int]]:
    """Odd-even transposition schedule: ``count`` rounds of adjacent
    comparator columns (round r compares (i, i+1) for i = r%2, r%2+2, …).
    Returns the left index of each comparator, per round."""
    return [list(range(r % 2, count - 1, 2)) for r in range(count)]


def compile_canon(spec: SymmetrySpec) -> Callable[[Any], Any]:
    """The device kernel: ``canon(words[W]) -> words[W]`` (jnp, traceable,
    vmapped by the engines over frontier rows)."""

    def canon(words):
        import jax.numpy as jnp

        from ..packing import _word_update

        for g in spec.groups:
            n = g.count
            # Extract: one [n] uint32 vector per lane, static shifts/masks.
            vals = []
            for lane in g.lanes:
                mask = jnp.uint32((1 << lane.bits) - 1)
                vals.append(
                    jnp.stack(
                        [
                            (words[w] >> jnp.uint32(s)) & mask
                            for w, s in lane.positions
                        ]
                    )
                )
            # Stable odd-even transposition network: swap on STRICT
            # lexicographic greater-than over the lanes.
            for comparators in _comparator_rounds(n):
                for i in comparators:
                    gt = jnp.bool_(False)
                    eq = jnp.bool_(True)
                    for v in vals:
                        a, b = v[i], v[i + 1]
                        gt = gt | (eq & (a > b))
                        eq = eq & (a == b)
                    new_vals = []
                    for v in vals:
                        a, b = v[i], v[i + 1]
                        lo = jnp.where(gt, b, a)
                        hi = jnp.where(gt, a, b)
                        v = _word_update(v, i, lo)
                        v = _word_update(v, i + 1, hi)
                        new_vals.append(v)
                    vals = new_vals
            # Reassemble: clear the group's bits per touched word (static
            # mask), OR the sorted lane values back at static shifts.
            clear: dict = {}
            contrib: dict = {}
            for lane, v in zip(g.lanes, vals):
                lane_mask = (1 << lane.bits) - 1
                for b, (w, s) in enumerate(lane.positions):
                    clear[w] = clear.get(w, 0) | (lane_mask << s)
                    contrib.setdefault(w, []).append(v[b] << jnp.uint32(s))
            for w in sorted(clear):
                acc = words[w] & jnp.uint32(~clear[w] & 0xFFFFFFFF)
                for piece in contrib[w]:
                    acc = acc | piece
                words = _word_update(words, w, acc)
        return words

    return canon


def canonicalize_host(spec: SymmetrySpec, row: np.ndarray) -> np.ndarray:
    """Bit-exact numpy twin of :func:`compile_canon` for one packed row —
    the engines' host-side fingerprint path and the differential tests'
    oracle. A stable sort by the full block key tuple equals the strict
    greater-than adjacent-transposition network exactly."""
    out = np.array(row, dtype=np.uint32, copy=True)
    for g in spec.groups:
        n = g.count
        blocks = []
        for b in range(n):
            key = tuple(
                (int(out[w]) >> s) & ((1 << lane.bits) - 1)
                for lane in g.lanes
                for w, s in [lane.positions[b]]
            )
            blocks.append(key)
        order = sorted(range(n), key=lambda b: blocks[b])
        for li, lane in enumerate(g.lanes):
            lane_mask = (1 << lane.bits) - 1
            vals = [blocks[b][li] for b in range(n)]
            for new_b, old_b in enumerate(order):
                w, s = lane.positions[new_b]
                out[w] = np.uint32(
                    (int(out[w]) & ~(lane_mask << s)) | (vals[old_b] << s)
                )
    return out


def host_canonicalizer(spec: SymmetrySpec) -> Callable[[np.ndarray], np.ndarray]:
    """Partial application of :func:`canonicalize_host` (the form the
    engines store next to the device kernel)."""

    def canon(row: np.ndarray) -> np.ndarray:
        return canonicalize_host(spec, row)

    return canon


def object_canonicalizer(model) -> Callable[[Any], Any]:
    """An OBJECT-state canonicalizer for the host search engines, derived
    from the model's spec through its own pack/unpack codec — the host
    symmetry oracle the device engines are differentially tested against:

        host = Model(...).checker().symmetry_fn(object_canonicalizer(m))

    explores exactly the classes ``spawn_xla`` + spec symmetry visits
    (class-invariant canon => traversal-order-independent counts)."""
    spec = getattr(model, "symmetry_spec", None)
    if spec is None:
        raise SymmetryUnsupported(
            "object_canonicalizer",
            f"{type(model).__name__} ships no symmetry_spec",
        )

    def canon(state):
        row = np.asarray(model.pack(state), dtype=np.uint32)
        return model.unpack(canonicalize_host(spec, row))

    return canon
