"""Resolution of the ``spawn_xla(symmetry=)`` / ``STPU_SYMMETRY`` surface.

Modes (arg wins over env; env default ``"auto"``):

- ``"auto"`` — honor the builder: symmetry is on iff the checker was
  built with ``.symmetry()`` / ``.symmetry_fn()``. A model that ships a
  ``symmetry_spec`` then canonicalizes through the spec-compiled kernel
  automatically (no hand-written per-model device code).
- ``"on"`` / ``1`` / ``True`` — force symmetry on, builder or not (the
  env form makes any model CLI's ``check`` symmetry-reduced:
  ``STPU_SYMMETRY=1 python -m stateright_tpu.models.two_phase_commit
  check 5``). Requires the model to ship a spec or a
  ``packed_representative``; otherwise :class:`SymmetryUnsupported`.
- ``"off"`` / ``0`` / ``False`` — force symmetry off (the A/B knob; an
  explicit user choice, so a ``.symmetry()`` builder runs full-space).

When enabled, the kernel is chosen by capability:

1. ``model.symmetry_spec`` (a :class:`SymmetrySpec`) — the compiled
   class-invariant canonicalization kernel; tag ``spec:<hash12>``.
2. ``model.packed_representative`` — the model's hand-written kernel
   (may be a partial canonicalization; counts are then traversal-order
   dependent, see docs/symmetry.md); tag ``model:packed_representative``.
3. neither — :class:`SymmetryUnsupported` naming the engine (the old
   behavior silently fell back to full-space exploration on some paths;
   pinned as a regression in tests/test_symmetry.py).
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple, Optional

from .kernel import compile_canon, host_canonicalizer
from .spec import SymmetrySpec, SymmetryUnsupported


class ResolvedSymmetry(NamedTuple):
    """What the engine stores: ``enabled``; ``tag`` (the cache/checkpoint
    identity: None when off, ``spec:<hash12>`` or
    ``model:packed_representative`` when on); the device kernel; and the
    host-row canonicalizer (None on the packed_representative path,
    which round-trips through the object ``representative()``)."""

    enabled: bool
    tag: Optional[str]
    device_canon: Optional[Callable[[Any], Any]]
    host_canon: Optional[Callable[[Any], Any]]


OFF = ResolvedSymmetry(False, None, None, None)

_ON = ("on", "1", "true", "yes")
_OFF = ("off", "0", "false", "no")


def _mode(symmetry) -> str:
    if symmetry is None:
        symmetry = os.environ.get("STPU_SYMMETRY", "auto")
    if symmetry is True:
        return "on"
    if symmetry is False:
        return "off"
    s = str(symmetry).strip().lower()
    if s in _ON:
        return "on"
    if s in _OFF:
        return "off"
    if s in ("auto", ""):
        return "auto"
    raise ValueError(
        f"symmetry must be auto/on/off (STPU_SYMMETRY), got {symmetry!r}"
    )


def resolve_symmetry(
    symmetry, builder_requested: bool, model, engine: str
) -> ResolvedSymmetry:
    """Resolve the knob for one engine instance (see module docstring).
    ``builder_requested`` is whether the CheckerBuilder carries a
    ``.symmetry()`` / ``.symmetry_fn()`` request; ``engine`` names the
    caller for the typed refusal."""
    mode = _mode(symmetry)
    enabled = builder_requested if mode == "auto" else (mode == "on")
    if not enabled:
        return OFF
    spec = getattr(model, "symmetry_spec", None)
    if spec is not None:
        if not isinstance(spec, SymmetrySpec):
            raise SymmetryUnsupported(
                engine,
                f"{type(model).__name__}.symmetry_spec is "
                f"{type(spec).__name__}, expected SymmetrySpec",
            )
        if spec.max_word >= model.state_words:
            raise SymmetryUnsupported(
                engine,
                f"{type(model).__name__}.symmetry_spec touches word "
                f"{spec.max_word} but state_words={model.state_words}",
            )
        return ResolvedSymmetry(
            True,
            f"spec:{spec.spec_hash()[:12]}",
            compile_canon(spec),
            host_canonicalizer(spec),
        )
    if hasattr(model, "packed_representative"):
        return ResolvedSymmetry(
            True, "model:packed_representative",
            model.packed_representative, None,
        )
    raise SymmetryUnsupported(
        engine,
        f"{type(model).__name__} ships neither a symmetry_spec nor "
        f"packed_representative (actor-framework and register models "
        f"embed block references in message/history fields, which a "
        f"block permutation alone cannot rewrite)",
    )
