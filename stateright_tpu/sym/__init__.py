"""Device-side symmetry reduction (docs/symmetry.md).

The host tier reduces symmetric state spaces through object-level
``representative()`` methods (``checker/builder.py symmetry()``,
``utils/rewrite_plan.py`` — the reference's ``representative.rs`` /
``rewrite_plan.rs``). This package is the packed-tier analogue: a
declarative per-model :class:`SymmetrySpec` names the role-symmetric
process blocks in the packed word layout (field group, block count,
block bit-width — the same declaration style ``packing.py`` uses for
fields), and :func:`compile_canon` compiles it into a fixed, vmapped,
**scatter-free** canonicalization kernel — a stable odd-even
transposition sorting network over block keys whose conditional block
swaps are pure ``jnp.where`` selects, reassembled into words via
``packing._word_update`` at static indices (STPU001-clean by
construction: no data-dependent scatter, no gather, rows-in layout,
no fused transpose).

The kernel is applied to each frontier row immediately before
fingerprinting in both device engines (``xla.py`` — inside the fused
superstep, zero extra dispatches — and ``checker/device_on_demand.py``)
and in the sharded mesh superstep (shard routing hashes the
representative). Because every lane of a block participates in the sort
key, the canonical form is a PERFECT (class-invariant) canonicalizer:
visited-representative counts are traversal-order-independent and
bit-equal across engines and dedup backends.

Surface: ``spawn_xla(symmetry=)`` / ``STPU_SYMMETRY`` (see
:func:`resolve_symmetry`); paths that cannot honor an enabled symmetry
raise :class:`SymmetryUnsupported` instead of silently exploring the
full space.
"""

from .spec import BlockGroup, Lane, SymmetrySpec, SymmetryUnsupported
from .kernel import canonicalize_host, compile_canon, object_canonicalizer
from .resolve import resolve_symmetry

__all__ = [
    "BlockGroup",
    "Lane",
    "SymmetrySpec",
    "SymmetryUnsupported",
    "canonicalize_host",
    "compile_canon",
    "object_canonicalizer",
    "resolve_symmetry",
]
