"""Declarative symmetry specs over packed word layouts.

A :class:`SymmetrySpec` names the role-symmetric process blocks of a
packed model — which bitfields make up one block, how many
interchangeable blocks there are, and where each block's copy of each
field lives in the word vector — in the same declarative style
``packing.py`` uses for fields. ``sym/kernel.py`` compiles a spec into
the device canonicalization kernel and its bit-exact host twin.

Soundness contract (docs/symmetry.md): the named blocks must be FULLY
interchangeable — permuting the blocks of a state (and nothing else)
always yields a behaviorally equivalent state — and every bit of
per-block data must be covered by some lane, because every lane
participates in the sort key. That makes the canonical form
class-invariant (a "perfect" canonicalizer): two states in the same
orbit map to the same representative, so reduced counts are
traversal-order-independent. Blocks whose fields embed *references* to
other blocks (actor ids in message payloads, per-thread prerequisite
indices in history fields) are NOT expressible as a plain block
permutation — such models must not ship a spec; enabling symmetry on
them raises :class:`SymmetryUnsupported` instead.
"""

from __future__ import annotations

import hashlib
from typing import NamedTuple, Optional, Sequence, Tuple


class SymmetryUnsupported(TypeError):
    """An engine or path cannot honor the requested symmetry reduction.

    Raised instead of silently exploring the full state space (or,
    worse, silently producing an unsound reduction). ``engine`` names
    the refusing engine/path; ``reason`` says what is missing.
    """

    def __init__(self, engine: str, reason: str):
        self.engine = engine
        self.reason = reason
        super().__init__(f"symmetry reduction under {engine}: {reason}")


class Lane(NamedTuple):
    """One per-block bitfield: ``positions[b]`` is the static
    ``(word, shift)`` of block ``b``'s copy; all copies are ``bits``
    wide. Every lane participates in the block sort key, in declaration
    order (earlier lanes are more significant)."""

    name: str
    bits: int
    positions: Tuple[Tuple[int, int], ...]


class BlockGroup(NamedTuple):
    """``count`` interchangeable blocks, each made of ``lanes``."""

    name: str
    count: int
    lanes: Tuple[Lane, ...]


class SymmetrySpec:
    """The symmetry declaration a packed model ships as its
    ``symmetry_spec`` attribute."""

    def __init__(self, groups: Sequence[BlockGroup], *, name: str = "sym"):
        self.name = name
        self.groups: Tuple[BlockGroup, ...] = tuple(groups)
        self._validate()

    # --- construction helpers --------------------------------------------

    @staticmethod
    def lane(
        name: str,
        bits: int,
        *,
        word: Optional[int] = None,
        shift0: int = 0,
        stride: Optional[int] = None,
        count: Optional[int] = None,
        positions: Optional[Sequence[Tuple[int, int]]] = None,
    ) -> Lane:
        """A lane either from explicit ``positions`` or from a strided
        run inside one word: block ``b`` at ``(word, shift0 + b*stride)``
        (``stride`` defaults to ``bits``)."""
        if positions is None:
            if word is None or count is None:
                raise ValueError(
                    f"lane {name!r}: give positions= or word=/count="
                )
            step = bits if stride is None else stride
            positions = [(word, shift0 + b * step) for b in range(count)]
        return Lane(name, bits, tuple((int(w), int(s)) for w, s in positions))

    @classmethod
    def from_layout(
        cls,
        layout,
        fields: Sequence[str],
        *,
        count: Optional[int] = None,
        group: str = "procs",
        name: str = "sym",
    ) -> "SymmetrySpec":
        """Spec over a :class:`packing.Layout`: each named ARRAY field
        becomes one lane, block ``b`` = element ``b`` of every field.
        This is the declaration path for models built on
        ``LayoutBuilder`` (increment, increment_lock); hand-rolled
        layouts use :meth:`lane` with explicit positions."""
        lanes = []
        n = count
        for fname in fields:
            f = layout.fields[fname]
            if not f.is_array:
                raise ValueError(
                    f"symmetry lane {fname!r} must be an array field "
                    f"(one element per block)"
                )
            if n is None:
                n = f.count
            if f.count < n:
                raise ValueError(
                    f"symmetry lane {fname!r} has {f.count} elements, "
                    f"need {n} (one per block)"
                )
            positions = [
                (f.word + b // f.epw, (b % f.epw) * f.bits) for b in range(n)
            ]
            lanes.append(Lane(fname, f.bits, tuple(positions)))
        return cls([BlockGroup(group, n or 0, tuple(lanes))], name=name)

    # --- validation --------------------------------------------------------

    def _validate(self) -> None:
        if not self.groups:
            raise ValueError("SymmetrySpec needs at least one block group")
        covered = {}
        for g in self.groups:
            if g.count < 2:
                raise ValueError(
                    f"group {g.name!r}: count must be >= 2, got {g.count}"
                )
            if not g.lanes:
                raise ValueError(f"group {g.name!r} has no lanes")
            for lane in g.lanes:
                if not 1 <= lane.bits <= 32:
                    raise ValueError(
                        f"lane {g.name}.{lane.name}: bits must be 1..32"
                    )
                if len(lane.positions) != g.count:
                    raise ValueError(
                        f"lane {g.name}.{lane.name}: {len(lane.positions)} "
                        f"positions for {g.count} blocks"
                    )
                for b, (w, s) in enumerate(lane.positions):
                    if w < 0 or s < 0 or s + lane.bits > 32:
                        raise ValueError(
                            f"lane {g.name}.{lane.name} block {b}: "
                            f"(word={w}, shift={s}, bits={lane.bits}) "
                            f"does not fit a uint32 word"
                        )
                    for bit in range(s, s + lane.bits):
                        key = (w, bit)
                        if key in covered:
                            raise ValueError(
                                f"lane {g.name}.{lane.name} block {b} "
                                f"overlaps {covered[key]} at word {w} "
                                f"bit {bit}"
                            )
                        covered[key] = f"{g.name}.{lane.name}[{b}]"

    # --- identity ----------------------------------------------------------

    @property
    def max_word(self) -> int:
        """Highest word index any lane touches (engine W bound check)."""
        return max(
            w for g in self.groups for ln in g.lanes for w, _ in ln.positions
        )

    def canonical_repr(self) -> str:
        return repr(
            [
                (g.name, g.count, [(ln.name, ln.bits, ln.positions) for ln in g.lanes])
                for g in self.groups
            ]
        )

    def spec_hash(self) -> str:
        """Stable content hash — the checkpoint/cache identity of this
        spec (a resumed run with a DIFFERENT spec would dedup against a
        differently-canonicalized table, silently corrupting counts, so
        checkpoints record this and mismatches fail typed)."""
        return hashlib.sha256(self.canonical_repr().encode()).hexdigest()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"SymmetrySpec({self.canonical_repr()}, name={self.name!r})"
