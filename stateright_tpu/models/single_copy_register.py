"""Single-copy register: each server exposes a rewritable register with no
consensus between servers.

Mirrors ``/root/reference/examples/single-copy-register.rs``: the system is
linearizable iff there is exactly one server (one copy); with two or more
servers clients can observe stale values and the ``linearizable`` property
yields a counterexample.

Exact-count oracles from the reference's own test
(single-copy-register.rs:110,136): 93 unique states at 2 clients / 1 server
(full coverage), 20 unique states at 2 clients / 2 servers (BFS stops at the
linearizability counterexample).

The reference's ``Value::default()`` (``'\\u{0}'``) is rendered as ``None``:
the "unwritten" register value, consistent with the ``Register(None)`` spec
initial state used throughout this package.
"""

from __future__ import annotations

from typing import Optional

from ..actor import Actor, ActorModel, Id, Network, Out, StateRef
from ..actor import register as reg
from ..core import Expectation
from ..packing import PackedModelAdapter, bits_for
from ..semantics import LinearizabilityTester
from ..semantics.register import Register


class SingleCopyActor(Actor):
    """A server holding one unreplicated register value
    (single-copy-register.rs:18-46). The actor state *is* the value."""

    def on_start(self, id: Id, out: Out):
        return None  # the unwritten value (Value::default())

    def on_msg(self, id: Id, state: StateRef, src: Id, msg, out: Out) -> None:
        if isinstance(msg, reg.Put):
            state.set(msg.value)
            out.send(src, reg.PutOk(msg.request_id))
        elif isinstance(msg, reg.Get):
            out.send(src, reg.GetOk(msg.request_id, state.get()))
        # Internal messages don't exist for this protocol; anything else is
        # ignored (a no-op action, suppressed by the model).


def single_copy_register_model(
    client_count: int = 2,
    server_count: int = 1,
    network: Optional[Network] = None,
    consistency: str = "linearizable",
) -> ActorModel:
    """Build the checkable model (single-copy-register.rs:55-86).

    ``consistency`` selects the tester riding in the history:
    ``"linearizable"`` (the reference's configuration) or
    ``"sequential"`` — the same protocol checked against
    ``SequentialConsistencyTester`` (sequential_consistency.rs:53-241),
    which the reference defines but never wires into an example.
    """
    if network is None:
        network = Network.new_unordered_nonduplicating()
    if consistency == "linearizable":
        tester, prop_name = LinearizabilityTester(Register(None)), "linearizable"
    elif consistency == "sequential":
        from ..semantics.sequential_consistency import SequentialConsistencyTester

        tester = SequentialConsistencyTester(Register(None))
        prop_name = "sequentially consistent"
    else:
        raise ValueError(f"unknown consistency {consistency!r}")

    model = ActorModel(cfg=None, init_history=tester)
    for _ in range(server_count):
        model.actor(SingleCopyActor())
    for _ in range(client_count):
        model.actor(reg.RegisterClient(put_count=1, server_count=server_count))
    return (
        model.init_network(network)
        .property(Expectation.ALWAYS, prop_name, reg.linearizable_condition())
        .property(Expectation.SOMETIMES, "value chosen", reg.value_chosen_condition)
        .record_msg_in(reg.record_returns)
        .record_msg_out(reg.record_invocations)
    )


class PackedSingleCopyRegister(reg.PackedClientsMixin, PackedModelAdapter):
    """The single-copy register on the device engine (``spawn_xla``) — the
    first packed model carrying a **consistency tester** in its state
    (SURVEY §7 M4 variant (a)).

    Everything is declared through :mod:`stateright_tpu.packing`:

    - per-server register values and per-client script positions are plain
      layout fields;
    - the non-duplicating multiset network packs as per-envelope counts
      over the *closed* envelope universe of this protocol (each client
      performs one Put then one Get with statically known request ids and
      targets, register.rs:94-260, so the universe is tiny);
    - the ``LinearizabilityTester`` history packs exactly via
      :class:`~stateright_tpu.packing.BoundedHistory` (2 ops/client).

    The consistency property (``linearizable``, or ``sequentially
    consistent`` under ``consistency="sequential"``) is checked on device
    via the static interleaving enumeration
    (:mod:`stateright_tpu.semantics.device`, SURVEY §7 M4 variant (b)) —
    EXACTLY while the client count keeps the enumeration under
    ``MAX_PATTERNS_EXACT`` (<= 4 clients at 2 ops each; the pattern axis
    chunks under ``lax.scan`` past the single-shot budget); beyond that
    the model declares ``host_verified_properties`` and the device runs a
    diverse sampled one-sided pass with exact host confirmation of flagged
    rows (variant (a)). With one server the model reaches full coverage (93
    unique states at 2 clients, single-copy-register.rs:110); with two
    servers the stale-read counterexample is found on device
    (single-copy-register.rs:136).
    """

    #: Per-client op bound (one Put then one Get): sizes the packed history
    #: AND the exact-vs-sampled gate below — one constant, one contract.
    MAX_OPS = 2

    def __init__(
        self,
        client_count: int = 2,
        server_count: int = 1,
        consistency: str = "linearizable",
        device_exact: Optional[bool] = None,
        pattern_limit: int = 20_000,
    ):
        from ..actor.network import Envelope
        from ..packing import BoundedHistory, LayoutBuilder, OverflowError32
        from ..semantics.device import MAX_PATTERNS_EXACT, pattern_count
        from ..semantics.register import Read, ReadOk, Write, WriteOk

        self._inner = single_copy_register_model(
            client_count, server_count, consistency=consistency
        )
        self._consistency = consistency
        self._prop_name = self._inner.properties()[0].name
        # Device-exact serialization checking scales to the interleaving
        # budget (chunked lax.scan past the single-shot lane limit); past
        # it — or with ``device_exact=False`` — the property runs as a
        # conservative device pass (a diverse pattern subsample — True
        # proves serializability) with exact host confirmation of the
        # flagged remainder: the engine's host_verified_properties path
        # (xla.py M4 variant (a)).
        P = pattern_count(client_count, self.MAX_OPS)
        if device_exact is None:
            device_exact = P <= MAX_PATTERNS_EXACT
        elif device_exact and P > MAX_PATTERNS_EXACT:
            raise ValueError(
                f"{P} interleavings exceed the exact device budget "
                "(semantics.device.MAX_PATTERNS_EXACT)"
            )
        if not device_exact:
            self.host_verified_properties = frozenset({self._prop_name})
            # The sampled pass's pattern budget is the cliff's tuning
            # knob (VERDICT r4 weak #6): more sampled patterns = fewer
            # device false alarms (host confirmations) but a bigger
            # compile and a wider per-level pipeline. tools/hv_cliff.py
            # characterizes the trade; 20k is the shipped default.
            self._pattern_limit = pattern_limit
        else:
            self._pattern_limit = None
        S, C = server_count, client_count
        self.S, self.C = S, C
        self.values = self._client_values()
        V = len(self.values)
        self.V = V

        # Closed envelope universe: per client k (abs id i = S+k), block of
        # 3 + V codes: Put, PutOk, Get, GetOk(value) per value.
        self._B = 3 + V
        envs = []
        for k in range(C):
            i = S + k
            envs.append(Envelope(Id(i), Id(i % S), reg.Put(1 * i, self.values[1 + k])))
            envs.append(Envelope(Id(i % S), Id(i), reg.PutOk(1 * i)))
            envs.append(Envelope(Id(i), Id((i + 1) % S), reg.Get(2 * i)))
            for v in self.values:
                envs.append(Envelope(Id((i + 1) % S), Id(i), reg.GetOk(2 * i, v)))
        self._envs = envs
        self._env_code = {env: c for c, env in enumerate(envs)}
        U = len(envs)
        self._U = U

        value_bits = bits_for(V - 1)
        op_ret_bits = max(V.bit_length(), 2)
        b = LayoutBuilder().array("srv", S, value_bits)
        self._client_layout(b)
        b.array("net", U, 2)
        self._hist = BoundedHistory(
            b,
            thread_ids=[Id(S + k) for k in range(C)],
            max_ops=self.MAX_OPS,
            op_bits=op_ret_bits,
            ret_bits=op_ret_bits,
            real_time=consistency == "linearizable",
        )
        self._layout = b.finish()
        self._hist.bind(self._layout)
        self.state_words = self._layout.words
        self.max_actions = U

        # History op/ret codes over the closed value universe.
        def op_code(op):
            if isinstance(op, Read):
                return 0
            return 1 + self.values.index(op.value)

        def code_op(c):
            return Read() if c == 0 else Write(self.values[c - 1])

        def ret_code(ret):
            if isinstance(ret, WriteOk):
                return 0
            return 1 + self.values.index(ret.value)

        def code_ret(c):
            return WriteOk() if c == 0 else ReadOk(self.values[c - 1])

        self._op_code, self._code_op = op_code, code_op
        self._ret_code, self._code_ret = ret_code, code_ret
        self._OverflowError32 = OverflowError32

    # --- codec -------------------------------------------------------------

    def pack(self, state) -> "np.ndarray":
        import numpy as np

        S, C = self.S, self.C
        srv = [self.values.index(state.actor_states[s]) for s in range(S)]
        fields = dict(srv=srv)
        self._pack_clients(fields, state)
        net = [0] * self._U
        for env, count in state.network.counts.items():
            code = self._env_code.get(env)
            if code is None:
                raise self._OverflowError32(f"envelope outside universe: {env!r}")
            if count > 3:
                raise self._OverflowError32(f"envelope count {count} > 3: {env!r}")
            net[code] = count
        fields["net"] = net
        fields.update(self._hist.from_tester(state.history, self._op_code, self._ret_code))
        return self._layout.pack(**fields)

    def unpack(self, words):
        from ..actor.model_state import ActorModelState
        from ..actor.network import UnorderedNonDuplicatingNetwork
        from ..actor.timers import Timers
        from ..semantics import LinearizabilityTester
        from ..semantics.register import Register
        from ..semantics.sequential_consistency import SequentialConsistencyTester

        f = self._layout.unpack(words)
        S, C = self.S, self.C
        actor_states = [self.values[code] for code in f["srv"]]
        self._unpack_clients(f, actor_states)
        counts = {
            self._envs[code]: count for code, count in enumerate(f["net"]) if count
        }
        make_tester = (
            (lambda: LinearizabilityTester(Register(None)))
            if self._consistency == "linearizable"
            else (lambda: SequentialConsistencyTester(Register(None)))
        )
        history = self._hist.to_tester(
            f, make_tester, self._code_op, self._code_ret
        )
        return ActorModelState(
            actor_states=tuple(actor_states),
            network=UnorderedNonDuplicatingNetwork(counts),
            timers_set=tuple(Timers() for _ in range(S + C)),
            history=history,
        )

    # --- device kernels -----------------------------------------------------

    def _net_dec(self, words, code):
        L = self._layout
        return L.set(words, "net", L.get(words, "net", code) - 1, code)

    def _net_inc(self, words, code):
        """Increment an envelope count; returns (words', overflow)."""
        import jax.numpy as jnp

        L = self._layout
        cnt = L.get(words, "net", code)
        return L.set(words, "net", cnt + 1, code), cnt == jnp.uint32(3)

    def packed_step(self, words):
        """Full action fan-out: deliver each universe envelope. No-op
        deliveries (script mismatches, model.rs:286-289) are masked
        invalid; capacity overflows are reported on the third output."""
        import jax.numpy as jnp

        L = self._layout
        S, C, V, B = self.S, self.C, self.V, self._B
        u32 = jnp.uint32

        nxt, valid, ovf = [], [], []
        for k in range(C):
            i = S + k
            base = k * B
            deliverable = lambda code: L.get(words, "net", code) > 0  # noqa: E731

            # Put -> server i%S: store the value, reply PutOk.
            code = base + 0
            w = self._net_dec(words, code)
            w = L.set(w, "srv", 1 + k, i % S)
            w, o = self._net_inc(w, base + 1)
            nxt.append(w)
            valid.append(deliverable(code))
            ovf.append(o)

            # PutOk -> client: record WriteOk return, invoke Read, send Get.
            code = base + 1
            eligible = L.get(words, "cl_await", k) == u32(1)
            w = self._net_dec(words, code)
            w = L.set(w, "cl_await", 2, k)
            w = L.set(w, "cl_ops", 2, k)
            w, o1 = self._hist.on_return(w, k, u32(0))  # WriteOk
            w = self._hist.on_invoke(w, k, u32(0))  # Read
            w, o2 = self._net_inc(w, base + 2)
            nxt.append(w)
            valid.append(deliverable(code) & eligible)
            ovf.append(o1 | o2)

            # Get -> server (i+1)%S: reply GetOk with the current value
            # (a traced index into the GetOk block of the universe).
            code = base + 2
            srv_val = L.get(words, "srv", (i + 1) % S)
            w = self._net_dec(words, code)
            w, o = self._net_inc(w, base + 3 + srv_val.astype(jnp.int32))
            nxt.append(w)
            valid.append(deliverable(code))
            ovf.append(o)

            # GetOk(value) -> client: record ReadOk return; script complete.
            for vi in range(V):
                code = base + 3 + vi
                eligible = L.get(words, "cl_await", k) == u32(2)
                w = self._net_dec(words, code)
                w = L.set(w, "cl_await", 0, k)
                w = L.set(w, "cl_ops", 3, k)
                w, o = self._hist.on_return(w, k, u32(1 + vi))  # ReadOk(value)
                nxt.append(w)
                valid.append(deliverable(code) & eligible)
                ovf.append(o)

        valid = jnp.stack(valid)
        return jnp.stack(nxt), valid, jnp.stack(ovf) & valid

    def packed_properties(self, words):
        """[serializable, value chosen] — order of ``properties()``. The
        first is the serialization check for the configured consistency
        model: device-EXACT while the interleaving count fits, or the
        diverse-subsample conservative predicate under
        ``host_verified_properties`` beyond (see ``__init__``)."""
        import jax.numpy as jnp

        L = self._layout
        if self._consistency == "linearizable":
            lin = self.device_linearizable_register(words, self._pattern_limit)
        else:
            lin = self.device_sequentially_consistent_register(
                words, self._pattern_limit
            )

        chosen = jnp.bool_(False)
        for k in range(self.C):
            for vi in range(1, self.V):  # real (written) values only
                chosen = chosen | (L.get(words, "net", k * self._B + 3 + vi) > 0)
        return jnp.stack([lin, chosen])


class PackedSingleCopyRegisterOrdered(reg.PackedClientsMixin, PackedModelAdapter):
    """The single-copy register over the **ordered** network on the device
    engine: the packed form of per-directed-pair FIFO channels where only
    flow heads are deliverable (network.rs:57-67, 221-293), encoded with
    :class:`~stateright_tpu.packing.FifoLanes`.

    One lane per directed flow: ``k`` = client k -> the server (codes
    0 = Put, 1 = Get), ``C + k`` = server -> client k (codes 0 = PutOk,
    1 + v = GetOk(values[v])). An action slot is a lane, not an envelope:
    delivering pops the head; a head whose delivery is a no-op (a reply the
    client is not awaiting) blocks its lane exactly like the object model's
    head-of-channel-only rule. The reference has no exact-count oracle for
    this configuration (its tests use unordered networks; ``bench.sh`` runs
    the ordered config as a benchmark), so parity is engine-vs-engine:
    differential action-level tests against this package's object
    ``OrderedNetwork`` model.
    """

    def __init__(self, client_count: int = 2):
        from ..packing import (
            BoundedHistory,
            FifoLanes,
            LayoutBuilder,
            OverflowError32,
            bits_for,
        )

        if client_count != 2:
            raise ValueError(
                "the packed model's exact device linearizability covers the "
                "2-client shape; other sizes run on the host engines"
            )
        C, S = client_count, 1
        self.C, self.S = C, S
        self._inner = single_copy_register_model(C, S, Network.new_ordered())
        self._OverflowError32 = OverflowError32
        self.values = self._client_values()
        NV = len(self.values)
        self.NV = NV
        self.max_actions = 2 * C  # one action slot per lane

        b = LayoutBuilder()
        b.array("srv", S, bits_for(NV - 1))
        self._client_layout(b)
        # Lane k: client k -> server; lane C+k: server -> client k. Depth 2
        # is headroom: the Put/Get script keeps at most one message in
        # flight per direction (overflow reports loudly regardless).
        self._lanes = FifoLanes(b, "flows", lanes=2 * C, depth=2, code_bits=bits_for(NV))
        code_bits = bits_for(NV)
        self._hist = BoundedHistory(
            b,
            thread_ids=[Id(S + k) for k in range(C)],
            max_ops=2,
            op_bits=code_bits,
            ret_bits=code_bits,
        )
        self._layout = b.finish()
        self._hist.bind(self._layout)
        self._lanes.bind(self._layout)
        self.state_words = self._layout.words

        codecs = reg.history_codecs(self.values)
        self._op_code, self._code_op, self._ret_code, self._code_ret = codecs

    # --- lane codec ---------------------------------------------------------

    def _lane_key(self, lane: int):
        C, S = self.C, self.S
        if lane < C:
            return (Id(S + lane), Id(0))
        return (Id(0), Id(S + (lane - C)))

    def _msg_code(self, lane: int, msg) -> int:
        k = lane if lane < self.C else lane - self.C
        i = self.S + k
        if lane < self.C:  # client -> server
            if isinstance(msg, reg.Put) and msg == reg.Put(i, self.values[1 + k]):
                return 0
            if isinstance(msg, reg.Get) and msg == reg.Get(2 * i):
                return 1
        else:  # server -> client
            if isinstance(msg, reg.PutOk) and msg == reg.PutOk(i):
                return 0
            if isinstance(msg, reg.GetOk) and msg.request_id == 2 * i:
                return 1 + self._val_code(msg.value)
        raise self._OverflowError32(f"message outside universe on lane {lane}: {msg!r}")

    def _code_msg(self, lane: int, code: int):
        k = lane if lane < self.C else lane - self.C
        i = self.S + k
        if lane < self.C:
            return reg.Put(i, self.values[1 + k]) if code == 0 else reg.Get(2 * i)
        if code == 0:
            return reg.PutOk(i)
        return reg.GetOk(2 * i, self.values[code - 1])

    # --- codec -------------------------------------------------------------

    def pack(self, state):
        C = self.C
        fields: dict = {"srv": [self._val_code(state.actor_states[0])]}
        self._pack_clients(fields, state)
        cells = [0] * (2 * C * self._lanes.depth)
        lens = [0] * (2 * C)
        flows = dict(state.network.flows)
        for lane in range(2 * C):
            msgs = flows.pop(self._lane_key(lane), ())
            lane_cells, n = self._lanes.host_pack_lane(
                [self._msg_code(lane, m) for m in msgs]
            )
            cells[lane * self._lanes.depth : (lane + 1) * self._lanes.depth] = lane_cells
            lens[lane] = n
        if flows:
            raise self._OverflowError32(f"flows outside universe: {list(flows)!r}")
        fields["flows_cells"] = cells
        fields["flows_lens"] = lens
        fields.update(
            self._hist.from_tester(state.history, self._op_code, self._ret_code)
        )
        return self._layout.pack(**fields)

    def unpack(self, words):
        from ..actor.model_state import ActorModelState
        from ..actor.network import OrderedNetwork
        from ..actor.timers import Timers
        from ..semantics import LinearizabilityTester
        from ..semantics.register import Register

        f = self._layout.unpack(words)
        C, S = self.C, self.S
        actor_states = [self.values[f["srv"][0]]]
        self._unpack_clients(f, actor_states)
        flows = {}
        for lane in range(2 * C):
            n = f["flows_lens"][lane]
            cells = f["flows_cells"][
                lane * self._lanes.depth : lane * self._lanes.depth + n
            ]
            if n:
                flows[self._lane_key(lane)] = tuple(
                    self._code_msg(lane, c - 1) for c in cells
                )
        history = self._hist.to_tester(
            f,
            lambda: LinearizabilityTester(Register(None)),
            self._code_op,
            self._code_ret,
        )
        return ActorModelState(
            actor_states=tuple(actor_states),
            network=OrderedNetwork(flows),
            timers_set=tuple(Timers() for _ in range(S + C)),
            history=history,
        )

    # --- device kernels -----------------------------------------------------

    def packed_step(self, words):
        """One action slot per lane: deliver its head (or mask the slot
        invalid when the lane is empty / the head's delivery is a no-op)."""
        import jax
        import jax.numpy as jnp

        C = self.C
        to_server = jax.vmap(self._body_to_server, in_axes=(None, 0, 0))(
            words,
            jnp.arange(C, dtype=jnp.uint32),
            jnp.asarray([[k, C + k] for k in range(C)], jnp.uint32),
        )
        to_client = jax.vmap(self._body_to_client, in_axes=(None, 0, 0))(
            words,
            jnp.arange(C, dtype=jnp.uint32),
            jnp.asarray([[C + k, k] for k in range(C)], jnp.uint32),
        )
        nxt = jnp.concatenate([to_server[0], to_client[0]])
        valid = jnp.concatenate([to_server[1], to_client[1]])
        ovf = jnp.concatenate([to_server[2], to_client[2]])
        return nxt, valid, ovf & valid

    def _body_to_server(self, words, k, prm):
        """Head of client k's lane -> the server: Put stores the value and
        acks; Get replies with the current value (single-copy-register.rs:
        18-46). Always valid when nonempty — the server never no-ops."""
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        lane, reply_lane = prm[0], prm[1]
        code, nonempty = self._lanes.head(words, lane)
        w = self._lanes.pop(words, lane, enabled=nonempty)
        is_put = code == 0
        srv_val = L.get(words, "srv", 0)
        w = L.set(w, "srv", jnp.where(is_put & nonempty, k + u32(1), srv_val), 0)
        push_code = jnp.where(is_put, u32(0), u32(1) + srv_val)
        w, ovf = self._lanes.push(w, reply_lane, push_code, enabled=nonempty)
        return w, nonempty, nonempty & ovf

    def _body_to_client(self, words, k, prm):
        """Head of the server's lane -> client k: PutOk advances the script
        (record WriteOk, invoke Read, send Get); GetOk completes it. A reply
        the client is not awaiting is a no-op and BLOCKS the lane — the
        packed form of head-of-channel-only delivery."""
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        lane, req_lane = prm[0], prm[1]
        code, nonempty = self._lanes.head(words, lane)
        is_putok = code == 0
        await_k = L.get(words, "cl_await", k)
        eligible = nonempty & jnp.where(is_putok, await_k == u32(1), await_k == u32(2))
        w = self._lanes.pop(words, lane, enabled=eligible)
        w = L.set(
            w,
            "cl_await",
            jnp.where(eligible, jnp.where(is_putok, u32(2), u32(0)), await_k),
            k,
        )
        ops_k = L.get(words, "cl_ops", k)
        w = L.set(
            w,
            "cl_ops",
            jnp.where(eligible, jnp.where(is_putok, u32(2), u32(3)), ops_k),
            k,
        )
        o = jnp.bool_(False)
        for t in range(self.C):
            on_p = eligible & is_putok & (k == u32(t))
            w, o1 = self._hist.on_return(w, t, u32(0), enabled=on_p)  # WriteOk
            w = self._hist.on_invoke(w, t, u32(0), enabled=on_p)  # Read
            # GetOk(values[v]) lane code 1+v IS the ReadOk ret code.
            on_g = eligible & ~is_putok & (k == u32(t))
            w, o2 = self._hist.on_return(w, t, code, enabled=on_g)
            o = o | o1 | o2
        w, povf = self._lanes.push(w, req_lane, 1, enabled=eligible & is_putok)
        return w, eligible, eligible & (o | povf)

    def packed_properties(self, words):
        """[linearizable, value chosen]; "chosen" checks lane
        HEADS only — under ordered semantics only heads are deliverable
        (value_chosen_condition over iter_deliverable, network.rs:275-277)."""
        import jax.numpy as jnp

        lin = self.device_linearizable_register(words)
        chosen = jnp.bool_(False)
        for k in range(self.C):
            code, nonempty = self._lanes.head(words, self.C + k)
            chosen = chosen | (nonempty & (code >= jnp.uint32(2)))
        return jnp.stack([lin, chosen])


def main(argv=None) -> None:
    """CLI mirroring single-copy-register.rs:139-233:
    ``check``/``explore``/``spawn`` subcommands."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    orig_args = list(args)
    cmd = args.pop(0) if args else None
    if cmd in ("check", "check-xla"):
        # ``check`` runs the device (XLA) engine — the reference's check
        # likewise runs its fastest checker. Network semantics the packed
        # codec does not cover fall back to the host oracle.
        client_count = int(args.pop(0)) if args and args[0].isdigit() else 2
        netname = args.pop(0) if args else None
        if netname in (None, "ordered"):
            from ..backend import guarded_main

            guarded_main("stateright_tpu.models.single_copy_register", orig_args)
            print(
                f"Model checking a single-copy register with {client_count} "
                "clients on XLA."
            )
            model = (
                PackedSingleCopyRegisterOrdered(client_count, 1)
                if netname == "ordered"
                else PackedSingleCopyRegister(client_count, 1)
            )
            (
                model.checker()
                .spawn_xla(frontier_capacity=1 << 11, table_capacity=1 << 14)
                .report(WriteReporter())
            )
        else:
            network = Network.from_name(netname)
            print(
                f"Model checking a single-copy register with {client_count} "
                "clients."
            )
            (
                single_copy_register_model(client_count, 1, network)
                .checker()
                .spawn_dfs()
                .report(WriteReporter())
            )
    elif cmd == "check-host":
        client_count = int(args.pop(0)) if args else 2
        network = Network.from_name(args.pop(0)) if args else None
        print(f"Model checking a single-copy register with {client_count} clients.")
        (
            single_copy_register_model(client_count, 1, network)
            .checker()
            .spawn_dfs()
            .report(WriteReporter())
        )
    elif cmd == "explore":
        client_count = int(args.pop(0)) if args else 2
        address = args.pop(0) if args else "localhost:3000"
        network = Network.from_name(args.pop(0)) if args else None
        print(
            f"Exploring state space for single-copy register with "
            f"{client_count} clients on {address}."
        )
        single_copy_register_model(client_count, 1, network).checker().serve(address)
    elif cmd == "spawn":
        from ..actor.spawn import json_codec, spawn

        port = 3000
        serialize, deserialize = json_codec(reg.Put, reg.Get, reg.PutOk, reg.GetOk)
        print("  A server that implements a single-copy register.")
        print("  You can interact using netcat:")
        print(f"$ nc -u localhost {port}")
        print(serialize(reg.Put(1, "X")).decode())
        print(serialize(reg.Get(2)).decode())
        spawn(
            serialize,
            deserialize,
            [(Id.from_addr("127.0.0.1", port), SingleCopyActor())],
        )
    else:
        print("USAGE:")
        print("  single-copy-register check [CLIENT_COUNT] [NETWORK]  (device/XLA engine)")
        print("  single-copy-register check-host [CLIENT_COUNT] [NETWORK]  (sequential host oracle)")
        print("  single-copy-register check-xla [NETWORK]  (alias of check)")
        print("  single-copy-register explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  single-copy-register spawn")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
