"""Single-copy register: each server exposes a rewritable register with no
consensus between servers.

Mirrors ``/root/reference/examples/single-copy-register.rs``: the system is
linearizable iff there is exactly one server (one copy); with two or more
servers clients can observe stale values and the ``linearizable`` property
yields a counterexample.

Exact-count oracles from the reference's own test
(single-copy-register.rs:110,136): 93 unique states at 2 clients / 1 server
(full coverage), 20 unique states at 2 clients / 2 servers (BFS stops at the
linearizability counterexample).

The reference's ``Value::default()`` (``'\\u{0}'``) is rendered as ``None``:
the "unwritten" register value, consistent with the ``Register(None)`` spec
initial state used throughout this package.
"""

from __future__ import annotations

from typing import Optional

from ..actor import Actor, ActorModel, Id, Network, Out, StateRef
from ..actor import register as reg
from ..core import Expectation
from ..semantics import LinearizabilityTester
from ..semantics.register import Register


class SingleCopyActor(Actor):
    """A server holding one unreplicated register value
    (single-copy-register.rs:18-46). The actor state *is* the value."""

    def on_start(self, id: Id, out: Out):
        return None  # the unwritten value (Value::default())

    def on_msg(self, id: Id, state: StateRef, src: Id, msg, out: Out) -> None:
        if isinstance(msg, reg.Put):
            state.set(msg.value)
            out.send(src, reg.PutOk(msg.request_id))
        elif isinstance(msg, reg.Get):
            out.send(src, reg.GetOk(msg.request_id, state.get()))
        # Internal messages don't exist for this protocol; anything else is
        # ignored (a no-op action, suppressed by the model).


def single_copy_register_model(
    client_count: int = 2,
    server_count: int = 1,
    network: Optional[Network] = None,
) -> ActorModel:
    """Build the checkable model (single-copy-register.rs:55-86)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    model = ActorModel(cfg=None, init_history=LinearizabilityTester(Register(None)))
    for _ in range(server_count):
        model.actor(SingleCopyActor())
    for _ in range(client_count):
        model.actor(reg.RegisterClient(put_count=1, server_count=server_count))
    return (
        model.init_network(network)
        .property(Expectation.ALWAYS, "linearizable", reg.linearizable_condition())
        .property(Expectation.SOMETIMES, "value chosen", reg.value_chosen_condition)
        .record_msg_in(reg.record_returns)
        .record_msg_out(reg.record_invocations)
    )


def main(argv=None) -> None:
    """CLI mirroring single-copy-register.rs:139-233:
    ``check``/``explore``/``spawn`` subcommands."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args.pop(0) if args else None
    if cmd == "check":
        client_count = int(args.pop(0)) if args else 2
        network = Network.from_name(args.pop(0)) if args else None
        print(f"Model checking a single-copy register with {client_count} clients.")
        (
            single_copy_register_model(client_count, 1, network)
            .checker()
            .spawn_dfs()
            .report(WriteReporter())
        )
    elif cmd == "explore":
        client_count = int(args.pop(0)) if args else 2
        address = args.pop(0) if args else "localhost:3000"
        network = Network.from_name(args.pop(0)) if args else None
        print(
            f"Exploring state space for single-copy register with "
            f"{client_count} clients on {address}."
        )
        single_copy_register_model(client_count, 1, network).checker().serve(address)
    elif cmd == "spawn":
        from ..actor.spawn import json_codec, spawn

        port = 3000
        serialize, deserialize = json_codec(reg.Put, reg.Get, reg.PutOk, reg.GetOk)
        print("  A server that implements a single-copy register.")
        print("  You can interact using netcat:")
        print(f"$ nc -u localhost {port}")
        print(serialize(reg.Put(1, "X")).decode())
        print(serialize(reg.Get(2)).decode())
        spawn(
            serialize,
            deserialize,
            [(Id.from_addr("127.0.0.1", port), SingleCopyActor())],
        )
    else:
        print("USAGE:")
        print("  single-copy-register check [CLIENT_COUNT] [NETWORK]")
        print("  single-copy-register explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  single-copy-register spawn")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
