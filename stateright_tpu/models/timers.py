"""Timer-semantics demo: pingers driven entirely by model timers.

Mirrors ``/root/reference/examples/timers.rs``: each actor sets three timers
on start (``Even``, ``Odd``, ``NoOp``). In the model a timeout is a
nondeterministic action (the duration range is irrelevant,
actor/model.rs:59-64); firing ``Even``/``Odd`` re-arms the timer and pings
the even/odd peers, while ``NoOp`` only re-arms itself — which the no-op
detection (``is_no_op_with_timer``, actor.rs:254-264) suppresses, so ``NoOp``
timeouts never generate states.

The state space is unbounded (counters grow), so ``check`` bounds the run
with ``target_state_count`` — use the Explorer to poke at it interactively.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional

from ..actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
    StateRef,
    model_peers,
    model_timeout,
)
from ..core import Expectation
from ..utils.variant import variant

Ping = variant("Ping", [])
Pong = variant("Pong", [])

Even = variant("Even", [])
Odd = variant("Odd", [])
NoOp = variant("NoOp", [])


class PingerState(NamedTuple):
    sent: int
    received: int


class PingerActor(Actor):
    """timers.rs:32-96."""

    def __init__(self, peer_ids):
        self.peer_ids = list(peer_ids)

    def on_start(self, id: Id, out: Out) -> PingerState:
        out.set_timer(Even(), model_timeout())
        out.set_timer(Odd(), model_timeout())
        out.set_timer(NoOp(), model_timeout())
        return PingerState(sent=0, received=0)

    def on_msg(self, id: Id, state: StateRef, src: Id, msg: Any, out: Out) -> None:
        if isinstance(msg, Ping):
            out.send(src, Pong())
        elif isinstance(msg, Pong):
            s = state.get()
            state.set(s._replace(received=s.received + 1))

    def on_timeout(self, id: Id, state: StateRef, timer: Any, out: Out) -> None:
        if isinstance(timer, NoOp):
            out.set_timer(NoOp(), model_timeout())  # pure re-arm: a no-op
            return
        parity = 0 if isinstance(timer, Even) else 1
        out.set_timer(timer, model_timeout())
        for dst in self.peer_ids:
            if int(dst) % 2 == parity:
                s = state.get()
                state.set(s._replace(sent=s.sent + 1))
                out.send(dst, Ping())


def timers_model(
    server_count: int = 3, network: Optional[Network] = None
) -> ActorModel:
    """Build the checkable model (timers.rs:104-113)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()
    model = ActorModel(cfg=None)
    for i in range(server_count):
        model.actor(PingerActor(model_peers(i, server_count)))
    return model.init_network(network).property(
        Expectation.ALWAYS, "true", lambda _m, _s: True
    )


def main(argv=None) -> None:
    """CLI mirroring timers.rs:115-164 (``check`` bounded, see module doc)."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args.pop(0) if args else None
    if cmd == "check":
        network = Network.from_name(args.pop(0)) if args else None
        print("Model checking Pingers (bounded to 100k states).")
        (
            timers_model(3, network)
            .checker()
            .target_state_count(100_000)
            .spawn_dfs()
            .report(WriteReporter())
        )
    elif cmd == "explore":
        address = args.pop(0) if args else "localhost:3000"
        network = Network.from_name(args.pop(0)) if args else None
        print(f"Exploring state space for Pingers on {address}.")
        timers_model(3, network).checker().serve(address)
    else:
        print("USAGE:")
        print("  timers check [NETWORK]")
        print("  timers explore [ADDRESS] [NETWORK]")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
