"""Timer-semantics demo: pingers driven entirely by model timers.

Mirrors ``/root/reference/examples/timers.rs``: each actor sets three timers
on start (``Even``, ``Odd``, ``NoOp``). In the model a timeout is a
nondeterministic action (the duration range is irrelevant,
actor/model.rs:59-64); firing ``Even``/``Odd`` re-arms the timer and pings
the even/odd peers, while ``NoOp`` only re-arms itself — which the no-op
detection (``is_no_op_with_timer``, actor.rs:254-264) suppresses, so ``NoOp``
timeouts never generate states.

The state space is unbounded (counters grow), so ``check`` bounds the run
with ``target_state_count`` — use the Explorer to poke at it interactively.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional

from ..actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
    StateRef,
    model_peers,
    model_timeout,
)
from ..actor.network import Envelope
from ..actor.timers import Timers
from ..core import Expectation
from ..packing import PackedModelAdapter
from ..utils.variant import variant

Ping = variant("Ping", [])
Pong = variant("Pong", [])

Even = variant("Even", [])
Odd = variant("Odd", [])
NoOp = variant("NoOp", [])


class PingerState(NamedTuple):
    sent: int
    received: int


class PingerActor(Actor):
    """timers.rs:32-96."""

    def __init__(self, peer_ids):
        self.peer_ids = list(peer_ids)

    def on_start(self, id: Id, out: Out) -> PingerState:
        out.set_timer(Even(), model_timeout())
        out.set_timer(Odd(), model_timeout())
        out.set_timer(NoOp(), model_timeout())
        return PingerState(sent=0, received=0)

    def on_msg(self, id: Id, state: StateRef, src: Id, msg: Any, out: Out) -> None:
        if isinstance(msg, Ping):
            out.send(src, Pong())
        elif isinstance(msg, Pong):
            s = state.get()
            state.set(s._replace(received=s.received + 1))

    def on_timeout(self, id: Id, state: StateRef, timer: Any, out: Out) -> None:
        if isinstance(timer, NoOp):
            out.set_timer(NoOp(), model_timeout())  # pure re-arm: a no-op
            return
        parity = 0 if isinstance(timer, Even) else 1
        out.set_timer(timer, model_timeout())
        for dst in self.peer_ids:
            if int(dst) % 2 == parity:
                s = state.get()
                state.set(s._replace(sent=s.sent + 1))
                out.send(dst, Ping())


def timers_model(
    server_count: int = 3, network: Optional[Network] = None
) -> ActorModel:
    """Build the checkable model (timers.rs:104-113)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()
    model = ActorModel(cfg=None)
    for i in range(server_count):
        model.actor(PingerActor(model_peers(i, server_count)))
    return model.init_network(network).property(
        Expectation.ALWAYS, "true", lambda _m, _s: True
    )


class PackedTimers(PackedModelAdapter):
    """The Pingers system on the device engine (``spawn_xla``) — timers on
    device, completing device-engine coverage of every reference example.

    Pending timers need no storage: every actor's set is constantly
    ``{Even, Odd, NoOp}`` (all three are re-armed on every firing and never
    cancelled, timers.rs:50-74). The ``NoOp`` timeout gets no action slot —
    its pure re-arm is suppressed by no-op detection in the object model
    (``is_no_op_with_timer``, actor.rs:254-264) and is statically never
    enabled here. ``Even``/``Odd`` timeout slots are statically valid
    whenever the actor has a peer of that parity, and bump ``sent`` by the
    (static) peer count while incrementing each Ping's multiset count.

    The space is unbounded (counters grow), so device runs use
    ``target_state_count``/``target_max_depth`` exactly like the object
    CLI; counters and envelope counts that outgrow their declared widths
    surface as the loud codec-overflow failure.
    """

    def __init__(self, server_count: int = 3, *, count_bits: int = 8,
                 net_bits: int = 5):
        from ..packing import LayoutBuilder

        n = server_count
        self.n = n
        self._inner = timers_model(n)
        # Closed envelope universe: Ping(i->j) then Pong(i->j), i != j.
        pairs = [(i, j) for i in range(n) for j in range(n) if i != j]
        self._pairs = pairs
        U = 2 * len(pairs)
        self._U = U
        self._ping_code = {p: c for c, p in enumerate(pairs)}
        self._pong_code = {p: len(pairs) + c for c, p in enumerate(pairs)}
        self._count_bits, self._net_bits = count_bits, net_bits
        self._layout = (
            LayoutBuilder()
            .array("sent", n, count_bits)
            .array("recv", n, count_bits)
            .array("net", U, net_bits)
            .finish()
        )
        self.state_words = self._layout.words
        # Slots: [Even timeout x n, Odd timeout x n, one delivery per code].
        self.max_actions = 2 * n + U
        # Static per-actor parity targets.
        self._targets = {
            (i, parity): [j for j in range(n) if j != i and j % 2 == parity]
            for i in range(n)
            for parity in (0, 1)
        }

    # object-level Model API: inherited from PackedModelAdapter, which
    # resolves it against ``self._inner``.

    # --- codec --------------------------------------------------------------

    def pack(self, state):
        from ..packing import OverflowError32

        sent = [s.sent for s in state.actor_states]
        recv = [s.received for s in state.actor_states]
        net = [0] * self._U
        for env, count in state.network.counts.items():
            pair = (int(env.src), int(env.dst))
            code = (
                self._ping_code.get(pair)
                if isinstance(env.msg, Ping)
                else self._pong_code.get(pair)
            )
            if code is None:
                raise OverflowError32(f"envelope outside universe: {env!r}")
            net[code] = count
        for v in sent + recv:
            if v >= 1 << self._count_bits:
                raise OverflowError32(f"counter {v} exceeds {self._count_bits} bits")
        for c in net:
            if c >= 1 << self._net_bits:
                raise OverflowError32(f"envelope count {c} exceeds {self._net_bits} bits")
        return self._layout.pack(sent=sent, recv=recv, net=net)

    def unpack(self, words):
        from ..actor.model_state import ActorModelState
        from ..actor.network import Network

        from ..actor.network import UnorderedNonDuplicatingNetwork

        f = self._layout.unpack(words)
        counts = {}
        for (i, j), c in self._ping_code.items():
            if f["net"][c]:
                counts[Envelope(Id(i), Id(j), Ping())] = int(f["net"][c])
        for (i, j), c in self._pong_code.items():
            if f["net"][c]:
                counts[Envelope(Id(i), Id(j), Pong())] = int(f["net"][c])
        timers = Timers(frozenset((Even(), Odd(), NoOp())))
        return ActorModelState(
            actor_states=tuple(
                PingerState(int(f["sent"][k]), int(f["recv"][k]))
                for k in range(self.n)
            ),
            network=UnorderedNonDuplicatingNetwork(counts),
            timers_set=tuple(timers for _ in range(self.n)),
            history=(),
        )

    # --- device kernels ------------------------------------------------------

    def packed_step(self, words):
        import jax.numpy as jnp

        L = self._layout
        n = self.n
        one = jnp.uint32(1)
        cmax = jnp.uint32((1 << self._count_bits) - 1)
        nmax = jnp.uint32((1 << self._net_bits) - 1)
        nxt, valid, ovf = [], [], []

        for i in range(n):
            for parity in (0, 1):
                targets = self._targets[(i, parity)]
                if not targets:
                    # No matching peer: the timeout is a pure re-arm, a
                    # suppressed no-op — statically invalid.
                    nxt.append(words)
                    valid.append(jnp.bool_(False))
                    ovf.append(jnp.bool_(False))
                    continue
                sent = L.get(words, "sent", i)
                w = L.set(words, "sent", sent + jnp.uint32(len(targets)), i)
                o = sent + jnp.uint32(len(targets)) > cmax
                for j in targets:
                    c = L.get(w, "net", self._ping_code[(i, j)])
                    o = o | (c == nmax)
                    w = L.set(w, "net", c + one, self._ping_code[(i, j)])
                nxt.append(w)
                valid.append(jnp.bool_(True))
                ovf.append(o)

        for (i, j), code in self._ping_code.items():
            # Deliver Ping(i->j): j replies Pong(j->i).
            c = L.get(words, "net", code)
            pong = self._pong_code[(j, i)]
            cp = L.get(words, "net", pong)
            w = L.set(words, "net", c - one, code)
            w = L.set(w, "net", cp + one, pong)
            nxt.append(w)
            valid.append(c > 0)
            ovf.append((c > 0) & (cp == nmax))
        for (i, j), code in self._pong_code.items():
            # Deliver Pong(i->j): j counts a received pong.
            c = L.get(words, "net", code)
            r = L.get(words, "recv", j)
            w = L.set(words, "net", c - one, code)
            w = L.set(w, "recv", r + one, j)
            nxt.append(w)
            valid.append(c > 0)
            ovf.append((c > 0) & (r == cmax))

        return jnp.stack(nxt), jnp.stack(valid), jnp.stack(ovf)

    def packed_properties(self, words):
        import jax.numpy as jnp

        return jnp.stack([jnp.bool_(True)])  # the object model's "true"


def main(argv=None) -> None:
    """CLI mirroring timers.rs:115-164 (``check`` bounded, see module doc)."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    orig_args = list(args)
    cmd = args.pop(0) if args else None
    if cmd in ("check", "check-xla"):
        # ``check`` runs the device (XLA) engine; custom network semantics
        # fall back to the host oracle (the packed codec models the
        # default network).
        netname = args.pop(0) if args else None
        if netname is None:
            from ..backend import guarded_main

            guarded_main("stateright_tpu.models.timers", orig_args)
            print("Model checking Pingers on XLA (bounded to 100k states).")
            (
                PackedTimers(3)
                .checker()
                .target_state_count(100_000)
                .spawn_xla(frontier_capacity=1 << 15, table_capacity=1 << 18)
                .report(WriteReporter())
            )
        else:
            network = Network.from_name(netname)
            print("Model checking Pingers (bounded to 100k states).")
            (
                timers_model(3, network)
                .checker()
                .target_state_count(100_000)
                .spawn_dfs()
                .report(WriteReporter())
            )
    elif cmd == "check-host":
        network = Network.from_name(args.pop(0)) if args else None
        print("Model checking Pingers (bounded to 100k states).")
        (
            timers_model(3, network)
            .checker()
            .target_state_count(100_000)
            .spawn_dfs()
            .report(WriteReporter())
        )
    elif cmd == "explore":
        address = args.pop(0) if args else "localhost:3000"
        network = Network.from_name(args.pop(0)) if args else None
        print(f"Exploring state space for Pingers on {address}.")
        timers_model(3, network).checker().serve(address)
    else:
        print("USAGE:")
        print("  timers check [NETWORK]       (device/XLA engine)")
        print("  timers check-host [NETWORK]  (sequential host oracle)")
        print("  timers check-xla             (alias of check)")
        print("  timers explore [ADDRESS] [NETWORK]")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
