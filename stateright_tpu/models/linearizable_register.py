"""ABD linearizable register: quorum-replicated shared memory.

Mirrors ``/root/reference/examples/linearizable-register.rs``: the Attiya,
Bar-Noy, Dolev algorithm ("Sharing Memory Robustly in Message-Passing
Systems", doi:10.1145/200836.200869). Every operation runs two phases:

1. **Query**: poll a quorum for (logical-clock sequencer, value) pairs;
2. **Record**: write back the maximal pair (for a write: the incremented
   sequencer and the new value) and wait for a quorum of acks.

Because both reads and writes perform the write-back phase, the register is
linearizable with any majority quorum.

Exact-count oracle from the reference's own test
(linearizable-register.rs:289,316): 544 unique states at 2 clients /
2 servers on an unordered non-duplicating network, both BFS and DFS.
"""

from __future__ import annotations

from typing import Any, FrozenSet, NamedTuple, Optional, Tuple

from ..actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
    StateRef,
    majority,
    model_peers,
)
from ..actor import register as reg
from ..core import Expectation
from ..semantics import LinearizabilityTester
from ..semantics.register import Register
from ..utils.variant import variant

Seq = Tuple[int, Id]  # (logical clock, writer id) — totally ordered

# Internal ABD protocol messages (linearizable-register.rs:28-33).
Query = variant("Query", ["request_id"])
AckQuery = variant("AckQuery", ["request_id", "seq", "value"])
Record = variant("Record", ["request_id", "seq", "value"])
AckRecord = variant("AckRecord", ["request_id"])

# The two client-request phases (linearizable-register.rs:44-57).
# ``responses`` is a map Id -> (Seq, Value) stored as a frozenset of pairs;
# ``acks`` is a frozenset of replica ids.  ``write`` (phase 1) and ``read``
# (phase 2) are ``None`` for the other operation kind and a 1-tuple
# ``(value,)`` otherwise — the tuple keeps a value of ``None`` (a read of
# the unwritten default, or a Put of None) distinct from "not this kind of
# operation" (Rust's Option<Value> makes the same distinction, rs:48,54).
Phase1 = variant("Phase1", ["request_id", "requester_id", "write", "responses"])
Phase2 = variant("Phase2", ["request_id", "requester_id", "read", "acks"])


class AbdState(NamedTuple):
    """Replica state (linearizable-register.rs:37-41)."""

    seq: Seq
    val: Any
    phase: Optional[Any]


def _map_insert(m: FrozenSet, k: Any, v: Any) -> FrozenSet:
    d = dict(m)
    d[k] = v
    return frozenset(d.items())


class AbdActor(Actor):
    """One ABD replica; also coordinates client requests
    (linearizable-register.rs:64-214)."""

    def __init__(self, peers):
        self.peers = list(peers)

    def on_start(self, id: Id, out: Out) -> AbdState:
        return AbdState(seq=(0, id), val=None, phase=None)

    def on_msg(self, id: Id, state: StateRef, src: Id, msg: Any, out: Out) -> None:
        s: AbdState = state.get()

        if isinstance(msg, (reg.Put, reg.Get)) and s.phase is None:
            # Begin phase 1: poll a quorum, seeding with our own pair
            # (linearizable-register.rs:86-111). ``write`` is a 1-tuple so a
            # Put of ``None`` stays distinct from a Get (same trick as
            # ``read`` below).
            write = (msg.value,) if isinstance(msg, reg.Put) else None
            out.broadcast(self.peers, reg.Internal(Query(msg.request_id)))
            state.set(
                s._replace(
                    phase=Phase1(
                        request_id=msg.request_id,
                        requester_id=src,
                        write=write,
                        responses=_map_insert(frozenset(), id, (s.seq, s.val)),
                    )
                )
            )
            return

        if not isinstance(msg, reg.Internal):
            return
        m = msg.msg

        if isinstance(m, Query):
            out.send(src, reg.Internal(AckQuery(m.request_id, s.seq, s.val)))

        elif (
            isinstance(m, AckQuery)
            and isinstance(s.phase, Phase1)
            and s.phase.request_id == m.request_id
        ):
            # Collect quorum responses; on quorum, pick the maximal
            # (seq, value), bump the clock for writes, and move to phase 2
            # with Record/AckRecord self-sends applied inline
            # (linearizable-register.rs:118-176).
            p = s.phase
            responses = _map_insert(p.responses, src, (m.seq, m.value))
            if len(responses) < majority(len(self.peers) + 1):
                state.set(s._replace(phase=p._replace(responses=responses)))
                return
            # Sequencers are distinct ((clock, id) pairs), so max is
            # deterministic (comment at linearizable-register.rs:139-142).
            seq, val = max((v for _k, v in responses), key=lambda sv: sv[0])
            read = None
            if p.write is not None:
                seq = (seq[0] + 1, id)
                val = p.write[0]
            else:
                read = (val,)
            out.broadcast(self.peers, reg.Internal(Record(p.request_id, seq, val)))
            s2 = s
            if seq > s.seq:  # self-send Record
                s2 = s2._replace(seq=seq, val=val)
            state.set(
                s2._replace(
                    phase=Phase2(
                        request_id=p.request_id,
                        requester_id=p.requester_id,
                        read=read,
                        acks=frozenset((id,)),  # self-send AckRecord
                    )
                )
            )

        elif isinstance(m, Record):
            # Adopt newer pairs; always ack (linearizable-register.rs:177-184).
            out.send(src, reg.Internal(AckRecord(m.request_id)))
            if m.seq > s.seq:
                state.set(s._replace(seq=m.seq, val=m.value))

        elif (
            isinstance(m, AckRecord)
            and isinstance(s.phase, Phase2)
            and s.phase.request_id == m.request_id
            and src not in s.phase.acks
        ):
            # On an ack quorum, answer the client and clear the phase
            # (linearizable-register.rs:185-210).
            p = s.phase
            acks = p.acks | {src}
            if len(acks) == majority(len(self.peers) + 1):
                if p.read is not None:
                    out.send(p.requester_id, reg.GetOk(p.request_id, p.read[0]))
                else:
                    out.send(p.requester_id, reg.PutOk(p.request_id))
                state.set(s._replace(phase=None))
            else:
                state.set(s._replace(phase=p._replace(acks=acks)))


def linearizable_register_model(
    client_count: int = 2,
    server_count: int = 2,
    network: Optional[Network] = None,
) -> ActorModel:
    """Build the checkable model (linearizable-register.rs:223-257)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    model = ActorModel(cfg=None, init_history=LinearizabilityTester(Register(None)))
    for i in range(server_count):
        model.actor(AbdActor(model_peers(i, server_count)))
    for _ in range(client_count):
        model.actor(reg.RegisterClient(put_count=1, server_count=server_count))
    return (
        model.init_network(network)
        .property(Expectation.ALWAYS, "linearizable", reg.linearizable_condition())
        .property(Expectation.SOMETIMES, "value chosen", reg.value_chosen_condition)
        .record_msg_in(reg.record_returns)
        .record_msg_out(reg.record_invocations)
    )


def main(argv=None) -> None:
    """CLI mirroring linearizable-register.rs:319-430."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args.pop(0) if args else None
    if cmd == "check":
        client_count = int(args.pop(0)) if args else 2
        network = Network.from_name(args.pop(0)) if args else None
        print(f"Model checking a linearizable register with {client_count} clients.")
        (
            linearizable_register_model(client_count, 3, network)
            .checker()
            .spawn_dfs()
            .report(WriteReporter())
        )
    elif cmd == "explore":
        client_count = int(args.pop(0)) if args else 2
        address = args.pop(0) if args else "localhost:3000"
        network = Network.from_name(args.pop(0)) if args else None
        print(
            f"Exploring state space for linearizable register with "
            f"{client_count} clients on {address}."
        )
        linearizable_register_model(client_count, 3, network).checker().serve(address)
    elif cmd == "spawn":
        from ..actor.spawn import json_codec, spawn

        port = 3000
        ids = [Id.from_addr("127.0.0.1", port + i) for i in range(3)]
        serialize, deserialize = json_codec(
            reg.Put, reg.Get, reg.PutOk, reg.GetOk, reg.Internal,
            Query, AckQuery, Record, AckRecord,
        )
        print("  Three servers that implement a linearizable register.")
        print("  You can interact using netcat:")
        print(f"$ nc -u localhost {port}")
        print(serialize(reg.Put(1, "X")).decode())
        print(serialize(reg.Get(2)).decode())
        spawn(
            serialize,
            deserialize,
            [
                (ids[i], AbdActor([x for x in ids if x != ids[i]]))
                for i in range(3)
            ],
        )
    else:
        print("USAGE:")
        print("  linearizable-register check [CLIENT_COUNT] [NETWORK]")
        print("  linearizable-register explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  linearizable-register spawn")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
